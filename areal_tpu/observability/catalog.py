"""The authoritative catalog of every areal_tpu metric family.

Each instrumented layer obtains its handles through one factory here, so
this module is the single place a metric name/label-set/help text exists.
``tools/validate_installation.py`` lints the catalog (names match
``^areal_[a-z0-9_]+$``, help text present) and ``docs/observability.md``
documents it; keep the three in sync.

Factories are idempotent (the registry dedups by name), so calling them
from multiple instances is safe and cheap.
"""

from __future__ import annotations

from types import SimpleNamespace

from areal_tpu.observability.metrics import Registry, get_registry

# short-latency buckets for TTFT / dispatch (sub-ms to 10s)
FAST_BUCKETS = (
    0.0005,
    0.001,
    0.0025,
    0.005,
    0.01,
    0.025,
    0.05,
    0.1,
    0.25,
    0.5,
    1.0,
    2.5,
    5.0,
    10.0,
)
# version-lag buckets (integer staleness steps)
LAG_BUCKETS = (0, 1, 2, 3, 4, 6, 8, 12, 16, 32)


def staleness_metrics(reg: Registry | None = None) -> SimpleNamespace:
    """StalenessManager: admission-control visibility."""
    r = reg or get_registry()
    return SimpleNamespace(
        capacity=r.gauge(
            "areal_rollout_capacity",
            "Remaining rollout admission capacity (staleness-bounded).",
        ),
        running=r.gauge(
            "areal_rollout_running", "Rollouts currently in flight."
        ),
        submitted=r.counter(
            "areal_rollout_submitted_total", "Rollout tasks admitted."
        ),
        accepted=r.counter(
            "areal_rollout_accepted_total",
            "Rollout trajectories accepted into the training buffer.",
        ),
        rejected=r.counter(
            "areal_rollout_rejected_total",
            "Rollout trajectories rejected (filter or empty result).",
        ),
        version_lag=r.histogram(
            "areal_rollout_version_lag",
            "Policy-version lag (current - head version) of accepted "
            "trajectories.",
            buckets=LAG_BUCKETS,
        ),
        version_span=r.histogram(
            "areal_rollout_version_span",
            "Per-trajectory policy-version spread (max - min per-token "
            "version): >0 means the sequence spanned a weight commit.",
            buckets=LAG_BUCKETS,
        ),
        mixed_version=r.counter(
            "areal_rollout_mixed_version_total",
            "Accepted trajectories whose tokens span more than one policy "
            "version (generated across a zero-pause weight commit).",
        ),
    )


def executor_metrics(reg: Registry | None = None) -> SimpleNamespace:
    """WorkflowExecutor: queue depths + dispatch latency."""
    r = reg or get_registry()
    return SimpleNamespace(
        input_depth=r.gauge(
            "areal_executor_input_queue_depth",
            "Queued train rollout tasks awaiting staleness capacity.",
        ),
        eval_depth=r.gauge(
            "areal_executor_eval_queue_depth",
            "Queued eval rollout tasks awaiting dispatch.",
        ),
        inflight=r.gauge(
            "areal_executor_inflight_tasks",
            "Rollout tasks launched and not yet completed.",
        ),
        results_buffered=r.gauge(
            "areal_executor_results_buffered",
            "Accepted trajectories buffered awaiting wait()/prepare_batch.",
        ),
        # default (latency-wide) buckets: gate waits under exhausted
        # staleness capacity routinely run tens of seconds to minutes
        dispatch_latency=r.histogram(
            "areal_executor_dispatch_latency_seconds",
            "Time from submit() to task launch (staleness-gate wait).",
        ),
    )


def engine_metrics(reg: Registry | None = None) -> SimpleNamespace:
    """DecodeEngine: decode-loop throughput counters."""
    r = reg or get_registry()
    return SimpleNamespace(
        generated_tokens=r.counter(
            "areal_decode_generated_tokens_total",
            "Tokens emitted by the decode loop.",
        ),
        completed=r.counter(
            "areal_decode_completed_total",
            "Generation requests finished (stop/length).",
        ),
        aborted=r.counter(
            "areal_decode_aborted_total",
            "Generation requests aborted (weight-update pause/preemption).",
        ),
        prefills=r.counter(
            "areal_decode_prefills_total", "Sequences prefilled."
        ),
        prefill_tokens=r.counter(
            "areal_decode_prefill_tokens_total",
            "Prompt tokens actually prefilled (radix-cached prefix tokens "
            "excluded — the denominator's complement for prefix hit rate).",
        ),
        chunks=r.counter(
            "areal_decode_chunks_total", "Jitted decode chunks executed."
        ),
        batch_occupancy=r.gauge(
            "areal_decode_batch_occupancy",
            "Active decode slots (of ServerConfig.max_batch_size).",
        ),
    )


def prefix_cache_metrics(reg: Registry | None = None) -> SimpleNamespace:
    """Cross-request radix prefix cache over the paged KV pool
    (inference/paged_kv.py RadixPrefixCache): prompt-KV reuse visibility.
    Hit rate = hit_tokens / (hit_tokens + areal_decode_prefill_tokens_total)."""
    r = reg or get_registry()
    return SimpleNamespace(
        lookups=r.counter(
            "areal_prefix_cache_lookups_total",
            "Radix-cache prefix lookups at admission.",
        ),
        hit_tokens=r.counter(
            "areal_prefix_cache_hit_tokens_total",
            "Prompt tokens served from radix-cached KV pages instead of "
            "prefill (page refcount bumps, zero FLOPs).",
        ),
        inserted_pages=r.counter(
            "areal_prefix_cache_inserted_pages_total",
            "KV pages published into the radix tree at request "
            "completion/park time.",
        ),
        evicted_pages=r.counter(
            "areal_prefix_cache_evicted_pages_total",
            "Radix-cached pages released (LRU-leaf eviction under pool "
            "pressure, capacity eviction, or flush at a weight commit).",
        ),
        pages_held=r.gauge(
            "areal_prefix_cache_pages_held",
            "KV pages currently owned by the radix tree.",
        ),
    )


def lifecycle_metrics(reg: Registry | None = None) -> SimpleNamespace:
    """Request lifecycle manager (docs/request_lifecycle.md): deadlines,
    cancellation, admission control, and load shedding across the stack."""
    r = reg or get_registry()
    return SimpleNamespace(
        admission_rejected=r.counter(
            "areal_admission_rejected_total",
            "Generation requests rejected at admission with 429 + "
            "Retry-After, by reason (queue_depth | page_headroom).",
            label_names=("reason",),
        ),
        deadline_exceeded=r.counter(
            "areal_request_deadline_exceeded_total",
            "Requests reaped at their deadline (queued or mid-decode); "
            "partial output returned with truncated_by=deadline.",
        ),
        aborts=r.counter(
            "areal_abort_total",
            "In-flight requests cancelled via /abort_request (client "
            "disconnects, workflow task failures) — slots and KV pages "
            "reclaimed instead of decoding for a caller that is gone.",
        ),
        queue_depth=r.gauge(
            "areal_request_queue_depth",
            "Lifecycle view of engine admission pressure: submission queue "
            "+ backlog depth the admission-control gate compares against "
            "lifecycle.max_queue_depth.",
        ),
        watchdog_fired=r.counter(
            "areal_slot_watchdog_fired_total",
            "Active slots aborted by the per-slot progress watchdog (no "
            "token emitted within lifecycle.watchdog_s).",
        ),
        gateway_shed=r.counter(
            "areal_gateway_shed_total",
            "Requests load-shed at the gateway with 429 + Retry-After, by "
            "priority class (rollout sheds before interactive).",
            label_names=("priority",),
        ),
        gateway_latency=r.histogram(
            "areal_gateway_admitted_latency_seconds",
            "End-to-end latency of requests ADMITTED through the gateway, "
            "by priority class (interactive | rollout).",
            label_names=("priority",),
        ),
        gateway_inflight=r.gauge(
            "areal_gateway_inflight",
            "Requests currently forwarded through the gateway, by "
            "priority class.",
            label_names=("priority",),
        ),
    )


def timeline_metrics(reg: Registry | None = None) -> SimpleNamespace:
    """Request timeline observatory (observability/timeline.py): per-stage
    latency attribution for every engine request. Completed timelines feed
    these histograms; the same breakdown is stamped per-request onto
    ``ModelResponse`` (queue_wait_s / prefill_s / decode_s / ...)."""
    r = reg or get_registry()
    return SimpleNamespace(
        queue_wait=r.histogram(
            "areal_request_queue_wait_seconds",
            "Submission-to-admission wait per request (engine queue + "
            "backlog + slot availability).",
            buckets=FAST_BUCKETS,
        ),
        prefill=r.histogram(
            "areal_request_prefill_seconds",
            "Prefill window per admitted request (suffix-only on a radix "
            "prefix hit; zero-prefill resumes are not observed).",
            buckets=FAST_BUCKETS,
        ),
        ttft=r.histogram(
            "areal_request_ttft_seconds",
            "Engine-side time to first token (queued -> first emitted "
            "token), by priority class (interactive | rollout).",
            label_names=("priority",),
            buckets=FAST_BUCKETS,
        ),
        tpot=r.histogram(
            "areal_request_tpot_seconds",
            "Time per output token after the first (first-token to "
            "terminal over tokens - 1); hold-fence stalls excluded.",
            buckets=(
                0.0001,
                0.00025,
                0.0005,
                0.001,
                0.0025,
                0.005,
                0.01,
                0.025,
                0.05,
                0.1,
                0.25,
                1.0,
            ),
        ),
        fence_stall=r.histogram(
            "areal_request_fence_stall_seconds",
            "Per-request decode stall under weight-commit hold fences "
            "(zero-pause protocol; docs/weight_sync.md).",
            buckets=FAST_BUCKETS,
        ),
        park=r.histogram(
            "areal_request_park_seconds",
            "Parked-KV wait resumed requests carried (abort pause -> "
            "resume round-trip; rid-affinity KV reuse).",
        ),
    )


def flight_metrics(reg: Registry | None = None) -> SimpleNamespace:
    """Fleet flight recorder (observability/timeline.py FlightRecorder):
    significant-event ring visibility."""
    r = reg or get_registry()
    return SimpleNamespace(
        events=r.counter(
            "areal_flight_events_total",
            "Events recorded into the process flight ring, by kind "
            "(admission_reject, evict_radix, evict_parked, preempt, "
            "weight_stage, weight_commit, circuit_open, watchdog, wedge, "
            "quarantine, gateway_shed, ...).",
            label_names=("kind",),
        ),
        dumps=r.counter(
            "areal_flight_dumps_total",
            "Flight-ring dumps persisted to disk (wedge escalation, "
            "SIGTERM, or manual /debug tooling).",
        ),
    )


def server_metrics(reg: Registry | None = None) -> SimpleNamespace:
    """Inference HTTP server: per-request latency + pause/update windows."""
    r = reg or get_registry()
    return SimpleNamespace(
        requests=r.counter(
            "areal_server_requests_total",
            "HTTP requests served, by endpoint.",
            label_names=("endpoint",),
        ),
        ttft=r.histogram(
            "areal_server_ttft_seconds",
            "Per-request time to first token.",
            buckets=FAST_BUCKETS,
        ),
        request_latency=r.histogram(
            "areal_server_generate_seconds",
            "Per-request end-to-end /generate latency.",
        ),
        paused=r.gauge(
            "areal_server_paused",
            "1 while generation is paused for a weight update, else 0.",
        ),
        pauses=r.counter(
            "areal_server_pause_total", "pause_generation calls."
        ),
        resumes=r.counter(
            "areal_server_resume_total", "continue_generation calls."
        ),
        queue_depth=r.gauge(
            "areal_server_queue_depth",
            "Engine submission queue + admission backlog depth.",
        ),
        update_bucket_bytes=r.counter(
            "areal_weight_update_bucket_bytes_total",
            "Streamed weight-bucket bytes received (server side).",
        ),
        update_stage_seconds=r.histogram(
            "areal_weight_update_stage_seconds",
            "Server-side begin->commit latency of a staged weight update.",
        ),
    )


def client_metrics(reg: Registry | None = None) -> SimpleNamespace:
    """RemoteJaxEngine: trainer-side weight-update path."""
    r = reg or get_registry()
    return SimpleNamespace(
        updates=r.counter(
            "areal_weight_update_total", "Weight updates pushed to the fleet."
        ),
        update_bytes=r.counter(
            "areal_weight_update_bytes_total",
            "Encoded weight bytes uploaded (trainer side; 1x per bucket "
            "regardless of relay fan-out).",
        ),
        pause_seconds=r.histogram(
            "areal_weight_update_pause_seconds",
            "Fleet availability gap per update (pause->continue window).",
        ),
        # zero-pause protocol split (docs/weight_sync.md): staging streams
        # while generation runs; only the commit fence costs availability
        stage_seconds=r.histogram(
            "areal_update_stage_secs",
            "Streamed weight-update staging window (begin -> last bucket "
            "staged), during which generation keeps running.",
        ),
        commit_pause_seconds=r.histogram(
            "areal_update_pause_secs",
            "Per-update availability gap under the zero-pause protocol: "
            "the commit fence window only.",
        ),
        tokens_during_update=r.counter(
            "areal_generation_tokens_during_update",
            "Tokens the fleet generated while weight updates were staging "
            "(summed from commit responses; zero-pause visibility).",
        ),
        scrape_retries=r.counter(
            "areal_client_scrape_retries_total",
            "Metric-scrape GETs retried after a timeout or error.",
        ),
    )


def rpc_metrics(reg: Registry | None = None) -> SimpleNamespace:
    """RPC worker server: per-method request/error/latency."""
    r = reg or get_registry()
    return SimpleNamespace(
        requests=r.counter(
            "areal_rpc_requests_total",
            "Engine RPC calls, by method.",
            label_names=("method",),
        ),
        errors=r.counter(
            "areal_rpc_errors_total",
            "Engine RPC calls that raised, by method.",
            label_names=("method",),
        ),
        latency=r.histogram(
            "areal_rpc_request_seconds",
            "Engine RPC call latency, by method.",
            label_names=("method",),
        ),
    )


def trainer_metrics(reg: Registry | None = None) -> SimpleNamespace:
    """PPOTrainer: step cadence + policy version."""
    r = reg or get_registry()
    return SimpleNamespace(
        step_seconds=r.histogram(
            "areal_train_step_seconds",
            "Wall-clock seconds per global training step.",
            buckets=(1.0, 5.0, 15.0, 30.0, 60.0, 120.0, 300.0, 600.0, 1800.0),
        ),
        version=r.gauge(
            "areal_train_version", "Current policy version (global step + 1)."
        ),
        update_seconds=r.histogram(
            "areal_train_update_weights_seconds",
            "Trainer-side update_weights duration per step.",
        ),
    )


def train_obs_metrics(reg: Registry | None = None) -> SimpleNamespace:
    """Trainer goodput observatory (observability/step_timeline.py +
    hw_accounting.py): step-phase attribution, utilization, HBM ledger,
    and XLA compile visibility. The phase histogram labels by the step
    phase taxonomy (rollout_wait | host_prep | forward_backward |
    optimizer | weight_publish | ckpt_eval | other)."""
    r = reg or get_registry()
    return SimpleNamespace(
        phase_seconds=r.histogram(
            "areal_train_phase_seconds",
            "Wall-clock seconds per training-step phase (rollout_wait is "
            "the async bubble: blocking in prepare_batch). Named phases + "
            "the explicit `other` residual sum exactly to the step wall "
            "time (areal_train_step_seconds).",
            label_names=("phase",),
            buckets=(0.001, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 15.0, 60.0, 300.0),
        ),
        bubble_fraction=r.gauge(
            "areal_train_bubble_fraction",
            "rollout_wait / step wall time of the last completed step — "
            "the trainer bubble fully-async RL is supposed to remove.",
        ),
        mfu=r.gauge(
            "areal_train_mfu",
            "Model FLOPs utilization over the last step's compute window "
            "(forward_backward + optimizer phases) vs the chip peak spec "
            "(TelemetryConfig.chip_peak_tflops overrides unknown chips).",
        ),
        tokens_per_chip=r.gauge(
            "areal_train_tokens_per_sec_per_chip",
            "Trained tokens per second per chip over the last full step "
            "(end-to-end goodput; the bubble fraction explains gaps vs "
            "the compute-window MFU).",
        ),
        hbm_bytes=r.gauge(
            "areal_hbm_bytes",
            "Itemized device-memory ledger by component (params, "
            "opt_state, kv_page_pool, radix_cache, staged_update, "
            "in_use, limit); device memory_stats where available, "
            "analytic byte sums on CPU.",
            label_names=("component",),
        ),
        hbm_headroom=r.gauge(
            "areal_hbm_headroom_fraction",
            "Free fraction of device memory (1 - in_use/limit) — the "
            "OOM-headroom number to alert on.",
        ),
        compiles=r.counter(
            "areal_xla_compiles_total",
            "XLA backend compilations observed in this process "
            "(utils/compile_cache counters; a climbing rate mid-training "
            "is a recompile storm — check bucketing/shape keys).",
        ),
        compile_seconds=r.histogram(
            "areal_xla_compile_seconds",
            "Per-compilation backend compile time (jax monitoring hook).",
            buckets=(0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 15.0, 60.0, 300.0),
        ),
        compile_cache_hits=r.counter(
            "areal_xla_compile_cache_hits_total",
            "Compilations served from the persistent XLA compile cache "
            "instead of a fresh backend compile.",
        ),
    )


def learning_health_metrics(reg: Registry | None = None) -> SimpleNamespace:
    """Learning-health observatory (docs/observability.md): decoupled-PPO
    loss diagnostics conditioned on per-token version lag, computed in-jit
    by ``grpo_loss_fn`` and exported once per ``ppo_update``. The
    ``lag_bucket`` label values are the staleness_manager taxonomy
    (``0 | 1 | 2 | 4+``); gauges carry the last step's view for dashboards
    while the ``*_total`` counters give the autopilot's signal plane a
    windowable (bucket-delta) view, per the PR 13 convention."""
    r = reg or get_registry()
    return SimpleNamespace(
        clip_ratio=r.gauge(
            "areal_train_lag_clip_ratio",
            "Fraction of the bucket's valid tokens whose PPO ratio was "
            "clipped in the last update (1.0 = the bucket contributes no "
            "gradient), by version-lag bucket.",
            label_names=("lag_bucket",),
        ),
        behave_kl=r.gauge(
            "areal_train_lag_behave_kl",
            "Mean behave approx-KL (|log pi_prox - log pi_behave|) of the "
            "bucket's uncapped tokens in the last update — how far the "
            "policy moved since the tokens were generated, by lag bucket.",
            label_names=("lag_bucket",),
        ),
        approx_kl=r.gauge(
            "areal_train_lag_approx_kl",
            "Mean approx-KL (log pi_theta - log pi_prox) of the bucket's "
            "valid tokens in the last update, by lag bucket.",
            label_names=("lag_bucket",),
        ),
        imp_weight=r.gauge(
            "areal_train_lag_behave_imp_weight",
            "Mean behave importance weight of the bucket's uncapped "
            "tokens in the last update, by lag bucket.",
            label_names=("lag_bucket",),
        ),
        cap_hit=r.gauge(
            "areal_train_lag_cap_hit_share",
            "Fraction of the bucket's valid tokens whose behave "
            "importance weight hit behav_imp_weight_cap (dead weight: "
            "masked out of the loss), by lag bucket.",
            label_names=("lag_bucket",),
        ),
        token_share=r.gauge(
            "areal_train_lag_token_share",
            "The bucket's share of the last update's valid tokens, by lag "
            "bucket (shares sum to 1 when version tags are present).",
            label_names=("lag_bucket",),
        ),
        tokens_total=r.counter(
            "areal_train_lag_tokens_total",
            "Valid loss tokens trained, by version-lag bucket (the "
            "windowable denominator for the autopilot's learning-health "
            "guard).",
            label_names=("lag_bucket",),
        ),
        clipped_total=r.counter(
            "areal_train_lag_clipped_total",
            "Clipped loss tokens trained, by version-lag bucket.",
            label_names=("lag_bucket",),
        ),
        capped_total=r.counter(
            "areal_train_lag_capped_total",
            "Loss tokens masked out at behav_imp_weight_cap, by version-lag "
            "bucket (the cap-hit tail as a windowable counter).",
            label_names=("lag_bucket",),
        ),
        behave_kl_sum=r.counter(
            "areal_train_lag_behave_kl_sum_total",
            "Sum of behave approx-KL over trained tokens, by lag bucket "
            "(divide a window's delta by the tokens_total delta for the "
            "windowed mean the guard acts on).",
            label_names=("lag_bucket",),
        ),
        lineage_records=r.counter(
            "areal_lineage_records_total",
            "Trajectory lineage records registered (one per accepted "
            "train trajectory; observability/lineage.py ring).",
        ),
        lineage_joined=r.counter(
            "areal_lineage_joined_total",
            "Lineage records joined to training-step loss stats (the "
            "generate->journal->consume->update chain closed for that "
            "trace id).",
        ),
    )


def robustness_metrics(reg: Registry | None = None) -> SimpleNamespace:
    """Fault-tolerance layer (robustness/): retry/circuit/supervision/chaos."""
    r = reg or get_registry()
    return SimpleNamespace(
        replica_state=r.gauge(
            "areal_replica_state",
            "Replica health by address: 0 in rotation (healthy), "
            "1 suspect (half-open circuit / failed probes), "
            "2 evicted (circuit open or supervisor-declared dead).",
            label_names=("replica",),
        ),
        retries=r.counter(
            "areal_retry_total",
            "HTTP requests retried after a failure, by call kind.",
            label_names=("kind",),
        ),
        circuit_open=r.counter(
            "areal_circuit_open_total",
            "Circuit-breaker open transitions (replica evicted from "
            "rotation after consecutive failures).",
        ),
        failovers=r.counter(
            "areal_failover_total",
            "Requests re-routed to a different replica after the preferred "
            "one failed or tripped open.",
        ),
        budget_exhausted=r.counter(
            "areal_retry_budget_exhausted_total",
            "Retries skipped because the retry token budget was exhausted "
            "(fail-fast under fleet-wide outage).",
        ),
        task_retries=r.counter(
            "areal_task_retry_total",
            "Rollout tasks relaunched after a failed attempt.",
        ),
        task_quarantined=r.counter(
            "areal_task_quarantined_total",
            "Rollout tasks dropped as poison after exhausting their "
            "retry strikes.",
        ),
        replica_respawns=r.counter(
            "areal_replica_respawn_total",
            "Dead rollout workers respawned by the controller supervisor.",
        ),
        replica_resyncs=r.counter(
            "areal_replica_resync_total",
            "Replicas that rejoined the fleet needing re-sync (respawned "
            "workers re-versioned by the supervisor; servers refreshed by "
            "the next weight-update fan-out).",
        ),
        recover_fallbacks=r.counter(
            "areal_recover_fallback_total",
            "Recovery loads that fell back to the previous checkpoint "
            "after detecting a corrupt or dangling recover record.",
        ),
        chaos_injected=r.counter(
            "areal_chaos_injected_total",
            "Faults injected by the chaos harness, by kind.",
            label_names=("kind",),
        ),
    )


def preemption_metrics(reg: Registry | None = None) -> SimpleNamespace:
    """Preemption tolerance (robustness/preemption.py + the async
    checkpoint / trajectory-journal paths it drives): graceful-drain
    visibility across trainer and serving roles."""
    r = reg or get_registry()
    return SimpleNamespace(
        preemptions=r.counter(
            "areal_preemption_total",
            "Preemption signals honored (SIGTERM/SIGUSR1 entered the "
            "grace-window drain state machine), by process role "
            "(trainer | inference_server | rollout_worker).",
            label_names=("role",),
        ),
        drain_seconds=r.histogram(
            "areal_drain_seconds",
            "Graceful-drain duration: signal (or drain request) to "
            "drained — trainer rollout drain, or serving finish-or-park "
            "of in-flight decodes.",
            buckets=(0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0),
        ),
        ckpt_save_seconds=r.histogram(
            "areal_ckpt_save_seconds",
            "Step-loop pause per recover/checkpoint save, by mode: "
            "\"sync\" blocks for the full Orbax write, \"async\" only for "
            "the host snapshot (the write runs on a background thread).",
            label_names=("mode",),
            buckets=(0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 15.0, 60.0),
        ),
        journal_appended=r.counter(
            "areal_journal_appended_total",
            "Accepted trajectories appended to the durable trajectory "
            "journal (infra/trajectory_journal.py).",
        ),
        journal_replayed=r.counter(
            "areal_journal_replayed_total",
            "Journaled trajectories replayed into the batch queue on "
            "recovery (still inside the staleness bound — rollout work "
            "saved instead of re-generated).",
        ),
        journal_dropped_stale=r.counter(
            "areal_journal_dropped_stale_total",
            "Journaled trajectories dropped at replay: over-stale for the "
            "restored policy version, or already consumed by a training "
            "step the recover checkpoint covers.",
        ),
    )


def router_metrics(reg: Registry | None = None) -> SimpleNamespace:
    """Cache-aware routing brain (areal_tpu/routing/): replica-selection
    decisions and the predicted-vs-actual prefix-hit audit. Predicted hit
    rate that diverges from actual means the shadow index has drifted from
    the fleet's radix trees (docs/serving.md "Cache-aware routing")."""
    r = reg or get_registry()
    return SimpleNamespace(
        decisions=r.counter(
            "areal_router_decisions_total",
            "Replica-selection decisions, by reason (affinity | "
            "prefix_overlap | least_loaded | rush_deadline | role_pool | "
            "round_robin | stale_snapshots | single_candidate).",
            label_names=("reason",),
        ),
        prefix_overlap=r.histogram(
            "areal_router_prefix_overlap_pages",
            "Shadow-index cached-prefix overlap (KV pages) of the chosen "
            "replica at decision time.",
            buckets=(0, 1, 2, 4, 8, 16, 32, 64, 128, 256),
        ),
        predicted_hits=r.counter(
            "areal_router_predicted_hit_total",
            "Decisions that predicted a warm prefix (shadow-index overlap "
            "> 0 pages on the chosen replica).",
        ),
        actual_hits=r.counter(
            "areal_router_actual_hit_total",
            "Routed requests whose replica reported serving cached prefix "
            "tokens (the engine's radix cache actually hit).",
        ),
        backpressure_demotions=r.counter(
            "areal_router_backpressure_demotions_total",
            "429 responses folded into a replica's score as a transient "
            "demotion instead of circuit-trip/failover.",
        ),
        snapshot_age=r.gauge(
            "areal_router_snapshot_age_seconds",
            "Age of the OLDEST live replica snapshot the router holds "
            "(staleness past routing.snapshot_ttl_s degrades the policy "
            "to round-robin).",
        ),
    )


def autopilot_metrics(reg: Registry | None = None) -> SimpleNamespace:
    """Goodput autopilot (areal_tpu/autopilot/): the adaptive control
    plane's decision audit. Every setpoint change also lands in the
    flight ring as ``kind=autopilot_decision`` with the signal values
    that drove it (docs/autopilot.md)."""
    r = reg or get_registry()
    return SimpleNamespace(
        decisions=r.counter(
            "areal_autopilot_decisions_total",
            "Autopilot setpoint changes applied, by controller "
            "(staleness | admission | cache | fleet) and reason "
            "(trainer_starved | queue_wait_high | shed_under_capacity | "
            "hbm_pressure | sustained_idle | sustained_backlog | ...).",
            label_names=("controller", "reason"),
        ),
        setpoint=r.gauge(
            "areal_autopilot_setpoint",
            "Current autopilot-managed setpoint value, by knob "
            "(max_staleness | max_queue_depth | min_free_pages | "
            "gateway_interactive_headroom | radix_max_fraction | "
            "target_replicas).",
            label_names=("knob",),
        ),
        last_action_age=r.gauge(
            "areal_autopilot_last_action_age_seconds",
            "Seconds since each controller last changed a setpoint "
            "(refreshed every control round; -1 until a controller has "
            "acted).",
            label_names=("controller",),
        ),
        signal_holds=r.counter(
            "areal_autopilot_signal_hold_total",
            "Control rounds a controller held position because a required "
            "signal was absent or older than autopilot.signal_ttl_s (the "
            "stale-signal degradation mirroring the router's round-robin "
            "fallback).",
            label_names=("controller",),
        ),
        guard_vetoes=r.counter(
            "areal_autopilot_guard_veto_total",
            "Setpoint changes vetoed by a learning-health guard (the "
            "staleness controller declining to raise the bound while the "
            "high-lag bucket's tokens are clipped dead weight), by "
            "controller. Audited as kind=autopilot_guard_veto flight "
            "events.",
            label_names=("controller",),
        ),
        apply_failures=r.counter(
            "areal_autopilot_apply_failures_total",
            "Actuations that failed to apply (replica knob POST errored, "
            "drain/undrain failed); the controller's setpoint stands and "
            "the next round re-applies.",
        ),
    )


def aggregator_metrics(reg: Registry | None = None) -> SimpleNamespace:
    """Fleet aggregator: scrape health."""
    r = reg or get_registry()
    return SimpleNamespace(
        scrapes=r.counter(
            "areal_fleet_scrapes_total",
            "Scrape attempts, by outcome.",
            label_names=("outcome",),
        ),
        targets_up=r.gauge(
            "areal_fleet_targets_up", "Scrape targets currently reachable."
        ),
        targets_total=r.gauge(
            "areal_fleet_targets_total", "Scrape targets configured."
        ),
    )


def kernel_metrics(reg: Registry | None = None) -> SimpleNamespace:
    """Kernel observatory (observability/kernel_probe.py): per-decode-step
    phase attribution + roofline join (docs/perf.md "Kernel observatory")."""
    r = reg or get_registry()
    return SimpleNamespace(
        phase_seconds=r.histogram(
            "areal_decode_phase_seconds",
            "Per-decode-step host wall seconds by loop phase (admission, "
            "radix_match, prefill, draft, dispatch, device_wait, verify, "
            "bookkeeping, other); named phases + other sum exactly to the "
            "step wall.",
            label_names=("phase",),
            buckets=FAST_BUCKETS,
        ),
        step_flops=r.gauge(
            "areal_decode_step_flops",
            "Model FLOPs of the last drained decode chunk, from the "
            "compiled executable's cost_analysis or the analytic fallback.",
        ),
        roofline_fraction=r.gauge(
            "areal_decode_roofline_fraction",
            "Achieved over attainable FLOP/s of the last completed decode "
            "step: attainable = min(peak FLOPs, intensity x peak HBM bw).",
        ),
    )


def speculative_metrics(reg: Registry | None = None) -> SimpleNamespace:
    """Speculative decoding (docs/serving.md "Speculative decoding"):
    draft/verify/accept accounting. Acceptance rate =
    accepted_tokens / draft_tokens; each verify round also emits one base
    token that is never at risk, so round throughput is
    (accepted_length + 1) tokens per forward."""
    r = reg or get_registry()
    return SimpleNamespace(
        rounds=r.counter(
            "areal_spec_rounds_total",
            "Speculative draft+verify rounds executed by the decode loop.",
        ),
        draft_tokens=r.counter(
            "areal_spec_draft_tokens_total",
            "Draft tree tokens proposed to the verify forward, by drafter "
            "source (prompt n-gram lookup vs radix prefix tree).",
            label_names=("source",),
        ),
        accepted_tokens=r.counter(
            "areal_spec_accepted_tokens_total",
            "Draft tokens accepted by the target sampler (tokens emitted "
            "beyond each round's base token).",
        ),
        accepted_length=r.histogram(
            "areal_spec_accepted_length",
            "Accepted draft length per slot-round (0 = all drafts "
            "rejected; the base token still emits).",
            buckets=LAG_BUCKETS,
        ),
        rollback_pages=r.counter(
            "areal_spec_rollback_pages_total",
            "KV pages rolled back through the refcounted pool after "
            "partial acceptance (speculative over-allocation freed; "
            "rejected-draft KV itself never lands — it routes to the "
            "trash page).",
        ),
    )


def gateway_tier_metrics(reg: Registry | None = None) -> SimpleNamespace:
    """Horizontally-sharded gateway tier (docs/serving.md "Gateway tier"):
    ring membership health, degraded-mode discovery, and the affinity
    -repair path that resumes sessions on surviving shards."""
    r = reg or get_registry()
    return SimpleNamespace(
        shard_count=r.gauge(
            "areal_gateway_shard_count",
            "Live (non-draining) gateway shards in the current membership "
            "view — the ring's fan-out.",
        ),
        membership_stale=r.counter(
            "areal_gateway_shard_membership_stale_total",
            "Membership refreshes that failed (etcd/name_resolve "
            "unreachable) and kept serving on the last-known view — the "
            "tier's degraded mode is counted, never a crash.",
        ),
        route_recoveries=r.counter(
            "areal_gateway_shard_route_recoveries_total",
            "Sessions adopted by a surviving shard after a re-hash: the "
            "shard had no route for the presented session key and "
            "recovered it by probing the backend proxies (affinity "
            "repair after a shard death).",
        ),
        misroutes=r.counter(
            "areal_gateway_shard_misroute_total",
            "Requests that arrived at a shard other than the one the "
            "client's ring expected (x-areal-expect-shard mismatch) — "
            "served locally anyway; counts ring-view divergence.",
        ),
        sessions=r.gauge(
            "areal_gateway_shard_sessions",
            "Active session routes held by each gateway shard (shard"
            "-local route map size — tier balance at a glance).",
            label_names=("shard",),
        ),
        drains=r.counter(
            "areal_gateway_shard_drain_total",
            "Gateway-shard drain/undrain transitions (autopilot tier "
            "scaling + supervised eviction), by direction.",
            label_names=("direction",),
        ),
    )


ALL_FACTORIES = (
    staleness_metrics,
    executor_metrics,
    engine_metrics,
    kernel_metrics,
    prefix_cache_metrics,
    lifecycle_metrics,
    timeline_metrics,
    flight_metrics,
    server_metrics,
    client_metrics,
    rpc_metrics,
    trainer_metrics,
    train_obs_metrics,
    learning_health_metrics,
    robustness_metrics,
    preemption_metrics,
    router_metrics,
    autopilot_metrics,
    aggregator_metrics,
    gateway_tier_metrics,
    speculative_metrics,
)


def register_all(reg: Registry | None = None) -> Registry:
    """Instantiate every catalogued family (lint + docs tooling)."""
    r = reg or get_registry()
    for factory in ALL_FACTORIES:
        factory(r)
    return r
