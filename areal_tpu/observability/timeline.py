"""Request timeline observatory + fleet flight recorder.

Fully-async RL makes the *interesting* latency invisible: a slow request
could be queue wait, suffix prefill, a weight-commit hold fence, a
park/resume round-trip, or a radix miss — and the aggregate counters in
the metric catalog cannot attribute it. Two primitives close that gap:

- :class:`RequestTimeline` / :class:`TimelineRecorder` — every request
  accumulates timestamped stage events as it moves through the decode
  engine (queued -> admitted -> radix-match -> prefill -> first token ->
  per-chunk decode -> park/resume -> fence-stall -> terminal), tagged with
  the policy version and the ``x-areal-trace`` ids. Completed timelines
  feed the catalogued stage histograms (``areal_request_*_seconds``) and
  a per-request breakdown stamped onto ``ModelResponse`` so the
  WorkflowExecutor/trainer can attribute rollout latency without scraping.
- :class:`FlightRecorder` — a bounded, lock-cheap ring buffer of
  *significant* events per process (admission rejects, evictions by
  ladder rung, weight stage/commit, circuit trips, watchdog/wedge,
  quarantines), exposed at ``/debug/flight`` and dumped atomically
  (utils/atomic_io) on wedge escalation and SIGTERM.
  ``tools/postmortem.py`` scrapes these across a fleet and merges them
  through ``perf_trace_converter`` into one Perfetto timeline.

See docs/observability.md ("Request timelines" / "Flight recorder").
"""

from __future__ import annotations

import json
import os
import signal
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any

from areal_tpu.observability import catalog as obs_catalog
from areal_tpu.utils import logging as alog

logger = alog.getLogger("timeline")

# per-timeline event cap: per-chunk decode events are unbounded on long
# generations; past the cap new events are counted, not stored (the stage
# *durations* come from first/terminal marks, which always record)
MAX_EVENTS_PER_TIMELINE = 256
# completed timelines retained for /debug/flight + postmortem scrapes
DEFAULT_RECENT_TIMELINES = 512
# flight-recorder ring capacity (events, not bytes)
DEFAULT_FLIGHT_CAPACITY = 2048

# the only priority classes the ttft histogram may label with: the header
# is client-controlled, and every distinct value would mint a new labeled
# histogram child — unknown classes clamp to "interactive"
PRIORITY_CLASSES = ("interactive", "rollout")

# stage-name constants (docs/request_lifecycle.md terminals mirror these)
QUEUED = "queued"
ADMITTED = "admitted"
RADIX_MATCH = "radix_match"
PREFILL_START = "prefill_start"
PREFILL_END = "prefill_end"
FIRST_TOKEN = "first_token"
DECODE_CHUNK = "decode_chunk"
DRAFT = "draft"  # speculative round: drafter proposed tokens for this slot
VERIFY = "verify"  # speculative round: verify forward scored + accepted
PARK = "park"
RESUME = "resume"
FENCE_STALL = "fence_stall"
TERMINAL = "terminal"


@dataclass
class RequestTimeline:
    """Stage events of one engine-side generation attempt.

    Timestamps are ``time.monotonic()`` (durations) with one paired
    ``time.time()`` anchor (``epoch_anchor`` at ``queued``) so postmortem
    tooling can place the spans on a cross-process wall clock.
    """

    rid: str
    priority: str = "interactive"
    task_id: str | None = None  # x-areal-trace correlation ids
    session_id: str | None = None
    version: int = -1  # policy version at admission
    queued_ts: float = field(default_factory=time.monotonic)
    epoch_anchor: float = field(default_factory=time.time)
    events: list[tuple[str, float, dict | None]] = field(default_factory=list)
    dropped_events: int = 0
    # accumulators the decode loop maintains outside the event stream
    fence_stall_s: float = 0.0
    # the portion of fence_stall_s accrued BEFORE the first token (a hold
    # can land between prefill and the first chunk): TPOT's window starts
    # at the first token, so only the remainder is subtracted from it
    fence_stall_pre_first_s: float = 0.0
    park_s: float = 0.0
    terminal_reason: str | None = None

    def __post_init__(self) -> None:
        self.events.append((QUEUED, self.queued_ts, None))

    def mark(self, stage: str, **args: Any) -> None:
        # TERMINAL is exempt from the cap: a >cap-chunk generation must
        # still record its end, or the decode span (first_token->terminal)
        # vanishes from traces and ``breakdown`` loses its right edge
        if len(self.events) >= MAX_EVENTS_PER_TIMELINE and stage != TERMINAL:
            self.dropped_events += 1
            return
        self.events.append((stage, time.monotonic(), args or None))

    def ts_of(self, stage: str) -> float | None:
        """Monotonic timestamp of the FIRST occurrence of ``stage``."""
        for name, ts, _ in self.events:
            if name == stage:
                return ts
        return None

    def breakdown(self) -> dict[str, float]:
        """Per-stage durations. ``other_s`` is the explicit residual so the
        named stages plus ``other_s`` always sum to ``total_s`` exactly —
        "stage sums ≈ wall time" is then an assertion that ``other_s`` is
        small, not an accounting identity that hides gaps."""
        t_q = self.queued_ts
        t_admit = self.ts_of(ADMITTED)
        t_ps = self.ts_of(PREFILL_START)
        t_pe = self.ts_of(PREFILL_END)
        t_first = self.ts_of(FIRST_TOKEN)
        t_term = self.ts_of(TERMINAL)
        end = t_term if t_term is not None else time.monotonic()
        total = max(0.0, end - t_q)
        queue_wait = max(0.0, (t_admit if t_admit is not None else end) - t_q)
        prefill = (
            max(0.0, t_pe - t_ps)
            if (t_ps is not None and t_pe is not None)
            else 0.0
        )
        ttft = max(0.0, t_first - t_q) if t_first is not None else 0.0
        # decode runs from the end of prefill (or the resume/aliased
        # admission when there was none) to the terminal — the first token
        # is a milestone INSIDE decode, not its start, so the first chunk's
        # compute (and its pipeline-drain latency) is attributed, not lost.
        # Hold-fence stalls are measured separately and excluded.
        t_dec = t_pe if t_pe is not None else t_admit
        if t_dec is None:
            t_dec = t_first  # defensive: admitted-mark missing
        decode = (
            max(0.0, end - t_dec - self.fence_stall_s)
            if (t_dec is not None and t_first is not None)
            else 0.0
        )
        other = max(
            0.0, total - queue_wait - prefill - decode - self.fence_stall_s
        )
        return {
            "total_s": total,
            "queue_wait_s": queue_wait,
            "prefill_s": prefill,
            "ttft_s": ttft,
            "decode_s": decode,
            "fence_stall_s": self.fence_stall_s,
            "park_s": self.park_s,
            "other_s": other,
        }

    def to_dict(self, breakdown: dict[str, float] | None = None) -> dict[str, Any]:
        """JSON-transportable record for /debug/flight + postmortem.
        ``breakdown`` lets a caller that already computed it (the decode
        loop's ``complete``) skip the second event scan."""
        return {
            "rid": self.rid,
            "priority": self.priority,
            "task_id": self.task_id,
            "session_id": self.session_id,
            "version": self.version,
            "epoch_anchor": self.epoch_anchor,
            "queued_ts": self.queued_ts,
            "terminal_reason": self.terminal_reason,
            "dropped_events": self.dropped_events,
            "events": [
                {"stage": s, "ts": ts, **({"args": a} if a else {})}
                for s, ts, a in self.events
            ],
            "breakdown": breakdown if breakdown is not None else self.breakdown(),
        }


class TimelineRecorder:
    """Engine-side registry of request timelines.

    ``start`` is called from any submitting thread; stage marks and
    ``complete`` run on the decode loop. Completed timelines observe the
    catalogued stage histograms and are retained in a bounded deque for
    /debug scrapes. ``unterminated()`` (started minus completed) is the
    leak detector ``validate_installation --timeline-self-test`` asserts
    on: a nonzero steady-state value means a request left the engine
    without passing through ``complete``.
    """

    def __init__(self, max_recent: int = DEFAULT_RECENT_TIMELINES):
        self._recent: deque[dict] = deque(maxlen=max_recent)
        self._lock = threading.Lock()
        self._started = 0
        self._completed = 0
        self._obs = obs_catalog.timeline_metrics()

    def start(
        self,
        rid: str,
        priority: str = "interactive",
        task_id: str | None = None,
        session_id: str | None = None,
    ) -> RequestTimeline:
        with self._lock:
            self._started += 1
        return RequestTimeline(
            rid=rid,
            priority=priority if priority in PRIORITY_CLASSES else "interactive",
            task_id=task_id,
            session_id=session_id,
        )

    def complete(
        self, tl: RequestTimeline, reason: str, n_tokens: int
    ) -> dict[str, float]:
        """Terminal mark + histogram observation. Returns the breakdown
        (the dict stamped onto ``ModelResponse``)."""
        tl.terminal_reason = reason
        tl.mark(TERMINAL, reason=reason, n_tokens=n_tokens)
        bd = tl.breakdown()
        m = self._obs
        m.queue_wait.observe(bd["queue_wait_s"])
        if bd["prefill_s"] > 0:
            m.prefill.observe(bd["prefill_s"])
        if n_tokens > 0 and bd["ttft_s"] > 0:
            m.ttft.labels(priority=tl.priority).observe(bd["ttft_s"])
        if n_tokens > 1:
            # TPOT is first-token -> terminal (fence stalls excluded) over
            # the remaining tokens — the standard inter-token latency, NOT
            # decode_s/(n-1) (decode_s includes the first chunk)
            t_first = tl.ts_of(FIRST_TOKEN)
            t_term = tl.ts_of(TERMINAL)
            if t_first is not None and t_term is not None:
                in_window_stall = max(
                    0.0, tl.fence_stall_s - tl.fence_stall_pre_first_s
                )
                tail = max(0.0, t_term - t_first - in_window_stall)
                if tail > 0:
                    m.tpot.observe(tail / (n_tokens - 1))
        if bd["fence_stall_s"] > 0:
            m.fence_stall.observe(bd["fence_stall_s"])
        if bd["park_s"] > 0:
            m.park.observe(bd["park_s"])
        with self._lock:
            self._completed += 1
            self._recent.append(tl.to_dict(breakdown=bd))
        return bd

    def unterminated(self) -> int:
        with self._lock:
            return self._started - self._completed

    def stats(self) -> dict[str, int]:
        with self._lock:
            return {
                "started": self._started,
                "completed": self._completed,
                "unterminated": self._started - self._completed,
                "recent": len(self._recent),
            }

    def recent(self, n: int | None = None) -> list[dict]:
        with self._lock:
            out = list(self._recent)
        if n is None:
            return out
        # n bounds the payload: 0 means none (out[-0:] would mean ALL)
        return out[-n:] if n > 0 else []


class FlightRecorder:
    """Bounded ring of significant per-process events.

    ``record`` is a lock + ring append (no I/O, no allocation beyond the
    event dict) so it is safe on the decode loop and in HTTP handlers.
    The ring keeps the newest ``capacity`` events; overflow increments
    ``dropped`` instead of growing. ``dump`` persists the snapshot through
    utils/atomic_io so a crash mid-dump never leaves a torn file — the
    wedge-escalation and SIGTERM paths both dump through it.
    """

    def __init__(
        self,
        capacity: int = DEFAULT_FLIGHT_CAPACITY,
        role: str = "proc",
    ):
        self.capacity = max(1, capacity)
        self.role = role
        self._ring: deque[dict] = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._seq = 0
        self._dropped = 0
        self._obs = obs_catalog.flight_metrics()

    def record(self, kind: str, severity: str = "info", **data: Any) -> None:
        ev = {
            "ts": time.time(),
            "kind": kind,
            "severity": severity,
        }
        if data:
            ev["data"] = data
        with self._lock:
            if len(self._ring) == self.capacity:
                self._dropped += 1
            self._seq += 1
            ev["seq"] = self._seq
            self._ring.append(ev)
        self._obs.events.labels(kind=kind).inc()

    def snapshot(self) -> dict[str, Any]:
        with self._lock:
            return {
                "role": self.role,
                "pid": os.getpid(),
                "capacity": self.capacity,
                "dropped": self._dropped,
                "events": list(self._ring),
            }

    def dump(self, path: str, reason: str = "manual") -> str:
        """Atomically persist the ring (+ the dump reason) as JSON."""
        from areal_tpu.utils import atomic_io

        snap = self.snapshot()
        snap["dump_reason"] = reason
        snap["dumped_at"] = time.time()
        atomic_io.atomic_write_text(path, json.dumps(snap, indent=1))
        self._obs.dumps.inc()
        logger.warning(f"flight recorder dumped to {path} ({reason})")
        return path


# ---------------------------------------------------------------------------
# process-default flight recorder + signal dump
# ---------------------------------------------------------------------------

_FLIGHT = FlightRecorder()


def get_flight_recorder() -> FlightRecorder:
    return _FLIGHT


def default_dump_path(tag: str = "") -> str:
    d = os.environ.get("AREAL_FLIGHT_DIR", "/tmp/areal_tpu/flight")
    name = f"flight_{_FLIGHT.role}_{os.getpid()}"
    if tag:
        name += f"_{tag}"
    return os.path.join(d, name + ".json")


def install_signal_dump(path: str | None = None) -> bool:
    """Dump the flight ring on SIGTERM, then re-deliver the default
    handler (the process still terminates). Only possible from the main
    thread — returns False (and records why) anywhere else.

    The dump runs on a worker thread with a bounded join: the handler
    interrupts the main thread wherever it is, and if that spot happens
    to hold the ring lock (or a metrics shard lock), a dump attempted
    inline would deadlock against the frozen holder and the process would
    never terminate. A wedged dump worker is abandoned after the join
    timeout and SIGTERM proceeds — no dump beats no termination."""
    try:
        prev = signal.getsignal(signal.SIGTERM)

        def _dump():
            _FLIGHT.record("sigterm", severity="warn")
            _FLIGHT.dump(path or default_dump_path("sigterm"), "sigterm")

        def _on_term(signum, frame):
            try:
                # arealint: disable-next=SIG003 last-gasp dump: this process is terminating either way; the worker thread exists precisely so the dump cannot deadlock on a ring/metrics lock the frozen main frame holds (the preferred pre-armed pattern lives in robustness/preemption.py — this is the fallback for processes without a drainer)
                t = threading.Thread(target=_dump, daemon=True)
                t.start()
                # arealint: disable-next=SIG001 bounded 5s join, then SIGTERM proceeds regardless — no dump beats no termination, and the process has no later point to wait at
                t.join(timeout=5.0)
            finally:
                signal.signal(signal.SIGTERM, prev or signal.SIG_DFL)
                signal.raise_signal(signal.SIGTERM)

        signal.signal(signal.SIGTERM, _on_term)
        return True
    except ValueError:  # not the main thread
        logger.debug("signal dump unavailable off the main thread")
        return False


def timelines_to_trace_events(
    timelines: list[dict], base_epoch: float | None = None
) -> list[dict]:
    """Convert timeline records into catapult ``traceEvents``.

    Each stage span becomes an ``X`` (complete) event on the request's own
    tid row; point stages become instants. Monotonic stamps are rebased
    onto the wall clock via each record's ``epoch_anchor`` so events from
    different processes land on one comparable axis (catapult ``ts`` is
    microseconds)."""
    out: list[dict] = []
    for i, rec in enumerate(timelines):
        anchor = rec.get("epoch_anchor") or 0.0
        q_ts = rec.get("queued_ts") or 0.0

        def wall_us(mono_ts: float) -> float:
            return (anchor + (mono_ts - q_ts)) * 1e6

        args = {
            "rid": rec.get("rid"),
            "priority": rec.get("priority"),
            "version": rec.get("version"),
            "terminal": rec.get("terminal_reason"),
        }
        if rec.get("task_id"):
            args["task_id"] = rec["task_id"]
        if rec.get("session_id"):
            args["session_id"] = rec["session_id"]
        tid = 1000 + (i % 1000)
        events = rec.get("events", [])
        # first occurrence wins, matching breakdown()'s ts_of — a repeated
        # stage mark must not stretch a span over its successors
        stamps: dict[str, float] = {}
        for e in events:
            stamps.setdefault(e["stage"], e["ts"])
        # decode anchors where breakdown() anchors it — PREFILL_END (or the
        # resume/aliased admission when there was none): the first chunk's
        # compute must render as decode, not as blank space between spans
        decode_start = (
            PREFILL_END
            if PREFILL_END in stamps
            else (ADMITTED if ADMITTED in stamps else FIRST_TOKEN)
        )
        spans = (
            ("queue_wait", QUEUED, ADMITTED),
            ("prefill", PREFILL_START, PREFILL_END),
            ("decode", decode_start, TERMINAL),
        )
        for name, s0, s1 in spans:
            if name == "decode" and FIRST_TOKEN not in stamps:
                continue  # no token ever emitted: breakdown's decode_s is 0
            if s0 in stamps and s1 in stamps and stamps[s1] >= stamps[s0]:
                out.append(
                    {
                        "name": name,
                        "ph": "X",
                        "tid": tid,
                        "ts": wall_us(stamps[s0]),
                        "dur": (stamps[s1] - stamps[s0]) * 1e6,
                        "cat": "timeline",
                        "args": args,
                    }
                )
        for e in events:
            if e["stage"] in (
                RADIX_MATCH,
                DRAFT,
                VERIFY,
                PARK,
                RESUME,
                FENCE_STALL,
                TERMINAL,
            ):
                out.append(
                    {
                        "name": e["stage"],
                        "ph": "i",
                        "s": "t",
                        "tid": tid,
                        "ts": wall_us(e["ts"]),
                        "cat": "timeline",
                        "args": {**args, **(e.get("args") or {})},
                    }
                )
    return out


def flight_to_trace_events(snapshot: dict) -> list[dict]:
    """Convert a flight-recorder snapshot into catapult instant events
    (one shared tid row; ``ts`` already wall-clock)."""
    out = []
    for ev in snapshot.get("events", []):
        out.append(
            {
                "name": ev.get("kind", "event"),
                "ph": "i",
                "s": "p",
                "tid": 1,
                "ts": float(ev.get("ts", 0.0)) * 1e6,
                "cat": "flight",
                "args": {
                    "severity": ev.get("severity"),
                    **(ev.get("data") or {}),
                },
            }
        )
    return out
