from areal_tpu.reward.gsm8k import gsm8k_reward_fn  # noqa: F401
