"""Self-contained math answer verification.

The reference routes gsm8k/geometry3k rewards through the external
``math_verify`` package in a worker process (areal/reward/gsm8k.py,
geometry3k.py). That package is not in the TPU image, so this module
implements the verification behavior directly: extract the model's final
answer (\\boxed{}, "#### x", or last number), normalize LaTeX/numeric forms,
and compare numerically with tolerance, falling back to normalized string
equality. Covers the formats GSM8K / MATH-style datasets emit.
"""

from __future__ import annotations

import re
from fractions import Fraction

_BOXED_RE = re.compile(r"\\boxed\s*\{")
_HASH_ANS_RE = re.compile(r"####\s*(.+?)\s*$", re.MULTILINE)
_NUM_RE = re.compile(r"-?\d[\d,]*(?:\.\d+)?")


def extract_boxed(text: str) -> str | None:
    """Contents of the LAST \\boxed{...}, brace-balanced."""
    last = None
    for m in _BOXED_RE.finditer(text):
        depth, start = 1, m.end()
        for i in range(start, len(text)):
            if text[i] == "{":
                depth += 1
            elif text[i] == "}":
                depth -= 1
                if depth == 0:
                    last = text[start:i]
                    break
    return last


def extract_answer(text: str) -> str | None:
    """Final answer from a completion: boxed > '#### x' > last number."""
    boxed = extract_boxed(text)
    if boxed is not None:
        return boxed
    m = _HASH_ANS_RE.search(text)
    if m:
        return m.group(1)
    nums = _NUM_RE.findall(text)
    return nums[-1] if nums else None


def _normalize(ans: str) -> str:
    s = ans.strip()
    s = s.replace("\\$", "").replace("$", "").replace("\\%", "").replace("%", "")
    s = re.sub(r"\\text\s*\{([^}]*)\}", r"\1", s)
    s = re.sub(r"\\mathrm\s*\{([^}]*)\}", r"\1", s)
    s = re.sub(r"\\(?:left|right|!|,|;)", "", s)
    s = re.sub(r"\\d?frac\s*\{([^{}]*)\}\s*\{([^{}]*)\}", r"(\1)/(\2)", s)
    s = re.sub(r"\\sqrt\s*\{([^{}]*)\}", r"sqrt(\1)", s)
    s = s.replace("\\cdot", "*").replace("\\times", "*").replace("^", "**")
    s = s.replace(" ", "").replace(",", "")
    return s.rstrip(".")


def _to_number(s: str) -> Fraction | None:
    s = s.strip()
    try:
        if "/" in s:
            num, den = s.split("/", 1)
            return Fraction(
                Fraction(num.strip("()")), Fraction(den.strip("()"))
            )
        if "." in s or "e" in s.lower():
            return Fraction(s)
        return Fraction(int(s))
    except (ValueError, ZeroDivisionError):
        return None


def answers_equal(given: str, reference: str) -> bool:
    """Normalized numeric-or-string equivalence of two final answers."""
    a, b = _normalize(given), _normalize(reference)
    if a == b:
        return True
    na, nb = _to_number(a), _to_number(b)
    if na is not None and nb is not None:
        if na == nb:
            return True
        # decimal-rounding tolerance (e.g. 0.333 vs 1/3)
        return abs(float(na) - float(nb)) < 1e-6 * max(1.0, abs(float(nb)))
    return False


def math_verify_reward_fn(
    prompt, completions, prompt_ids, completion_ids, answer, **kwargs
) -> float:
    """Binary verifiable reward: 1.0 iff the completion's final answer
    matches ``answer`` (the reference's math_verify worker contract)."""
    given = extract_answer(str(completions))
    if given is None:
        return 0.0
    # the reference answer only gets UNWRAPPED (boxed / '#### x'); the
    # last-number fallback is for model completions, not ground truth
    ref = str(answer)
    ref = extract_boxed(ref) or (
        m.group(1) if (m := _HASH_ANS_RE.search(ref)) else ref
    )
    return 1.0 if answers_equal(given, ref) else 0.0
