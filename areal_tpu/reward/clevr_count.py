"""CLEVR counting reward (reference areal/reward/clevr_count_70k.py):
the model answers with a bracketed count like "[3]"; exact string match."""

from __future__ import annotations

import re

_BRACKET_RE = re.compile(r"\[([0-9\.]+)\]")


def extract_bracketed(pred: str) -> str:
    matches = _BRACKET_RE.findall(pred)
    return matches[-1] if matches else ""


def clevr_count_reward_fn(
    prompt, completions, prompt_ids, completion_ids, answer, **kwargs
) -> float:
    sol = extract_bracketed(str(completions))
    if not sol or answer is None:
        return 0.0
    return 1.0 if sol.strip() == str(answer).strip() else 0.0
