"""Countdown numbers-game reward (reference examples/countdown/
reward_score.py behavior, re-derived): the completion must end with
``<answer>EQUATION</answer>`` where EQUATION uses each provided number
exactly once with + - * / ( ) and evaluates to the target.

Scores: 1.0 correct; 0.1 well-formed (parsable equation using exactly the
provided numbers) but wrong value — the reference's format credit; 0.0
otherwise.
"""

from __future__ import annotations

import re

_ANSWER_RE = re.compile(r"<answer>(.*?)</answer>", re.DOTALL)
_ALLOWED = set("0123456789+-*/(). ")


def extract_equation(text: str) -> str | None:
    matches = _ANSWER_RE.findall(text)
    return matches[-1].strip() if matches else None


def uses_exact_numbers(equation: str, numbers: list[int]) -> bool:
    in_eq = sorted(int(n) for n in re.findall(r"\d+", equation))
    return in_eq == sorted(int(n) for n in numbers)


def safe_eval(equation: str) -> float | None:
    # '**' (power) and '//' (floor division) are outside the task's stated
    # + - * / op set; rewarding them would diverge from the prompt spec
    if (
        not equation
        or not set(equation) <= _ALLOWED
        or "**" in equation
        or "//" in equation
    ):
        return None
    try:
        return float(eval(equation, {"__builtins__": {}}, {}))  # noqa: S307
    except Exception:  # noqa: BLE001 — malformed model output
        return None


def countdown_reward_fn(
    prompt, completions, prompt_ids, completion_ids, numbers=None, target=None, **kw
) -> float:
    equation = extract_equation(str(completions))
    if equation is None or numbers is None or target is None:
        return 0.0
    if not uses_exact_numbers(equation, list(numbers)):
        return 0.0
    value = safe_eval(equation)
    if value is None:
        return 0.0
    if abs(value - float(target)) < 1e-6:
        return 1.0
    return 0.1  # well-formed attempt: format credit
