"""Tokenizer-free rewards for the zero-asset smoke/e2e path (plays the role
of the reference's GSM8K reward in tests/grpo/test_grpo.py at unit scale)."""

from __future__ import annotations


def arith_char_reward_fn(
    prompt: str, completions: str, prompt_ids, completion_ids, **kwargs
) -> float:
    """Char-level decode of the completion must start with the answer digits
    (dataset 'synthetic_arith' rows carry answer='#### <sum>')."""
    answer = str(kwargs.get("answer", "")).split("####")[-1].strip()
    text = "".join(chr(int(t)) for t in completion_ids if 32 <= int(t) < 127)
    got = "".join(c for c in text if c.isdigit() or c == "-")
    return 1.0 if answer and got.startswith(answer) else 0.0


def target_token_reward_fn(
    prompt: str, completions: str, prompt_ids, completion_ids, target: int = 7, **kw
) -> float:
    return 1.0 if int(target) in [int(t) for t in completion_ids] else 0.0
