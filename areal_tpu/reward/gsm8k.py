"""GSM8K answer extraction + exact-match reward.

Behavioral parity with reference areal/reward/gsm8k.py: extract the final
number (after "####" in references, last number in completions) and compare
canonicalized strings.
"""

from __future__ import annotations

import re

_NUM = re.compile(r"-?[\d,]*\.?\d+")


def extract_answer(text: str) -> str | None:
    if "####" in text:
        text = text.split("####")[-1]
    matches = _NUM.findall(text)
    if not matches:
        return None
    return matches[-1].replace(",", "").rstrip(".").strip()


def _canon(s: str) -> str:
    s = s.replace(",", "").strip()
    try:
        f = float(s)
        return str(int(f)) if f == int(f) else str(f)
    except ValueError:
        return s


def gsm8k_reward_fn(
    prompt: str,
    completions: str,
    prompt_ids,
    completion_ids,
    answer: str = "",
    **kwargs,
) -> float:
    pred = extract_answer(completions)
    gold = extract_answer(answer) if answer else None
    if pred is None or gold is None:
        return 0.0
    return float(_canon(pred) == _canon(gold))
