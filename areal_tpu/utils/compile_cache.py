"""Persistent XLA compile-cache enablement, gated the only safe way.

One policy for every entrypoint (bench phases, ladder steps, the decode
engine, ad-hoc profiling): enable jax's persistent compilation cache ONLY
when the initialized backend is really TPU. CPU runs must never share the
cache: AOT CPU entries are machine-feature-specific, and the axon
remote-compile service writes entries with the *service host's* features —
loading those locally produces cpu_aot_loader errors / SIGILL-class
failures (verify-skill gotcha, observed r02-r04).

The default location is ``<repo>/.jax_cache`` so compiled programs survive
across bench phases AND across rounds (VERDICT r04 item #1: the cold-start
compile is what kept killing the measurement window).

This module also owns the XLA compile COUNTERS
(``install_compile_counters``): a jax monitoring listener that feeds every
backend compilation into ``areal_xla_compiles_total`` /
``areal_xla_compile_seconds`` (+ persistent-cache hits), so recompile
storms — a drifting jit shape key recompiling the train program every
step — show up as a climbing counter on the trainer dashboard instead of
as mystery wall time (docs/observability.md "Trainer observatory").
"""

import os
import threading

_DEFAULT_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    ".jax_cache",
)

# jax monitoring event names (stable across 0.4.x): one duration event per
# backend compile, one point event per persistent-cache hit
_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
_CACHE_HIT_EVENT = "/jax/compilation_cache/cache_hits"

_stats_lock = threading.Lock()
_COMPILE_STATS = {"compiles": 0, "compile_seconds": 0.0, "cache_hits": 0}
_INSTALLED = False


def enable_persistent_cache(cache_dir: str | None = None) -> str | None:
    """Enable the persistent compile cache if (and only if) backend==tpu.

    Returns the cache dir in effect, or None when disabled. Safe to call
    repeatedly; an explicitly pre-configured dir wins over the default.
    """
    import jax

    if jax.default_backend() != "tpu":
        return None
    if jax.config.jax_compilation_cache_dir is None:
        jax.config.update(
            "jax_compilation_cache_dir",
            cache_dir or os.environ.get("AREAL_COMPILE_CACHE", _DEFAULT_DIR),
        )
    # cache even sub-second programs — whatever dir is in effect: the
    # serving path replays dozens of small chunk/scatter variants whose
    # compiles sum to the cold-start cost
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    return jax.config.jax_compilation_cache_dir


def install_compile_counters() -> bool:
    """Feed every XLA backend compilation into the catalogued compile
    metrics. Idempotent; returns False when the jax monitoring hook is
    unavailable (the observatory then simply shows no compile rows)."""
    global _INSTALLED
    if _INSTALLED:
        return True
    try:
        from jax._src import monitoring
    except ImportError:
        return False
    from areal_tpu.observability import catalog as obs_catalog

    obs = obs_catalog.train_obs_metrics()

    def _on_duration(event: str, duration: float, **_kw) -> None:
        if event != _COMPILE_EVENT:
            return
        with _stats_lock:
            _COMPILE_STATS["compiles"] += 1
            _COMPILE_STATS["compile_seconds"] += duration
        obs.compiles.inc()
        obs.compile_seconds.observe(duration)

    def _on_event(event: str, **_kw) -> None:
        if event != _CACHE_HIT_EVENT:
            return
        with _stats_lock:
            _COMPILE_STATS["cache_hits"] += 1
        obs.compile_cache_hits.inc()

    try:
        monitoring.register_event_duration_secs_listener(_on_duration)
        monitoring.register_event_listener(_on_event)
    except Exception:  # noqa: BLE001 — monitoring API drift: degrade quiet
        return False
    _INSTALLED = True
    return True


def compile_stats() -> dict:
    """Process-lifetime compile counters (also mirrored in the metric
    registry): compiles, total compile seconds, persistent-cache hits."""
    with _stats_lock:
        return dict(_COMPILE_STATS)
