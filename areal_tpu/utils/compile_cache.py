"""Persistent XLA compile-cache enablement, gated the only safe way.

One policy for every entrypoint (bench phases, ladder steps, the decode
engine, ad-hoc profiling): enable jax's persistent compilation cache ONLY
when the initialized backend is really TPU. CPU runs must never share the
cache: AOT CPU entries are machine-feature-specific, and the axon
remote-compile service writes entries with the *service host's* features —
loading those locally produces cpu_aot_loader errors / SIGILL-class
failures (verify-skill gotcha, observed r02-r04).

The default location is ``<repo>/.jax_cache`` so compiled programs survive
across bench phases AND across rounds (VERDICT r04 item #1: the cold-start
compile is what kept killing the measurement window).
"""

import os

_DEFAULT_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    ".jax_cache",
)


def enable_persistent_cache(cache_dir: str | None = None) -> str | None:
    """Enable the persistent compile cache if (and only if) backend==tpu.

    Returns the cache dir in effect, or None when disabled. Safe to call
    repeatedly; an explicitly pre-configured dir wins over the default.
    """
    import jax

    if jax.default_backend() != "tpu":
        return None
    if jax.config.jax_compilation_cache_dir is None:
        jax.config.update(
            "jax_compilation_cache_dir",
            cache_dir or os.environ.get("AREAL_COMPILE_CACHE", _DEFAULT_DIR),
        )
    # cache even sub-second programs — whatever dir is in effect: the
    # serving path replays dozens of small chunk/scatter variants whose
    # compiles sum to the cold-start cost
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    return jax.config.jax_compilation_cache_dir
