"""KV service discovery with TTL and watch (parity: reference
areal/utils/name_resolve.py:182,282,410,1209).

Backends: in-process memory (tests, single host) and filesystem tree (NFS —
the multi-host path on TPU pods, where every host mounts shared storage).
etcd is intentionally not implemented (no etcd3 client in the image); the
filesystem backend covers the same contract.

TTL semantics: an entry added with ``keepalive_ttl`` expires (reads treat it
as missing) unless refreshed; ``KeepaliveThread`` re-adds it periodically,
mirroring the reference's keepalive threads, so entries of crashed processes
drop out of discovery.
"""

from __future__ import annotations

import json
import os
import shutil
import threading
import time
from abc import ABC, abstractmethod


class NameEntryExistsError(RuntimeError):
    pass


class NameEntryNotFoundError(RuntimeError):
    pass


class NameResolveRepo(ABC):
    @abstractmethod
    def add(self, name: str, value: str, replace: bool = False, keepalive_ttl: float | None = None) -> None: ...

    @abstractmethod
    def get(self, name: str) -> str: ...

    @abstractmethod
    def get_subtree(self, name_root: str) -> list[str]: ...

    @abstractmethod
    def find_subtree(self, name_root: str) -> list[str]: ...

    @abstractmethod
    def delete(self, name: str) -> None: ...

    @abstractmethod
    def clear_subtree(self, name_root: str) -> None: ...

    def wait(self, name: str, timeout: float | None = None, poll_frequency: float = 0.5) -> str:
        deadline = time.monotonic() + timeout if timeout is not None else None
        while True:
            try:
                return self.get(name)
            except NameEntryNotFoundError:
                if deadline is not None and time.monotonic() >= deadline:
                    raise TimeoutError(f"timeout waiting for name {name!r}")
                time.sleep(poll_frequency)

    def keepalive(self, name: str, value: str, ttl: float) -> "KeepaliveThread":
        """Register ``name`` with a TTL and keep refreshing it until stopped."""
        self.add(name, value, replace=True, keepalive_ttl=ttl)
        return KeepaliveThread(self, name, value, ttl)

    def reset(self) -> None:
        pass


class KeepaliveThread:
    def __init__(self, repo: NameResolveRepo, name: str, value: str, ttl: float):
        self._repo = repo
        self._name = name
        self._value = value
        self._ttl = ttl
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        period = max(0.1, self._ttl / 3)
        while not self._stop.wait(period):
            try:
                self._repo.add(
                    self._name, self._value, replace=True, keepalive_ttl=self._ttl
                )
            except Exception:
                pass

    def stop(self, delete_entry: bool = True):
        self._stop.set()
        self._thread.join(timeout=2)
        if delete_entry:
            try:
                self._repo.delete(self._name)
            except NameEntryNotFoundError:
                pass


class MemoryNameResolveRepo(NameResolveRepo):
    def __init__(self):
        self._lock = threading.RLock()
        # name -> (value, expires_at | None)
        self._store: dict[str, tuple[str, float | None]] = {}

    def _alive(self, name: str) -> bool:
        entry = self._store.get(name)
        if entry is None:
            return False
        _, exp = entry
        if exp is not None and time.monotonic() > exp:
            del self._store[name]
            return False
        return True

    def add(self, name, value, replace=False, keepalive_ttl=None):
        with self._lock:
            if self._alive(name) and not replace:
                raise NameEntryExistsError(name)
            exp = time.monotonic() + keepalive_ttl if keepalive_ttl else None
            self._store[name] = (str(value), exp)

    def get(self, name):
        with self._lock:
            if not self._alive(name):
                raise NameEntryNotFoundError(name)
            return self._store[name][0]

    def find_subtree(self, name_root):
        with self._lock:
            prefix = name_root.rstrip("/") + "/"
            return sorted(
                k
                for k in list(self._store)
                if (k == name_root or k.startswith(prefix)) and self._alive(k)
            )

    def get_subtree(self, name_root):
        with self._lock:
            return [self._store[k][0] for k in self.find_subtree(name_root)]

    def delete(self, name):
        with self._lock:
            if not self._alive(name):
                raise NameEntryNotFoundError(name)
            del self._store[name]

    def clear_subtree(self, name_root):
        with self._lock:
            for k in self.find_subtree(name_root):
                self._store.pop(k, None)

    def reset(self):
        with self._lock:
            self._store.clear()


class NfsNameResolveRepo(NameResolveRepo):
    """File-tree backend: one JSON file per key under ``root``."""

    def __init__(self, root: str = "/tmp/areal_tpu/name_resolve"):
        self._root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, name: str) -> str:
        return os.path.join(self._root, name.strip("/"), "ENTRY.json")

    def _read(self, name: str) -> str:
        p = self._path(name)
        try:
            with open(p) as f:
                entry = json.load(f)
        except (FileNotFoundError, json.JSONDecodeError):
            raise NameEntryNotFoundError(name)
        ttl = entry.get("ttl")
        if ttl is not None and time.time() > entry["ts"] + ttl:
            try:
                os.remove(p)
            except FileNotFoundError:
                pass
            raise NameEntryNotFoundError(name)
        return entry["value"]

    def add(self, name, value, replace=False, keepalive_ttl=None):
        p = self._path(name)
        if not replace:
            try:
                self._read(name)
                raise NameEntryExistsError(name)
            except NameEntryNotFoundError:
                pass
        os.makedirs(os.path.dirname(p), exist_ok=True)
        tmp = p + f".tmp.{os.getpid()}.{threading.get_ident()}"
        with open(tmp, "w") as f:
            json.dump(
                {"value": str(value), "ts": time.time(), "ttl": keepalive_ttl}, f
            )
        os.replace(tmp, p)

    def get(self, name):
        return self._read(name)

    def _walk(self, name_root) -> list[tuple[str, str]]:
        """Single-read listing: (name, value) for each live entry."""
        base = os.path.join(self._root, name_root.strip("/"))
        entries = []
        if os.path.isdir(base):
            for dirpath, _, files in os.walk(base):
                if "ENTRY.json" in files:
                    rel = os.path.relpath(dirpath, self._root)
                    try:
                        entries.append((rel, self._read(rel)))
                    except NameEntryNotFoundError:
                        continue
        return sorted(entries)

    def find_subtree(self, name_root):
        return [n for n, _ in self._walk(name_root)]

    def get_subtree(self, name_root):
        return [v for _, v in self._walk(name_root)]

    def delete(self, name):
        p = self._path(name)
        if not os.path.exists(p):
            raise NameEntryNotFoundError(name)
        os.remove(p)

    def clear_subtree(self, name_root):
        base = os.path.join(self._root, name_root.strip("/"))
        if os.path.isdir(base):
            shutil.rmtree(base, ignore_errors=True)


def _repo_from_env() -> "NameResolveRepo":
    """Cross-process discovery needs a shared backend: launchers/schedulers
    export AREAL_NAME_RESOLVE(=file)+AREAL_NAME_RESOLVE_ROOT so every child
    process resolves against the same tree (reference NameResolveConfig)."""
    kind = os.environ.get("AREAL_NAME_RESOLVE", "memory")
    if kind in ("nfs", "file"):
        root = os.environ.get("AREAL_NAME_RESOLVE_ROOT")
        return NfsNameResolveRepo(**({"root": root} if root else {}))
    return MemoryNameResolveRepo()


DEFAULT_REPO: NameResolveRepo = _repo_from_env()


def make_repo(type_: str = "memory", **kwargs) -> NameResolveRepo:
    if type_ == "memory":
        return MemoryNameResolveRepo()
    if type_ in ("nfs", "file"):
        return NfsNameResolveRepo(**kwargs)
    raise ValueError(f"unknown name_resolve backend {type_!r}")


def reconfigure(type_: str = "memory", **kwargs) -> NameResolveRepo:
    global DEFAULT_REPO
    DEFAULT_REPO = make_repo(type_, **kwargs)
    return DEFAULT_REPO


# Conventional key layout (parity with reference names.py)
def rollout_server_key(experiment: str, trial: str, server_idx: int | str = "") -> str:
    base = f"{experiment}/{trial}/rollout_servers"
    return f"{base}/{server_idx}" if server_idx != "" else base


add = lambda *a, **k: DEFAULT_REPO.add(*a, **k)  # noqa: E731
get = lambda *a, **k: DEFAULT_REPO.get(*a, **k)  # noqa: E731
get_subtree = lambda *a, **k: DEFAULT_REPO.get_subtree(*a, **k)  # noqa: E731
find_subtree = lambda *a, **k: DEFAULT_REPO.find_subtree(*a, **k)  # noqa: E731
delete = lambda *a, **k: DEFAULT_REPO.delete(*a, **k)  # noqa: E731
clear_subtree = lambda *a, **k: DEFAULT_REPO.clear_subtree(*a, **k)  # noqa: E731
wait = lambda *a, **k: DEFAULT_REPO.wait(*a, **k)  # noqa: E731
keepalive = lambda *a, **k: DEFAULT_REPO.keepalive(*a, **k)  # noqa: E731
