"""KV service discovery with TTL and watch (parity: reference
areal/utils/name_resolve.py:182,282,410,1209).

Backends:
- in-process memory (tests, single host);
- filesystem tree (NFS — multi-host TPU pods where every host mounts shared
  storage);
- etcd v3 over its JSON gRPC-gateway (clusters WITHOUT a shared filesystem).
  The reference uses the ``etcd3`` python client (name_resolve.py:410-780);
  this image ships no etcd client, so the backend speaks the gateway's
  ``/v3/kv/*`` + ``/v3/lease/*`` HTTP endpoints with stdlib urllib — zero
  new dependencies, works against any etcd >= 3.3.

TTL semantics: an entry added with ``keepalive_ttl`` expires (reads treat it
as missing) unless refreshed; ``KeepaliveThread`` re-adds it periodically,
mirroring the reference's keepalive threads, so entries of crashed processes
drop out of discovery. On etcd the TTL is a lease (1 s server-side
granularity — etcd rejects sub-second leases, so TTLs round up).
"""

from __future__ import annotations

import base64
import json
import math
import os
import shutil
import threading
import time
import urllib.error
import urllib.request
from abc import ABC, abstractmethod


class NameEntryExistsError(RuntimeError):
    pass


class NameEntryNotFoundError(RuntimeError):
    pass


class NameResolveRepo(ABC):
    @abstractmethod
    def add(self, name: str, value: str, replace: bool = False, keepalive_ttl: float | None = None) -> None: ...

    @abstractmethod
    def get(self, name: str) -> str: ...

    @abstractmethod
    def get_subtree(self, name_root: str) -> list[str]: ...

    @abstractmethod
    def find_subtree(self, name_root: str) -> list[str]: ...

    @abstractmethod
    def delete(self, name: str) -> None: ...

    @abstractmethod
    def clear_subtree(self, name_root: str) -> None: ...

    def wait(self, name: str, timeout: float | None = None, poll_frequency: float = 0.5) -> str:
        deadline = time.monotonic() + timeout if timeout is not None else None
        while True:
            try:
                return self.get(name)
            except NameEntryNotFoundError:
                if deadline is not None and time.monotonic() >= deadline:
                    raise TimeoutError(f"timeout waiting for name {name!r}")
                time.sleep(poll_frequency)

    def keepalive(self, name: str, value: str, ttl: float) -> "KeepaliveThread":
        """Register ``name`` with a TTL and keep refreshing it until stopped."""
        self.add(name, value, replace=True, keepalive_ttl=ttl)
        return KeepaliveThread(self, name, value, ttl)

    def reset(self) -> None:
        pass


class KeepaliveThread:
    def __init__(self, repo: NameResolveRepo, name: str, value: str, ttl: float):
        self._repo = repo
        self._name = name
        self._value = value
        self._ttl = ttl
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        period = max(0.1, self._ttl / 3)
        while not self._stop.wait(period):
            try:
                self._repo.add(
                    self._name, self._value, replace=True, keepalive_ttl=self._ttl
                )
            except Exception:
                pass

    def stop(self, delete_entry: bool = True):
        self._stop.set()
        self._thread.join(timeout=2)
        if delete_entry:
            try:
                self._repo.delete(self._name)
            except NameEntryNotFoundError:
                pass


class MemoryNameResolveRepo(NameResolveRepo):
    def __init__(self):
        self._lock = threading.RLock()
        # name -> (value, expires_at | None)
        self._store: dict[str, tuple[str, float | None]] = {}

    def _alive(self, name: str) -> bool:
        entry = self._store.get(name)
        if entry is None:
            return False
        _, exp = entry
        if exp is not None and time.monotonic() > exp:
            del self._store[name]
            return False
        return True

    def add(self, name, value, replace=False, keepalive_ttl=None):
        with self._lock:
            if self._alive(name) and not replace:
                raise NameEntryExistsError(name)
            exp = time.monotonic() + keepalive_ttl if keepalive_ttl else None
            self._store[name] = (str(value), exp)

    def get(self, name):
        with self._lock:
            if not self._alive(name):
                raise NameEntryNotFoundError(name)
            return self._store[name][0]

    def find_subtree(self, name_root):
        with self._lock:
            prefix = name_root.rstrip("/") + "/"
            return sorted(
                k
                for k in list(self._store)
                if (k == name_root or k.startswith(prefix)) and self._alive(k)
            )

    def get_subtree(self, name_root):
        with self._lock:
            return [self._store[k][0] for k in self.find_subtree(name_root)]

    def delete(self, name):
        with self._lock:
            if not self._alive(name):
                raise NameEntryNotFoundError(name)
            del self._store[name]

    def clear_subtree(self, name_root):
        with self._lock:
            for k in self.find_subtree(name_root):
                self._store.pop(k, None)

    def reset(self):
        with self._lock:
            self._store.clear()


class NfsNameResolveRepo(NameResolveRepo):
    """File-tree backend: one JSON file per key under ``root``."""

    def __init__(self, root: str = "/tmp/areal_tpu/name_resolve"):
        self._root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, name: str) -> str:
        return os.path.join(self._root, name.strip("/"), "ENTRY.json")

    def _read(self, name: str) -> str:
        p = self._path(name)
        try:
            with open(p) as f:
                entry = json.load(f)
        except (FileNotFoundError, json.JSONDecodeError):
            raise NameEntryNotFoundError(name)
        ttl = entry.get("ttl")
        if ttl is not None and time.time() > entry["ts"] + ttl:
            try:
                os.remove(p)
            except FileNotFoundError:
                pass
            raise NameEntryNotFoundError(name)
        return entry["value"]

    def add(self, name, value, replace=False, keepalive_ttl=None):
        p = self._path(name)
        if not replace:
            try:
                self._read(name)
                raise NameEntryExistsError(name)
            except NameEntryNotFoundError:
                pass
        os.makedirs(os.path.dirname(p), exist_ok=True)
        tmp = p + f".tmp.{os.getpid()}.{threading.get_ident()}"
        with open(tmp, "w") as f:
            json.dump(
                {"value": str(value), "ts": time.time(), "ttl": keepalive_ttl}, f
            )
        os.replace(tmp, p)

    def get(self, name):
        return self._read(name)

    def _walk(self, name_root) -> list[tuple[str, str]]:
        """Single-read listing: (name, value) for each live entry."""
        base = os.path.join(self._root, name_root.strip("/"))
        entries = []
        if os.path.isdir(base):
            for dirpath, _, files in os.walk(base):
                if "ENTRY.json" in files:
                    rel = os.path.relpath(dirpath, self._root)
                    try:
                        entries.append((rel, self._read(rel)))
                    except NameEntryNotFoundError:
                        continue
        return sorted(entries)

    def find_subtree(self, name_root):
        return [n for n, _ in self._walk(name_root)]

    def get_subtree(self, name_root):
        return [v for _, v in self._walk(name_root)]

    def delete(self, name):
        p = self._path(name)
        if not os.path.exists(p):
            raise NameEntryNotFoundError(name)
        os.remove(p)

    def clear_subtree(self, name_root):
        base = os.path.join(self._root, name_root.strip("/"))
        if os.path.isdir(base):
            shutil.rmtree(base, ignore_errors=True)


class Etcd3NameResolveRepo(NameResolveRepo):
    """etcd v3 backend via the JSON gRPC-gateway (no client library).

    Key layout matches the other repos (path-like names). Prefix queries
    issue two ranges — the exact key and ``name/``-prefixed descendants —
    so ``get_subtree("exp/t")`` can never match a sibling ``exp/tx`` (the
    memory/NFS repos have the same boundary semantics).

    TTL entries attach to a fresh lease per add; a keepalive refresh grants
    a new lease, re-puts, then revokes the old lease (2 RPCs at discovery
    scale beats tracking gateway keepalive streams)."""

    # arealint: disable-file=WIRE001 the /v3/* routes are etcd's own gRPC-gateway API served by an EXTERNAL etcd process — no in-package server registers them by design

    def __init__(
        self,
        addr: str | None = None,
        user: str | None = None,
        password: str | None = None,
        timeout: float = 5.0,
    ):
        self._addr = addr or os.environ.get("AREAL_ETCD_ADDR", "127.0.0.1:2379")
        self._timeout = timeout
        self._lock = threading.RLock()
        self._leases: dict[str, int] = {}  # name -> lease id we attached
        # same-NAME mutations must serialize (a lost race between two
        # replace-adds revokes the lease the key just got bound to —
        # revoking a lease deletes its attached keys); one Lock per
        # distinct name ever touched, bounded at discovery scale
        self._name_locks: dict[str, threading.Lock] = {}
        self._auth_token: str | None = None
        self._user = user or os.environ.get("AREAL_ETCD_USER")
        self._password = password or os.environ.get("AREAL_ETCD_PASSWORD")
        if self._user:
            self._authenticate()

    # -- wire helpers -----------------------------------------------------
    def _authenticate(self) -> None:
        resp = self._post(
            "/v3/auth/authenticate",
            {"name": self._user, "password": self._password or ""},
            _raw=True,
        )
        self._auth_token = resp.get("token")

    def _post(self, path: str, body: dict, _raw: bool = False) -> dict:
        def do() -> dict:
            req = urllib.request.Request(
                f"http://{self._addr}{path}",
                data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json"},
                method="POST",
            )
            if self._auth_token and not _raw:
                req.add_header("Authorization", self._auth_token)
            with urllib.request.urlopen(req, timeout=self._timeout) as r:
                return json.loads(r.read() or b"{}")

        try:
            return do()
        except urllib.error.HTTPError as e:
            # etcd simple-token auth expires (default 300 s); re-auth once
            # and retry so long-lived keepalive threads don't silently drop
            # their discovery entries on 401
            if e.code == 401 and self._user and not _raw:
                self._authenticate()
                return do()
            raise

    @staticmethod
    def _b64(s: str | bytes) -> str:
        if isinstance(s, str):
            s = s.encode()
        return base64.b64encode(s).decode()

    @staticmethod
    def _unb64(s: str) -> str:
        return base64.b64decode(s).decode()

    @staticmethod
    def _prefix_end(prefix: str) -> bytes:
        """etcd range_end for a prefix scan: increment the last byte
        (carrying over trailing 0xff, per the etcd client convention)."""
        b = bytearray(prefix.encode())
        while b and b[-1] == 0xFF:
            b.pop()
        if not b:
            return b"\x00"  # scan everything
        b[-1] += 1
        return bytes(b)

    def _range(self, key: str, prefix: bool = False) -> list[tuple[str, str]]:
        body: dict = {"key": self._b64(key)}
        if prefix:
            body["range_end"] = self._b64(self._prefix_end(key))
        resp = self._post("/v3/kv/range", body)
        return [
            (self._unb64(kv["key"]), self._unb64(kv.get("value", "")))
            for kv in resp.get("kvs", [])
        ]

    def _grant(self, ttl: float) -> int:
        resp = self._post("/v3/lease/grant", {"TTL": max(1, math.ceil(ttl))})
        return int(resp["ID"])

    def _revoke(self, lease_id: int) -> None:
        try:
            self._post("/v3/lease/revoke", {"ID": lease_id})
        except (urllib.error.URLError, OSError, KeyError):
            pass  # expired or already gone

    def _name_lock(self, name: str) -> threading.Lock:
        with self._lock:
            lk = self._name_locks.get(name)
            if lk is None:
                lk = self._name_locks[name] = threading.Lock()
            return lk

    # -- contract ---------------------------------------------------------
    def add(self, name, value, replace=False, keepalive_ttl=None):
        # Every etcd RPC (grant/put/txn/revoke) runs OUTSIDE self._lock
        # (arealint LCK003): the repo lock guards only the maps. Holding
        # it across the round-trips serialized every concurrent discovery
        # op — worker registrations, keepalive re-adds, deletes — behind
        # one slow etcd call (up to 4 x timeout per add). etcd's txn is
        # what provides cross-host atomicity; the local lock never did.
        # Same-NAME mutations DO serialize (on the per-name lock): two
        # interleaved replace-adds of one name could otherwise end with
        # the key bound to lease A while B's cleanup revokes A — and a
        # lease revoke deletes the keys attached to it.
        name = name.strip("/")
        with self._name_lock(name):
            self._add_locked(name, value, replace, keepalive_ttl)

    def _add_locked(self, name, value, replace, keepalive_ttl):
        body: dict = {"key": self._b64(name), "value": self._b64(str(value))}
        lease_id: int | None = None
        if keepalive_ttl:
            lease_id = self._grant(keepalive_ttl)
            body["lease"] = lease_id
        with self._lock:
            old_lease = self._leases.pop(name, None)
            if lease_id is not None:
                self._leases[name] = lease_id
        if replace:
            self._post("/v3/kv/put", body)
        else:
            # ATOMIC create-if-absent via a txn (create_revision == 0):
            # a client-side check-then-put would race across hosts —
            # the exact multi-host deployment this backend exists for
            resp = self._post(
                "/v3/kv/txn",
                {
                    "compare": [
                        {
                            "key": body["key"],
                            "target": "CREATE",
                            "result": "EQUAL",
                            "create_revision": "0",
                        }
                    ],
                    "success": [{"request_put": body}],
                },
            )
            if not resp.get("succeeded"):
                with self._lock:
                    # pop, not del: clear_subtree takes only the repo lock
                    # and may have raced the entry away mid-add
                    if lease_id is not None:
                        self._leases.pop(name, None)
                    if old_lease is not None and name not in self._leases:
                        self._leases[name] = old_lease
                if lease_id is not None:
                    self._revoke(lease_id)
                raise NameEntryExistsError(name)
        if old_lease is not None:
            self._revoke(old_lease)

    def get(self, name):
        name = name.strip("/")
        kvs = self._range(name)
        if not kvs:
            raise NameEntryNotFoundError(name)
        return kvs[0][1]

    def _walk(self, name_root) -> list[tuple[str, str]]:
        root = name_root.strip("/")
        entries = dict(self._range(root))
        entries.update(self._range(root + "/", prefix=True))
        return sorted(entries.items())

    def find_subtree(self, name_root):
        return [k for k, _ in self._walk(name_root)]

    def get_subtree(self, name_root):
        return [v for _, v in self._walk(name_root)]

    def delete(self, name):
        name = name.strip("/")
        with self._name_lock(name):  # serialize vs a racing same-name add
            resp = self._post("/v3/kv/deleterange", {"key": self._b64(name)})
            with self._lock:
                lease = self._leases.pop(name, None)
            if lease is not None:
                self._revoke(lease)
        if int(resp.get("deleted", 0)) == 0:
            raise NameEntryNotFoundError(name)

    def clear_subtree(self, name_root):
        root = name_root.strip("/")
        self._post("/v3/kv/deleterange", {"key": self._b64(root)})
        self._post(
            "/v3/kv/deleterange",
            {
                "key": self._b64(root + "/"),
                "range_end": self._b64(self._prefix_end(root + "/")),
            },
        )
        with self._lock:
            for name in list(self._leases):
                if name == root or name.startswith(root + "/"):
                    self._leases.pop(name)


def _repo_from_env() -> "NameResolveRepo":
    """Cross-process discovery needs a shared backend: launchers/schedulers
    export AREAL_NAME_RESOLVE(=file)+AREAL_NAME_RESOLVE_ROOT so every child
    process resolves against the same tree (reference NameResolveConfig)."""
    kind = os.environ.get("AREAL_NAME_RESOLVE", "memory")
    if kind in ("nfs", "file"):
        root = os.environ.get("AREAL_NAME_RESOLVE_ROOT")
        return NfsNameResolveRepo(**({"root": root} if root else {}))
    if kind in ("etcd", "etcd3"):
        return Etcd3NameResolveRepo()
    return MemoryNameResolveRepo()


DEFAULT_REPO: NameResolveRepo = _repo_from_env()


def make_repo(type_: str = "memory", **kwargs) -> NameResolveRepo:
    if type_ == "memory":
        return MemoryNameResolveRepo()
    if type_ in ("nfs", "file"):
        return NfsNameResolveRepo(**kwargs)
    if type_ in ("etcd", "etcd3"):
        return Etcd3NameResolveRepo(**kwargs)
    raise ValueError(f"unknown name_resolve backend {type_!r}")


def reconfigure(type_: str = "memory", **kwargs) -> NameResolveRepo:
    global DEFAULT_REPO
    DEFAULT_REPO = make_repo(type_, **kwargs)
    return DEFAULT_REPO


def reconfigure_from_config(cfg) -> NameResolveRepo:
    """Apply a ``NameResolveConfig`` (cluster.name_resolve) to the process
    default repo — the reference's NameResolveConfig wiring: type selects
    the backend, nfs_record_root/etcd3_addr parameterize it."""
    t = cfg.type
    if t in ("nfs", "file"):
        return reconfigure("nfs", root=cfg.nfs_record_root)
    if t in ("etcd", "etcd3"):
        return reconfigure("etcd3", addr=cfg.etcd3_addr)
    return reconfigure(t)


# Conventional key layout (parity with reference names.py)
def rollout_server_key(experiment: str, trial: str, server_idx: int | str = "") -> str:
    base = f"{experiment}/{trial}/rollout_servers"
    return f"{base}/{server_idx}" if server_idx != "" else base


add = lambda *a, **k: DEFAULT_REPO.add(*a, **k)  # noqa: E731
get = lambda *a, **k: DEFAULT_REPO.get(*a, **k)  # noqa: E731
get_subtree = lambda *a, **k: DEFAULT_REPO.get_subtree(*a, **k)  # noqa: E731
find_subtree = lambda *a, **k: DEFAULT_REPO.find_subtree(*a, **k)  # noqa: E731
delete = lambda *a, **k: DEFAULT_REPO.delete(*a, **k)  # noqa: E731
clear_subtree = lambda *a, **k: DEFAULT_REPO.clear_subtree(*a, **k)  # noqa: E731
wait = lambda *a, **k: DEFAULT_REPO.wait(*a, **k)  # noqa: E731
keepalive = lambda *a, **k: DEFAULT_REPO.keepalive(*a, **k)  # noqa: E731
