"""Logging helpers (behavioral parity with reference areal/utils/logging.py).

Colored console logging with per-module loggers and optional file logging.
"""

from __future__ import annotations

import logging
import os
import sys

_FORMAT = "%(asctime)s.%(msecs)03d %(name)s %(levelname)s: %(message)s"
_DATE_FORMAT = "%Y%m%d-%H:%M:%S"

_LEVEL_COLORS = {
    logging.DEBUG: "\033[36m",
    logging.INFO: "\033[32m",
    logging.WARNING: "\033[33m",
    logging.ERROR: "\033[31m",
    logging.CRITICAL: "\033[41m",
}
_RESET = "\033[0m"


class _ColorFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        msg = super().format(record)
        if sys.stderr.isatty():
            color = _LEVEL_COLORS.get(record.levelno, "")
            return f"{color}{msg}{_RESET}" if color else msg
        return msg


_configured = False


def _configure_root() -> None:
    global _configured
    if _configured:
        return
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(_ColorFormatter(fmt=_FORMAT, datefmt=_DATE_FORMAT))
    root = logging.getLogger("areal_tpu")
    root.addHandler(handler)
    root.setLevel(os.environ.get("AREAL_TPU_LOG_LEVEL", "INFO").upper())
    root.propagate = False
    _configured = True


def getLogger(name: str | None = None) -> logging.Logger:
    _configure_root()
    if not name:
        return logging.getLogger("areal_tpu")
    return logging.getLogger(f"areal_tpu.{name}")


def setup_file_logging(path: str) -> None:
    """Additionally log everything to ``path`` (created with parents)."""
    _configure_root()
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    handler = logging.FileHandler(path)
    handler.setFormatter(logging.Formatter(fmt=_FORMAT, datefmt=_DATE_FORMAT))
    logging.getLogger("areal_tpu").addHandler(handler)
