"""Balanced partitioning / bin-packing used for microbatching and DP routing.

Behavioral parity with reference areal/utils/datapack.py (ffd_allocate at
:187-210, balanced_greedy_partition at :211+, min_abs_diff_partition /
partition_balanced). All functions operate on integer "sizes" (sequence
lengths / token counts) and return *index* groups so callers can gather the
underlying data.

TPU note: FFD bins are ragged; callers that feed XLA pad each bin up to a
bucketed capacity so compiled shapes stay static (see utils/data.py).
"""

from __future__ import annotations

import ctypes
import heapq
from typing import Sequence

# items below this stay on the pure-Python path (the ctypes call + array
# marshalling overhead beats C for tiny inputs)
_NATIVE_MIN_N = 64


def _native():
    from areal_tpu.native import datapack_lib

    return datapack_lib()


def _groups_from_ids(group_of, n_groups: int) -> list[list[int]]:
    groups: list[list[int]] = [[] for _ in range(n_groups)]
    for i, g in enumerate(group_of):
        groups[g].append(i)  # i ascending -> groups come out sorted
    return groups


def ffd_allocate(
    sizes: Sequence[int],
    capacity: int,
    min_groups: int = 1,
) -> list[list[int]]:
    """First-fit-decreasing bin packing.

    Packs items into the smallest number of bins (>= ``min_groups``) such that
    each bin's total size is <= ``capacity``. Raises if any single item
    exceeds ``capacity`` (fail fast at packing time, like the reference,
    rather than blowing the downstream memory budget). Returns a list of
    index lists sorted by each bin's first item index for determinism.

    Hot path (every microbatch build, utils/grid.py): large inputs run the
    C++ kernel (native/datapack.cc), an exact port; this Python body is the
    semantic reference and the fallback.
    """
    n = len(sizes)
    lib = _native() if n >= _NATIVE_MIN_N else None
    if lib is not None:
        arr = (ctypes.c_int64 * n)(*sizes)
        out = (ctypes.c_int32 * n)()
        rc = lib.ffd_group_of(arr, n, capacity, min_groups, out)
        if rc < 0:
            i = -int(rc) - 1
            raise ValueError(
                f"item {i} has size {sizes[i]} > microbatch capacity "
                f"{capacity}; raise max_tokens_per_mb or truncate the sequence"
            )
        bins = _groups_from_ids(out, int(rc))
        bins = [b for b in bins if b or len(bins) <= min_groups]
        while len(bins) < min_groups:
            bins.append([])
        return sorted(bins, key=lambda b: (b[0] if b else n))
    for i, sz in enumerate(sizes):
        if sz > capacity:
            raise ValueError(
                f"item {i} has size {sz} > microbatch capacity {capacity}; "
                "raise max_tokens_per_mb or truncate the sequence"
            )
    order = sorted(range(len(sizes)), key=lambda i: (-sizes[i], i))
    bins: list[list[int]] = [[] for _ in range(min_groups)]
    loads = [0] * min_groups
    for i in order:
        sz = sizes[i]
        placed = False
        for b in range(len(bins)):
            if loads[b] + sz <= capacity or not bins[b]:
                bins[b].append(i)
                loads[b] += sz
                placed = True
                break
        if not placed:
            bins.append([i])
            loads.append(sz)
    bins = [sorted(b) for b in bins if b or len(bins) <= min_groups]
    # Keep empty bins only to honor min_groups.
    while len(bins) < min_groups:
        bins.append([])
    return sorted(bins, key=lambda b: (b[0] if b else len(sizes)))


def balanced_greedy_partition(sizes: Sequence[int], k: int) -> list[list[int]]:
    """Greedy longest-processing-time partition into exactly ``k`` groups.

    Sort descending, always assign to the least-loaded group. Returns k index
    lists (some possibly empty if len(sizes) < k), each sorted ascending.
    """
    assert k >= 1
    n = len(sizes)
    lib = _native() if n >= _NATIVE_MIN_N else None
    if lib is not None:
        arr = (ctypes.c_int64 * n)(*sizes)
        out = (ctypes.c_int32 * n)()
        lib.lpt_group_of(arr, n, k, out)
        return _groups_from_ids(out, k)
    heap = [(0, g) for g in range(k)]
    heapq.heapify(heap)
    groups: list[list[int]] = [[] for _ in range(k)]
    for i in sorted(range(len(sizes)), key=lambda i: (-sizes[i], i)):
        load, g = heapq.heappop(heap)
        groups[g].append(i)
        heapq.heappush(heap, (load + sizes[i], g))
    return [sorted(g) for g in groups]


def min_abs_diff_partition(sizes: Sequence[int], k: int) -> list[tuple[int, int]]:
    """Partition a sequence into ``k`` *contiguous* spans minimizing the
    maximum span sum (classic linear-partition DP). Returns [start, end)
    pairs covering the sequence in order.

    Mirrors reference areal/utils/datapack.py ``min_abs_diff_partition``'s
    role: contiguous seqlen-balanced splits for DP dispatch.
    """
    n = len(sizes)
    assert 1 <= k
    if n == 0:
        return [(0, 0)] * k
    if k >= n:
        spans = [(i, i + 1) for i in range(n)]
        spans += [(n, n)] * (k - n)
        return spans
    # the O(k*n^2) DP is seconds of Python at rollout-batch n; the C port
    # (same recurrence + tie-breaking) keeps it in the microseconds
    lib = _native() if n >= _NATIVE_MIN_N else None
    if lib is not None:
        arr = (ctypes.c_int64 * n)(*sizes)
        cuts = (ctypes.c_int64 * (k + 1))()
        lib.linear_partition_cuts(arr, n, k, cuts)
        return [(int(cuts[j]), int(cuts[j + 1])) for j in range(k)]
    prefix = [0] * (n + 1)
    for i, s in enumerate(sizes):
        prefix[i + 1] = prefix[i] + s

    # dp[j][i] = minimal max-sum splitting first i items into j parts
    INF = float("inf")
    dp = [[INF] * (n + 1) for _ in range(k + 1)]
    cut = [[0] * (n + 1) for _ in range(k + 1)]
    dp[0][0] = 0
    for j in range(1, k + 1):
        for i in range(j, n + 1):
            for p in range(j - 1, i):
                cand = max(dp[j - 1][p], prefix[i] - prefix[p])
                if cand < dp[j][i]:
                    dp[j][i] = cand
                    cut[j][i] = p
    spans: list[tuple[int, int]] = []
    i = n
    for j in range(k, 0, -1):
        p = cut[j][i]
        spans.append((p, i))
        i = p
    return spans[::-1]


def partition_balanced(sizes: Sequence[int], k: int) -> list[list[int]]:
    """Contiguous balanced partition returned as index groups."""
    return [list(range(s, e)) for s, e in min_abs_diff_partition(sizes, k)]
