from areal_tpu.utils.logging import getLogger  # noqa: F401
