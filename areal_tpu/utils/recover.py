"""Checkpoint-based recovery (reference areal/utils/recover.py:30-382).

``RecoverInfo`` snapshots everything the step loop needs to resume:
last StepInfo, saver/evaluator timer states, and the dataloader position.
Recovery is checkpoint-based, not in-place elastic — the supervisor (launcher
or driver) relaunches the trial and ``RecoverHandler.load`` restores engine
state from the latest recover checkpoint, then re-syncs inference weights.

Mode policy (reference :326-382):
- "disabled"/"off": never dump, never load.
- "on": always try to load at startup (error if absent ⇒ fresh start).
- "auto": load if a recover checkpoint exists, else fresh start.

Durability (robustness layer): ``dump`` writes ``recover_info.pkl`` and
``latest`` via tmp + ``os.replace`` + fsync with an embedded sha256, and
rotates the previous consistent pair to ``*.prev`` first. ``load`` verifies
the pair (checksum, unpickle, checkpoint-path existence) and falls back to
the ``.prev`` generation when the current one is truncated, corrupt, or
dangling — a crash mid-dump can cost at most one recover interval, never
the whole trial.
"""

from __future__ import annotations

import dataclasses
import json
import os
import pickle
from typing import Any

from areal_tpu.api.config import RecoverConfig
from areal_tpu.api.io_struct import SaveLoadMeta, StepInfo
from areal_tpu.observability import catalog
from areal_tpu.utils import atomic_io
from areal_tpu.utils import logging as alog
from areal_tpu.utils.saver import Saver

logger = alog.getLogger("recover")


@dataclasses.dataclass
class RecoverInfo:
    last_step_info: StepInfo
    saver_state: dict = dataclasses.field(default_factory=dict)
    evaluator_state: dict = dataclasses.field(default_factory=dict)
    dataloader_state: dict = dataclasses.field(default_factory=dict)
    extra: dict = dataclasses.field(default_factory=dict)
    # the weight checkpoint this record pairs with. Embedding the path makes
    # the record self-contained — load() never depends on `latest` matching
    # the info file's generation ("" on legacy records: fall back to latest)
    ckpt_path: str = ""


class RecoverHandler:
    def __init__(self, config: RecoverConfig, ft_spec=None):
        self.config = config
        self.ft_spec = ft_spec
        self.saver = Saver(config, ft_spec, for_recover=True)

    # -- paths -------------------------------------------------------------
    def _root(self) -> str:
        return self.saver.save_root()

    def _info_path(self, suffix: str = "") -> str:
        return os.path.join(self._root(), "recover_info.pkl" + suffix)

    def _latest_path(self, suffix: str = "") -> str:
        return os.path.join(self._root(), "latest" + suffix)

    # -- dump --------------------------------------------------------------
    def dump(
        self,
        engine,
        step_info: StepInfo,
        saver=None,
        evaluator=None,
        dataloader=None,
        stats_logger=None,
        tokenizer=None,
        force: bool = False,
        async_: bool = False,
    ) -> str | None:
        """Dump a recover generation when a frequency trigger fires
        (``force=True`` skips the gate — the preemption emergency path).

        ``async_=True`` routes the checkpoint through
        :meth:`Saver.save_async`: the step loop pauses only for the host
        snapshot, and the (info, latest) record pair is written by the
        background thread AFTER the Orbax bytes are durable — a crash
        mid-write leaves the previous generation's records in place, so
        load() never sees a pointer to a half-written checkpoint."""
        if self.config.mode in ("disabled", "off"):
            return None
        if not force and not self.saver.freq_ctl.check(
            epochs=step_info.epoch, steps=step_info.global_step + 1
        ):
            return None
        # timer/dataloader state is captured NOW, paired with the snapshot
        info = RecoverInfo(
            last_step_info=step_info,
            saver_state=saver.state_dict() if saver else {},
            evaluator_state=evaluator.state_dict() if evaluator else {},
            dataloader_state=(
                dataloader.state_dict()
                if dataloader is not None and hasattr(dataloader, "state_dict")
                else {}
            ),
        )

        def write_records(path: str) -> None:
            info.ckpt_path = path
            os.makedirs(self._root(), exist_ok=True)
            # rotate the previous consistent pair BEFORE writing the new
            # one: if this dump crashes half-way, load() falls back to .prev
            for cur, prev in (
                (self._info_path(), self._info_path(".prev")),
                (self._latest_path(), self._latest_path(".prev")),
            ):
                if os.path.exists(cur):
                    os.replace(cur, prev)
            # checksummed + atomic (tmp + replace + fsync): a torn write
            # can never masquerade as a valid record
            atomic_io.write_checksummed(self._info_path(), pickle.dumps(info))
            atomic_io.write_checksummed(
                self._latest_path(), path.encode("utf-8")
            )
            logger.info(
                f"recover checkpoint dumped at step {step_info.global_step}"
            )

        if async_:
            return self.saver.save_async(
                engine,
                step_info.epoch,
                step_info.epoch_step,
                step_info.global_step,
                tokenizer,
                on_written=write_records,
            )
        path = self.saver.save(
            engine,
            step_info.epoch,
            step_info.epoch_step,
            step_info.global_step,
            tokenizer,
        )
        write_records(path)
        return path

    def dump_emergency(
        self,
        engine,
        step_info: StepInfo,
        saver=None,
        evaluator=None,
        dataloader=None,
        tokenizer=None,
    ) -> str | None:
        """Preemption-path dump: force-synchronous, frequency gate
        bypassed, and fully durable before returning (any in-flight async
        write joined first, Orbax staging waited out) — the last thing a
        SIGTERM'd trainer does before exiting."""
        self.saver.wait_async()
        path = self.dump(
            engine,
            step_info,
            saver=saver,
            evaluator=evaluator,
            dataloader=dataloader,
            tokenizer=tokenizer,
            force=True,
            async_=False,
        )
        wait = getattr(engine, "wait_for_save", None)
        if wait is not None:
            wait()
        return path

    # -- load --------------------------------------------------------------
    def should_load(self) -> bool:
        mode = self.config.mode
        if mode in ("disabled", "off"):
            return False
        exists = any(
            os.path.exists(self._info_path(sfx)) for sfx in ("", ".prev")
        )
        if mode == "on" and not exists:
            logger.warning("recover mode 'on' but no checkpoint found; fresh start")
        return exists

    def _read_pair(self, suffix: str) -> tuple[RecoverInfo, str] | None:
        """One (info, ckpt_path) generation, fully verified: checksum,
        unpickle, and checkpoint-directory existence. None when any of it
        is truncated, corrupt, or dangling."""
        info_path = self._info_path(suffix)
        if not os.path.exists(info_path):
            return None
        try:
            info: RecoverInfo = pickle.loads(
                atomic_io.read_checksummed(info_path)
            )
        except Exception as e:  # noqa: BLE001 — any corruption shape falls back
            logger.warning(f"recover record {info_path} unreadable: {e!r}")
            return None
        ckpt_path = getattr(info, "ckpt_path", "") or ""
        if not ckpt_path:
            # legacy record: the path lives only in `latest`
            latest = self._latest_path(suffix)
            try:
                ckpt_path = (
                    atomic_io.read_checksummed(latest).decode("utf-8").strip()
                )
            except Exception as e:  # noqa: BLE001 — missing/corrupt pointer
                logger.warning(f"latest pointer {latest} unreadable: {e!r}")
                return None
        if not os.path.exists(ckpt_path):
            logger.warning(
                f"recover record {info_path} points at missing checkpoint "
                f"{ckpt_path} (dangling)"
            )
            return None
        return info, ckpt_path

    def read_recover_info(self) -> tuple[RecoverInfo, str] | None:
        """The newest loadable (info, ckpt_path) generation, falling back
        from the current record to ``.prev`` on corruption. The fallback is
        counted in ``areal_recover_fallback_total``."""
        pair = self._read_pair("")
        if pair is not None:
            return pair
        pair = self._read_pair(".prev")
        if pair is not None:
            catalog.robustness_metrics().recover_fallbacks.inc()
            logger.warning(
                "current recover record unusable — falling back to the "
                "previous checkpoint generation"
            )
            return pair
        return None

    def load(
        self,
        engine,
        saver=None,
        evaluator=None,
        dataloader=None,
        inference_engine=None,
        weight_update_meta=None,
    ) -> RecoverInfo | None:
        if not self.should_load():
            return None
        pair = self.read_recover_info()
        if pair is None:
            logger.warning(
                "no loadable recover checkpoint (all generations corrupt "
                "or dangling); fresh start"
            )
            return None
        info, ckpt_path = pair
        engine.load(SaveLoadMeta(path=ckpt_path, weight_format="orbax", with_optim=True))
        engine.set_version(info.last_step_info.global_step + 1)
        if saver is not None and info.saver_state:
            saver.load_state_dict(info.saver_state)
        if evaluator is not None and info.evaluator_state:
            evaluator.load_state_dict(info.evaluator_state)
        if (
            dataloader is not None
            and info.dataloader_state
            and hasattr(dataloader, "load_state_dict")
        ):
            dataloader.load_state_dict(info.dataloader_state)
        # re-sync inference fleet to the restored weights (reference
        # rl_trainer.py:260-268 re-runs the weight update after recovery)
        if inference_engine is not None and weight_update_meta is not None:
            engine.update_weights(weight_update_meta)
            inference_engine.set_version(engine.get_version())
        logger.info(
            f"recovered from {ckpt_path} at step "
            f"{info.last_step_info.global_step}"
        )
        return info
