"""Checkpoint-based recovery (reference areal/utils/recover.py:30-382).

``RecoverInfo`` snapshots everything the step loop needs to resume:
last StepInfo, saver/evaluator timer states, and the dataloader position.
Recovery is checkpoint-based, not in-place elastic — the supervisor (launcher
or driver) relaunches the trial and ``RecoverHandler.load`` restores engine
state from the latest recover checkpoint, then re-syncs inference weights.

Mode policy (reference :326-382):
- "disabled"/"off": never dump, never load.
- "on": always try to load at startup (error if absent ⇒ fresh start).
- "auto": load if a recover checkpoint exists, else fresh start.
"""

from __future__ import annotations

import dataclasses
import json
import os
import pickle
from typing import Any

from areal_tpu.api.config import RecoverConfig
from areal_tpu.api.io_struct import SaveLoadMeta, StepInfo
from areal_tpu.utils import logging as alog
from areal_tpu.utils.saver import Saver

logger = alog.getLogger("recover")


@dataclasses.dataclass
class RecoverInfo:
    last_step_info: StepInfo
    saver_state: dict = dataclasses.field(default_factory=dict)
    evaluator_state: dict = dataclasses.field(default_factory=dict)
    dataloader_state: dict = dataclasses.field(default_factory=dict)
    extra: dict = dataclasses.field(default_factory=dict)


class RecoverHandler:
    def __init__(self, config: RecoverConfig, ft_spec=None):
        self.config = config
        self.ft_spec = ft_spec
        self.saver = Saver(config, ft_spec, for_recover=True)

    # -- paths -------------------------------------------------------------
    def _root(self) -> str:
        return self.saver.save_root()

    def _info_path(self) -> str:
        return os.path.join(self._root(), "recover_info.pkl")

    def _latest_path(self) -> str:
        return os.path.join(self._root(), "latest")

    # -- dump --------------------------------------------------------------
    def dump(
        self,
        engine,
        step_info: StepInfo,
        saver=None,
        evaluator=None,
        dataloader=None,
        stats_logger=None,
        tokenizer=None,
    ) -> str | None:
        if self.config.mode in ("disabled", "off"):
            return None
        if not self.saver.freq_ctl.check(
            epochs=step_info.epoch, steps=step_info.global_step + 1
        ):
            return None
        path = self.saver.save(
            engine,
            step_info.epoch,
            step_info.epoch_step,
            step_info.global_step,
            tokenizer,
        )
        info = RecoverInfo(
            last_step_info=step_info,
            saver_state=saver.state_dict() if saver else {},
            evaluator_state=evaluator.state_dict() if evaluator else {},
            dataloader_state=(
                dataloader.state_dict()
                if dataloader is not None and hasattr(dataloader, "state_dict")
                else {}
            ),
        )
        os.makedirs(self._root(), exist_ok=True)
        with open(self._info_path(), "wb") as f:
            pickle.dump(info, f)
        with open(self._latest_path(), "w") as f:
            f.write(path)
        logger.info(f"recover checkpoint dumped at step {step_info.global_step}")
        return path

    # -- load --------------------------------------------------------------
    def should_load(self) -> bool:
        mode = self.config.mode
        if mode in ("disabled", "off"):
            return False
        exists = os.path.exists(self._info_path()) and os.path.exists(
            self._latest_path()
        )
        if mode == "on" and not exists:
            logger.warning("recover mode 'on' but no checkpoint found; fresh start")
        return exists

    def load(
        self,
        engine,
        saver=None,
        evaluator=None,
        dataloader=None,
        inference_engine=None,
        weight_update_meta=None,
    ) -> RecoverInfo | None:
        if not self.should_load():
            return None
        with open(self._info_path(), "rb") as f:
            info: RecoverInfo = pickle.load(f)
        with open(self._latest_path()) as f:
            ckpt_path = f.read().strip()
        engine.load(SaveLoadMeta(path=ckpt_path, weight_format="orbax", with_optim=True))
        engine.set_version(info.last_step_info.global_step + 1)
        if saver is not None and info.saver_state:
            saver.load_state_dict(info.saver_state)
        if evaluator is not None and info.evaluator_state:
            evaluator.load_state_dict(info.evaluator_state)
        if (
            dataloader is not None
            and info.dataloader_state
            and hasattr(dataloader, "load_state_dict")
        ):
            dataloader.load_state_dict(info.dataloader_state)
        # re-sync inference fleet to the restored weights (reference
        # rl_trainer.py:260-268 re-runs the weight update after recovery)
        if inference_engine is not None and weight_update_meta is not None:
            engine.update_weights(weight_update_meta)
            inference_engine.set_version(engine.get_version())
        logger.info(
            f"recovered from {ckpt_path} at step "
            f"{info.last_step_info.global_step}"
        )
        return info
