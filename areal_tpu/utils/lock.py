"""Distributed mutual exclusion (reference utils/lock.py:8-100 role).

The reference mutexes over the torch TCPStore (atomic counter + owner
token). This framework has no always-on store process; its cross-process
fabric is the name_resolve file tree — on one host a local directory, on
the slurm tier a shared filesystem every node mounts. The lock therefore
rides the same substrate: ``O_CREAT|O_EXCL`` file creation is the atomic
primitive (POSIX guarantees it locally; NFSv3+ guarantees it for exclusive
create), the file body is the owner token, and a TTL lets waiters steal a
lease whose holder crashed without releasing (the reference's TCPStore
loses all state when the trainer dies — here the failure mode is an
orphaned file, so expiry is explicit).

Typical guarded sections: rank-0-only checkpoint directory mutations,
recover-info rewrites, shared dataset cache fills.
"""

from __future__ import annotations

import os
import random
import time
import uuid

from areal_tpu.utils import logging as alog

logger = alog.getLogger("lock")


def _default_root() -> str:
    base = os.environ.get("AREAL_NAME_RESOLVE_ROOT", "/tmp/areal_tpu")
    return os.path.join(base, "locks")


class DistributedLock:
    """File-lease mutex. Not reentrant. Safe across processes and (on a
    shared filesystem) across hosts."""

    def __init__(
        self,
        name: str,
        root: str | None = None,
        backoff: float = 0.05,
        ttl: float | None = 300.0,  # None = leases never expire
    ):
        self.root = root or _default_root()
        os.makedirs(self.root, exist_ok=True)
        self.path = os.path.join(self.root, f"{name}.lock")
        self.backoff = backoff
        self.ttl = ttl
        self.token: str | None = None

    # -- core -------------------------------------------------------------
    def acquire(self, timeout: float | None = None) -> bool:
        assert self.token is None, "lock is not reentrant"
        start = time.perf_counter()
        sleep = self.backoff
        token = f"{os.uname().nodename}:{os.getpid()}:{uuid.uuid4().hex}"
        while True:
            if self._try_create(token):
                self.token = token
                return True
            self._maybe_steal_stale()
            if timeout is not None and time.perf_counter() - start > timeout:
                return False
            time.sleep(sleep * (1.0 + 0.25 * random.random()))
            sleep = min(sleep * 1.5, 0.5)

    def release(self) -> None:
        if self.token is None:
            raise RuntimeError("lock not held by this process")
        token, self.token = self.token, None
        owner = self._read_owner()
        if owner != token:
            # our lease was stolen after expiring (ttl overrun) — whether
            # the stealer still holds it or already finished, the guarded
            # section's exclusivity was violated; surface that always
            raise RuntimeError(
                "lock lease was lost (ttl overrun and stolen); owner is "
                f"now {owner!r}"
            )
        try:
            os.unlink(self.path)
        except OSError:
            pass

    # -- internals --------------------------------------------------------
    def _try_create(self, token: str) -> bool:
        try:
            fd = os.open(self.path, os.O_CREAT | os.O_EXCL | os.O_WRONLY, 0o644)
        except FileExistsError:
            return False
        with os.fdopen(fd, "w") as f:
            f.write(token)
        return True

    def _read_owner(self) -> str | None:
        try:
            with open(self.path) as f:
                return f.read().strip()
        except OSError:
            return None

    def _maybe_steal_stale(self) -> None:
        """Break a lease whose holder died without releasing: unlink once
        the file is older than the TTL. Token-verified immediately before
        the unlink, so a fresh lease created after our staleness
        observation (old holder released, new holder acquired) is not
        destroyed — the residual read-to-unlink window is microseconds and
        only reachable after a holder already violated the TTL contract
        (holders must finish or ``refresh()`` within ttl)."""
        if self.ttl is None:
            return
        stale_owner = self._read_owner()
        if stale_owner is None:
            return  # released meanwhile
        try:
            age = time.time() - os.stat(self.path).st_mtime
        except OSError:
            return
        if age <= self.ttl:
            return
        if self._read_owner() != stale_owner:
            return  # lease turned over while we were deciding
        logger.warning(
            f"breaking stale lock {self.path} (age {age:.0f}s > "
            f"ttl {self.ttl:.0f}s, owner {stale_owner!r})"
        )
        try:
            os.unlink(self.path)
        except OSError:
            pass

    def refresh(self) -> None:
        """Long-running holders bump the lease mtime to keep it."""
        assert self.token is not None
        os.utime(self.path, None)

    def __enter__(self) -> "DistributedLock":
        self.acquire()
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        self.release()
        return False
