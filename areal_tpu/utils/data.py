"""Host-side batch containers: padded dicts <-> packed 1D, microbatching.

Behavioral parity with reference areal/utils/data.py (pack_tensor_dict
:273-324, split_padded_tensor_dict_into_mb_list :477-598, MicroBatchList
:386-476, Normalization :1154-1373) — re-designed for TPU:

- containers are dict[str, np.ndarray] on host; jax arrays only appear at the
  engine boundary.
- packed batches carry ``cu_seqlens`` (int32, [B+1]) like the reference's
  flash-attn convention, and a static ``pad_to_multiple_of`` hook so compiled
  XLA shapes come from a small bucket set (recompile avoidance — SURVEY §7.3.4).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Iterator, Sequence

import numpy as np

from areal_tpu.utils import datapack

TensorDict = dict[str, Any]

# Keys that are per-sequence (not per-token) in trajectory dicts: scalars,
# plus ragged per-sequence arrays with their OWN length axis (vision patches)
_NON_TOKEN_KEYS = (
    "rewards",
    "task_ids",
    "begin_of_trajectory",
    "seq_no_eos_mask",
    "lineage_id",
    "pixel_values",
    "pixel_counts",
    "pixel_pos_ids",
)


def is_per_token(key: str) -> bool:
    return key not in _NON_TOKEN_KEYS


def pad_sequences_to_tensors(
    trajs: Sequence[TensorDict], pad_value: float | int = 0
) -> TensorDict:
    """Stack ragged per-sequence dicts into a padded batch with attention_mask.

    Each traj maps key -> 1D array (per-token) or scalar (per-sequence).
    """
    assert len(trajs) > 0
    lens = [int(np.asarray(t["input_ids"]).shape[0]) for t in trajs]
    max_len = max(lens)
    out: TensorDict = {}
    for key in trajs[0]:
        vals = [np.asarray(t[key]) for t in trajs]
        if vals[0].ndim == 0:
            out[key] = np.stack(vals)
            continue
        # ragged per-sequence arrays (vision patches) pad to their OWN max
        # length, not the token length
        tgt = max_len if is_per_token(key) else max(v.shape[0] for v in vals)
        padded = []
        for v in vals:
            pad_width = [(0, tgt - v.shape[0])] + [(0, 0)] * (v.ndim - 1)
            padded.append(np.pad(v, pad_width, constant_values=pad_value))
        out[key] = np.stack(padded)
    mask = np.zeros((len(trajs), max_len), dtype=np.bool_)
    for i, l in enumerate(lens):
        mask[i, :l] = True
    out["attention_mask"] = mask
    return out


def concat_padded_tensor_dicts(dicts: Sequence[TensorDict]) -> TensorDict:
    """Concatenate padded batches along batch dim, re-padding to the max len."""
    assert len(dicts) > 0
    max_len = max(d["attention_mask"].shape[1] for d in dicts)
    out: TensorDict = {}
    for key in dicts[0]:
        vals = []
        ragged_max = None
        if not is_per_token(key) and np.asarray(dicts[0][key]).ndim >= 2:
            ragged_max = max(np.asarray(d[key]).shape[1] for d in dicts)
        for d in dicts:
            v = np.asarray(d[key])
            own_len = d["attention_mask"].shape[1]
            if ragged_max is not None and v.shape[1] != ragged_max:
                # ragged per-sequence arrays (vision patches) align to their
                # own max, independent of the token length
                pad_width = [(0, 0), (0, ragged_max - v.shape[1])] + [(0, 0)] * (
                    v.ndim - 2
                )
                v = np.pad(v, pad_width)
            elif (
                is_per_token(key)
                and v.ndim >= 2
                and v.shape[1] == own_len
                and own_len != max_len
            ):
                # per-token arrays share the dict's padded length; re-pad
                pad_width = [(0, 0), (0, max_len - v.shape[1])] + [(0, 0)] * (
                    v.ndim - 2
                )
                v = np.pad(v, pad_width)
            vals.append(v)
        out[key] = np.concatenate(vals, axis=0)
    return out


def batch_size(data: TensorDict) -> int:
    return int(np.asarray(data["attention_mask"]).shape[0])


def seqlens_of(data: TensorDict) -> np.ndarray:
    return np.asarray(data["attention_mask"]).sum(axis=1).astype(np.int32)


def gather_batch(data: TensorDict, indices: Sequence[int]) -> TensorDict:
    idx = np.asarray(list(indices), dtype=np.int64)
    return {k: np.asarray(v)[idx] for k, v in data.items()}


def split_batch(data: TensorDict, groups: Sequence[Sequence[int]]) -> list[TensorDict]:
    return [gather_batch(data, g) for g in groups]


def pack_tensor_dict(data: TensorDict, pad_to_multiple_of: int | None = None) -> TensorDict:
    """Padded [B, L] batch -> packed 1D [T] batch with cu_seqlens.

    Parity: reference utils/data.py pack_tensor_dict:273-324. Per-sequence
    scalar keys are kept with shape [B]. If ``pad_to_multiple_of`` is given, a
    trailing dummy region (attention_mask False) pads T up so XLA sees bucketed
    shapes; ``cu_seqlens`` then has a final padding segment only implied by
    ``pad_length``.
    """
    mask = np.asarray(data["attention_mask"]).astype(bool)
    B, L = mask.shape
    lens = mask.sum(axis=1).astype(np.int32)
    cu = np.zeros(B + 1, dtype=np.int32)
    np.cumsum(lens, out=cu[1:])
    total = int(cu[-1])
    pad = 0
    if pad_to_multiple_of:
        pad = (-total) % pad_to_multiple_of
    out: TensorDict = {}
    for key, v in data.items():
        v = np.asarray(v)
        if key == "attention_mask":
            continue
        if v.ndim >= 2 and v.shape[:2] == (B, L):
            flat = v[mask]
            if pad:
                pad_width = [(0, pad)] + [(0, 0)] * (flat.ndim - 1)
                flat = np.pad(flat, pad_width)
            out[key] = flat
        else:
            out[key] = v
    out["cu_seqlens"] = cu
    out["max_seqlen"] = int(lens.max()) if B else 0
    out["pad_length"] = pad
    return out


def unpack_sequence(packed: np.ndarray, cu_seqlens: np.ndarray) -> list[np.ndarray]:
    return [
        np.asarray(packed)[int(cu_seqlens[i]) : int(cu_seqlens[i + 1])]
        for i in range(len(cu_seqlens) - 1)
    ]


def unpack_tensor_dict(data: TensorDict) -> list[TensorDict]:
    """Packed batch -> list of per-sequence dicts (inverse of pack on trajs)."""
    cu = np.asarray(data["cu_seqlens"])
    B = len(cu) - 1
    total = int(cu[-1])
    out: list[TensorDict] = [{} for _ in range(B)]
    for key, v in data.items():
        if key in ("cu_seqlens", "max_seqlen", "pad_length"):
            continue
        v = np.asarray(v)
        # known per-sequence keys win even when B == total (all length-1 seqs)
        per_seq_known = not is_per_token(key) and v.ndim >= 1 and v.shape[0] == B
        if not per_seq_known and v.ndim >= 1 and v.shape[0] in (
            total,
            total + int(data.get("pad_length", 0)),
        ):
            for i, seq in enumerate(unpack_sequence(v, cu)):
                out[i][key] = seq
        elif v.ndim >= 1 and v.shape[0] == B:
            for i in range(B):
                out[i][key] = v[i]
    return out


@dataclasses.dataclass
class MicroBatchSpec:
    """Parity: reference api/cli_args.py MicroBatchSpec."""

    n_mbs: int = 1
    max_tokens_per_mb: int | None = None
    granularity: int = 1


@dataclasses.dataclass
class MicroBatchList:
    mbs: list[TensorDict]
    group_indices: list[list[int]]
    padded_to: list[int]

    def __len__(self) -> int:
        return len(self.mbs)

    def __iter__(self):
        return iter(self.mbs)


def round_up_to_bucket(n: int, bucket_step: int = 512) -> int:
    """Round a token count up to a power-of-two-ish bucket to bound the number
    of distinct XLA compilations (TPU-specific; no reference counterpart)."""
    if n <= bucket_step:
        return bucket_step
    # buckets: step * 2^k and step * 3 * 2^k (dense enough, few compiles)
    k = math.ceil(math.log2(n / bucket_step))
    cands = [bucket_step * (2**k), bucket_step * 3 * (2 ** max(0, k - 2))]
    cands = [c for c in cands if c >= n]
    return min(cands) if cands else bucket_step * (2**k)


def split_padded_tensor_dict_into_mb_list(
    data: TensorDict,
    mb_spec: MicroBatchSpec,
    same_groups_as: list[list[int]] | None = None,
) -> MicroBatchList:
    """FFD-balance sequences into microbatches by token count.

    Parity: reference utils/data.py:477-598. ``granularity`` keeps adjacent
    sequences together (e.g. chosen/rejected pairs for reward modeling).
    ``same_groups_as`` forces an externally-synced allocation (the reference
    all-reduces FFD solutions across DP — here the caller passes the agreed
    grouping, see engine.prepare_mb_list).
    """
    lens = seqlens_of(data)
    B = len(lens)
    g = mb_spec.granularity
    assert B % g == 0, (B, g)
    unit_sizes = [int(lens[i * g : (i + 1) * g].sum()) for i in range(B // g)]
    if same_groups_as is not None:
        unit_groups = same_groups_as
    elif mb_spec.max_tokens_per_mb:
        unit_groups = datapack.ffd_allocate(
            unit_sizes, mb_spec.max_tokens_per_mb, min_groups=mb_spec.n_mbs
        )
    else:
        unit_groups = datapack.balanced_greedy_partition(unit_sizes, mb_spec.n_mbs)
    unit_groups = [grp for grp in unit_groups if grp]
    if same_groups_as is None and len(unit_groups) < mb_spec.n_mbs <= B // g:
        # FFD packed tighter than the requested minimum mb count (needed for
        # e.g. fixed gradient-accumulation length across DP): rebalance,
        # unless doing so would break the per-mb token capacity.
        rebalanced = [
            grp
            for grp in datapack.balanced_greedy_partition(unit_sizes, mb_spec.n_mbs)
            if grp
        ]
        cap = mb_spec.max_tokens_per_mb
        if cap is None or all(
            sum(unit_sizes[u] for u in grp) <= cap for grp in rebalanced
        ):
            unit_groups = rebalanced
    groups = [[u * g + j for u in grp for j in range(g)] for grp in unit_groups]
    groups = [grp for grp in groups if grp] or [list(range(B))]
    mbs = split_batch(data, groups)
    return MicroBatchList(mbs=mbs, group_indices=groups, padded_to=[0] * len(mbs))


def roll_to_label_alignment(x: np.ndarray) -> np.ndarray:
    """Token alignment -> label alignment: out[:, t] = x[:, t+1] (wrap like
    torch.roll; wrapped entries are masked by the rolled loss mask).
    Parity: the reference's roll(shifts=-1) in trainer/ppo/actor.py:165."""
    return np.roll(np.asarray(x), shift=-1, axis=-1)


class StatefulDataLoader:
    """Batched iteration over a list-like dataset of dict rows, with a
    resumable position (reference uses torchdata's StatefulDataLoader;
    recover.py restores the epoch position from state_dict)."""

    def __init__(
        self,
        dataset,
        batch_size: int = 1,
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = True,
    ):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self._epoch = 0
        self._batch_in_epoch = 0
        if drop_last and len(dataset) < batch_size:
            raise ValueError(
                f"dataset of {len(dataset)} rows cannot fill one batch of "
                f"{batch_size} with drop_last=True"
            )

    def __len__(self) -> int:
        n = len(self.dataset) // self.batch_size
        if not self.drop_last and len(self.dataset) % self.batch_size:
            n += 1
        return n

    def _order(self) -> np.ndarray:
        idx = np.arange(len(self.dataset))
        if self.shuffle:
            np.random.default_rng(self.seed + self._epoch).shuffle(idx)
        return idx

    def __iter__(self):
        while True:  # one pass; epoch counter persists across iters
            order = self._order()
            n_batches = len(self)
            start_batch = self._batch_in_epoch
            for b in range(start_batch, n_batches):
                sel = order[b * self.batch_size : (b + 1) * self.batch_size]
                if self.drop_last and len(sel) < self.batch_size:
                    break
                self._batch_in_epoch = b + 1
                yield [self.dataset[int(i)] for i in sel]
            self._epoch += 1
            self._batch_in_epoch = 0
            return

    def state_dict(self) -> dict:
        return {"epoch": self._epoch, "batch_in_epoch": self._batch_in_epoch}

    def load_state_dict(self, state: dict) -> None:
        self._epoch = state.get("epoch", 0)
        self._batch_in_epoch = state.get("batch_in_epoch", 0)


def cycle_dataloader(loader) -> Iterator:
    """Infinite generator over a (re-iterable) dataloader.

    Parity: reference utils/data.py cycle_dataloader (used by prepare_batch's
    cached generator, workflow_executor.py:1290-1313).
    """
    while True:
        yield from loader


class Normalization:
    """Mean/std normalization over masked values, batch- or group-wise.

    Parity: reference utils/data.py Normalization:1154-1373. ``group_size``
    normalizes within consecutive groups (GRPO group-normalized advantages).
    """

    def __init__(
        self,
        mean_level: str | None = "batch",  # none|batch|group
        std_level: str | None = "batch",
        group_size: int = 1,
        eps: float = 1e-5,
        mean_leave1out: bool = False,  # RLOO: center = mean of the OTHERS
        std_unbiased: bool = False,  # Bessel n/(n-1) correction on the std
    ):
        self.mean_level = mean_level or "none"
        self.std_level = std_level or "none"
        self.group_size = group_size
        self.eps = eps
        self.mean_leave1out = mean_leave1out
        self.std_unbiased = std_unbiased

    def __call__(self, x: np.ndarray, mask: np.ndarray | None = None) -> np.ndarray:
        x = np.asarray(x, dtype=np.float64)
        if mask is None:
            mask = np.ones_like(x, dtype=bool)
        mask = np.asarray(mask, dtype=bool)

        def _masked_mean(xs, ms):
            cnt = ms.sum()
            return (xs * ms).sum() / cnt if cnt else 0.0

        def _group_slices():
            B = x.shape[0]
            assert B % self.group_size == 0, (B, self.group_size)
            return [slice(s, s + self.group_size) for s in range(0, B, self.group_size)]

        # 1. the center is selected by mean_level; std is computed around that
        #    same center (mean_level=none -> RMS around 0), matching reference
        #    semantics so e.g. Dr.GRPO's no-mean variants stay sane.
        center = np.zeros_like(x)
        if self.mean_level == "group" and self.mean_leave1out:
            # RLOO baseline (reference Normalization mean_leave1out): each
            # sample's center is the mean of its group EXCLUDING itself
            for sl in _group_slices():
                xs, ms = x[sl], mask[sl]
                tot, cnt = (xs * ms).sum(), ms.sum()
                for j in range(xs.shape[0]):
                    c = cnt - ms[j].sum()
                    center[sl][j] = ((tot - (xs[j] * ms[j]).sum()) / c) if c else 0.0
        elif self.mean_level == "group":
            for sl in _group_slices():
                center[sl] = _masked_mean(x[sl], mask[sl])
        elif self.mean_level == "batch":
            center[:] = _masked_mean(x, mask)

        denom = np.ones_like(x)
        def _masked_var(xs, ms):
            v = _masked_mean(xs, ms)
            if self.std_unbiased:
                n = ms.sum()
                if n > 1:
                    v *= n / (n - 1)
            return v

        sq = (x - center) ** 2
        if self.std_level == "group":
            for sl in _group_slices():
                denom[sl] = math.sqrt(_masked_var(sq[sl], mask[sl])) + self.eps
        elif self.std_level == "batch":
            denom[:] = math.sqrt(_masked_var(sq, mask)) + self.eps

        return (((x - center) / denom) * mask).astype(np.float32)
