"""Host/port discovery utilities (parity: reference areal/utils/network.py)."""

from __future__ import annotations

import socket
from contextlib import closing


def find_free_ports(count: int = 1, low: int = 1024, high: int = 65535) -> list[int]:
    ports: list[int] = []
    socks = []
    try:
        for _ in range(count):
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            s.bind(("", 0))
            port = s.getsockname()[1]
            socks.append(s)
            ports.append(port)
    finally:
        for s in socks:
            s.close()
    return ports


def find_free_port() -> int:
    return find_free_ports(1)[0]


def gethostip() -> str:
    with closing(socket.socket(socket.AF_INET, socket.SOCK_DGRAM)) as s:
        try:
            s.connect(("8.8.8.8", 80))
            return s.getsockname()[0]
        except OSError:
            return "127.0.0.1"


def gethostname() -> str:
    return socket.gethostname()


def http_json(
    url: str, payload=None, timeout: float = 3600.0, headers: dict | None = None
) -> dict:
    """Tiny dependency-free JSON-over-HTTP helper (control-plane RPC).
    GET when payload is None, POST otherwise; non-2xx responses with JSON
    bodies are returned as dicts (rpc_server ships structured errors)."""
    import json
    import urllib.error
    import urllib.request

    if payload is None:
        req = urllib.request.Request(url, headers=dict(headers or {}))
    else:
        req = urllib.request.Request(
            url,
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json", **(headers or {})},
            method="POST",
        )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return json.loads(r.read())
    except urllib.error.HTTPError as e:
        body = e.read()
        try:
            return json.loads(body)
        except Exception:  # noqa: BLE001
            raise e from None


def ensure_pkg_on_pythonpath(env: dict) -> dict:
    """Child processes must import areal_tpu regardless of the caller's cwd
    (the package may run from a source tree, not an installed wheel)."""
    import os

    import areal_tpu

    pkg_root = os.path.dirname(os.path.dirname(areal_tpu.__file__))
    env["PYTHONPATH"] = (
        pkg_root + os.pathsep + env["PYTHONPATH"]
        if env.get("PYTHONPATH")
        else pkg_root
    )
    return env
