"""Host/port discovery utilities (parity: reference areal/utils/network.py)."""

from __future__ import annotations

import socket
from contextlib import closing


def find_free_ports(count: int = 1, low: int = 1024, high: int = 65535) -> list[int]:
    ports: list[int] = []
    socks = []
    try:
        for _ in range(count):
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            s.bind(("", 0))
            port = s.getsockname()[1]
            socks.append(s)
            ports.append(port)
    finally:
        for s in socks:
            s.close()
    return ports


def find_free_port() -> int:
    return find_free_ports(1)[0]


def gethostip() -> str:
    with closing(socket.socket(socket.AF_INET, socket.SOCK_DGRAM)) as s:
        try:
            s.connect(("8.8.8.8", 80))
            return s.getsockname()[0]
        except OSError:
            return "127.0.0.1"


def gethostname() -> str:
    return socket.gethostname()
