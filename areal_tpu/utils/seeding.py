"""Deterministic seeding per role (parity: reference areal/utils/seeding.py).

On TPU/JAX, randomness is explicit via ``jax.random`` keys; this module seeds
python/numpy for host-side code and derives a stable per-role jax PRNG key.
"""

from __future__ import annotations

import hashlib
import random

import numpy as np

_BASE_SEED: int | None = None
_ROLE: str = ""


def set_random_seed(seed: int, role: str = "") -> None:
    global _BASE_SEED, _ROLE
    _BASE_SEED = seed
    _ROLE = role
    mixed = _mix(seed, role)
    random.seed(mixed)
    np.random.seed(mixed % (2**32))


def _mix(seed: int, role: str) -> int:
    h = hashlib.sha256(f"{seed}-{role}".encode()).digest()
    return int.from_bytes(h[:8], "little")


def get_seed() -> int:
    if _BASE_SEED is None:
        raise RuntimeError("set_random_seed() has not been called")
    return _BASE_SEED


def jax_key(stream: str = "default"):
    """Derive a stable jax PRNG key for a named stream from the global seed."""
    import jax

    return jax.random.PRNGKey(_mix(get_seed(), f"{_ROLE}/{stream}") % (2**31))
