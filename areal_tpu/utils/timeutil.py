"""Frequency-gated triggers for saver/evaluator (parity: reference
api/cli_args.py _Timer:1700-1724 + utils/timeutil.py EpochStepTimeFreqCtl).

Each of the epoch/step/seconds triggers keeps an *independent* baseline, so a
frequent time trigger cannot postpone a step-based one (reference keeps three
separate FrequencyControl instances for the same reason)."""

from __future__ import annotations

import dataclasses
import time


@dataclasses.dataclass
class FrequencyControl:
    """Fires when any of epoch/step/seconds frequency is crossed."""

    freq_epoch: int | None = None
    freq_step: int | None = None
    freq_sec: float | None = None

    def __post_init__(self):
        self._last_time = time.monotonic()
        self._last_epoch = 0
        self._last_step = 0

    def check(self, epochs: int = 0, steps: int = 0) -> bool:
        fired = False
        now = time.monotonic()
        if self.freq_epoch and epochs - self._last_epoch >= self.freq_epoch:
            fired = True
            self._last_epoch = epochs
        if self.freq_step and steps - self._last_step >= self.freq_step:
            fired = True
            self._last_step = steps
        if self.freq_sec and now - self._last_time >= self.freq_sec:
            fired = True
            self._last_time = now
        return fired

    def state_dict(self) -> dict:
        return {
            "last_time_delta": time.monotonic() - self._last_time,
            "last_epoch": self._last_epoch,
            "last_step": self._last_step,
        }

    def load_state_dict(self, state: dict) -> None:
        self._last_time = time.monotonic() - state["last_time_delta"]
        self._last_epoch = state["last_epoch"]
        self._last_step = state["last_step"]
