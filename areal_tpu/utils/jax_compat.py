"""Version-bridging shims for jax APIs the engines rely on.

Newer jax promoted several experimental APIs to the top level and renamed
kwargs; this image ships 0.4.37 where they live in their old homes. The
engines/models/kernels route through these shims so the same code runs on
both:

- ``set_mesh(mesh)``: newer ``jax.set_mesh`` context manager; on <= 0.4.x
  the ``Mesh`` itself has been the ambient-mesh context since the pjit
  era. Without this, every engine initialize dies with ``AttributeError:
  module 'jax' has no attribute 'set_mesh'``.
- ``shard_map(...)``: newer ``jax.shard_map`` (kwarg ``check_vma``); old
  home is ``jax.experimental.shard_map.shard_map`` (kwarg ``check_rep``).
- ``get_abstract_mesh()``: newer ambient-mesh query; the old equivalent is
  the resource env's physical mesh (empty mesh when no context is active,
  which callers already treat as "no mesh").
- ``with_sharding_constraint(x, spec)``: manual-axes-aware constraint.
  Old ``shard_map`` makes EVERY mesh axis manual inside the mapped body,
  and ``jax.lax.with_sharding_constraint`` there rejects any spec naming
  a manual axis at lowering time ("Axis ... is also found in
  manual_axes") — the pp_engine failure class. Newer jax only
  manualizes the mapped axes, so GSPMD constraints keep working inside
  a partially-manual region. This shim recovers that behavior on 0.4.x
  by dropping manual axes from the spec (inside a full-manual region the
  array is already a local slice, so the constraint is meaningless for
  those axes) and becoming a no-op when nothing survives. All model/
  engine code must route constraints through this shim, not
  ``jax.lax.with_sharding_constraint`` directly (arealint MSH003).
- ``jax_threefry_partitionable``: flipped on at import (the newer-jax
  default) so seeded init is identical on every mesh topology.
"""

from __future__ import annotations

import jax
from jax.sharding import PartitionSpec as _P

# Newer jax defaults the partitionable threefry lowering ON, which makes
# jax.random generation invariant to the output sharding. 0.4.x defaults
# it OFF, so ``jit(init_params, out_shardings=...)`` yields *mesh-dependent*
# initial params — the pp-vs-plain engine parity failure class. Align 0.4.x
# with the new default so the same seed gives the same params on any mesh.
if not jax.config.jax_threefry_partitionable:
    jax.config.update("jax_threefry_partitionable", True)

if hasattr(jax, "set_mesh"):
    set_mesh = jax.set_mesh
else:

    def set_mesh(mesh):
        """jax<=0.4 fallback: a Mesh is itself the ambient-mesh context."""
        return mesh


if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:
    from jax.experimental.shard_map import shard_map as _shard_map_old

    def shard_map(f, *, mesh=None, in_specs=None, out_specs=None, **kw):
        """jax<=0.4 fallback: experimental home, check_vma -> check_rep,
        and mesh=None resolved from the ambient context (the new API does
        that implicitly; the old one requires an explicit mesh)."""
        if "check_vma" in kw:
            kw["check_rep"] = kw.pop("check_vma")
        else:
            # the old replication checker has known false positives on
            # scan carries (its own error message says to turn it off);
            # the new API's varying-types system replaced it entirely, so
            # code written for the new API gets it disabled by default
            kw.setdefault("check_rep", False)
        if mesh is None:
            mesh = get_abstract_mesh()
            if mesh is not None and not mesh.shape:
                mesh = None  # empty mesh = no ambient context
        return _shard_map_old(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw
        )


if hasattr(jax.lax, "axis_size"):
    axis_size = jax.lax.axis_size
else:

    def axis_size(axis_name):
        """jax<=0.4 fallback: psum of 1 constant-folds to a python int
        inside shard_map/pmap bodies (usable as a static loop bound)."""
        return jax.lax.psum(1, axis_name)


def manual_axis_names() -> frozenset[str]:
    """Mesh axes that are MANUAL at the current trace point (bound by an
    enclosing shard_map/pmap). Empty outside any manual region."""
    if hasattr(jax.sharding, "get_abstract_mesh"):
        # newer jax: the abstract mesh knows each axis's type
        mesh = jax.sharding.get_abstract_mesh()
        manual = getattr(mesh, "manual_axes", None)
        if manual is not None:
            return frozenset(manual)
    try:
        # 0.4.x: shard_map extends the axis env with every manual axis
        from jax._src.core import get_axis_env  # noqa: PVT — pinned below

        return frozenset(get_axis_env().axis_sizes)
    except (ImportError, AttributeError):  # pragma: no cover — layout drift
        return frozenset()


def with_sharding_constraint(x, spec):
    """``jax.lax.with_sharding_constraint`` that survives manual regions:
    axes currently bound manual (old shard_map manualizes ALL mesh axes)
    are dropped from ``spec``; a fully-dropped spec is a no-op. Outside
    any mesh context the constraint is also a no-op (same contract as
    qwen's historical ``_shard`` helper)."""
    manual = manual_axis_names()
    if manual:
        def keep(entry):
            if entry is None:
                return None
            if isinstance(entry, (tuple, list)):
                kept = tuple(a for a in entry if a not in manual)
                return kept if kept else None
            return None if entry in manual else entry
        spec = _P(*(keep(e) for e in spec))
        if all(e is None for e in spec):
            return x
    try:
        # arealint: disable-next=MSH003 this IS the shim every other raw call must route through
        return jax.lax.with_sharding_constraint(x, spec)
    except (ValueError, RuntimeError):
        return x  # no ambient mesh (single-process tests, CPU smoke)


def get_abstract_mesh():
    """The ambient mesh, or an empty/None mesh outside any mesh context."""
    if hasattr(jax.sharding, "get_abstract_mesh"):
        return jax.sharding.get_abstract_mesh()
    try:
        from jax._src.mesh import thread_resources
    except ImportError:  # pragma: no cover — very old/new private layout
        return None
    env = getattr(thread_resources, "env", None)
    return getattr(env, "physical_mesh", None)
