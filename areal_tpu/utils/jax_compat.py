"""Version-bridging shims for jax APIs the engines rely on.

Newer jax promoted several experimental APIs to the top level and renamed
kwargs; this image ships 0.4.37 where they live in their old homes. The
engines/models/kernels route through these shims so the same code runs on
both:

- ``set_mesh(mesh)``: newer ``jax.set_mesh`` context manager; on <= 0.4.x
  the ``Mesh`` itself has been the ambient-mesh context since the pjit
  era. Without this, every engine initialize dies with ``AttributeError:
  module 'jax' has no attribute 'set_mesh'``.
- ``shard_map(...)``: newer ``jax.shard_map`` (kwarg ``check_vma``); old
  home is ``jax.experimental.shard_map.shard_map`` (kwarg ``check_rep``).
- ``get_abstract_mesh()``: newer ambient-mesh query; the old equivalent is
  the resource env's physical mesh (empty mesh when no context is active,
  which callers already treat as "no mesh").
"""

from __future__ import annotations

import jax

if hasattr(jax, "set_mesh"):
    set_mesh = jax.set_mesh
else:

    def set_mesh(mesh):
        """jax<=0.4 fallback: a Mesh is itself the ambient-mesh context."""
        return mesh


if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:
    from jax.experimental.shard_map import shard_map as _shard_map_old

    def shard_map(f, *, mesh=None, in_specs=None, out_specs=None, **kw):
        """jax<=0.4 fallback: experimental home, check_vma -> check_rep,
        and mesh=None resolved from the ambient context (the new API does
        that implicitly; the old one requires an explicit mesh)."""
        if "check_vma" in kw:
            kw["check_rep"] = kw.pop("check_vma")
        else:
            # the old replication checker has known false positives on
            # scan carries (its own error message says to turn it off);
            # the new API's varying-types system replaced it entirely, so
            # code written for the new API gets it disabled by default
            kw.setdefault("check_rep", False)
        if mesh is None:
            mesh = get_abstract_mesh()
            if mesh is not None and not mesh.shape:
                mesh = None  # empty mesh = no ambient context
        return _shard_map_old(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw
        )


if hasattr(jax.lax, "axis_size"):
    axis_size = jax.lax.axis_size
else:

    def axis_size(axis_name):
        """jax<=0.4 fallback: psum of 1 constant-folds to a python int
        inside shard_map/pmap bodies (usable as a static loop bound)."""
        return jax.lax.psum(1, axis_name)


def get_abstract_mesh():
    """The ambient mesh, or an empty/None mesh outside any mesh context."""
    if hasattr(jax.sharding, "get_abstract_mesh"):
        return jax.sharding.get_abstract_mesh()
    try:
        from jax._src.mesh import thread_resources
    except ImportError:  # pragma: no cover — very old/new private layout
        return None
    env = getattr(thread_resources, "env", None)
    return getattr(env, "physical_mesh", None)
