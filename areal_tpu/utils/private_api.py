"""Pinned-signature guard for private jax APIs (the arealint PVT idiom).

The repo calls several private jax internals positionally (flash
attention, megablox gmm, the paged-attention launch wrapper, and the
kernel body forked in ``ops/paged_attention_q8.py``). A jax bump can
silently reorder or extend those signatures, after which positional call
sites feed the wrong argument into the wrong parameter with no error.

Each call site declares the parameter tuple it was audited against as a
module-level ``_EXPECTED_*`` literal and verifies it via
:func:`pin_signature` at first use. Two layers of defense share that one
literal:

- at runtime, :func:`pin_signature` raises with a parameter diff before
  a drifted signature is ever called;
- at lint time, arealint PVT002 re-checks every ``_EXPECTED_*`` pin
  against the *installed* jax, so the drift surfaces during the jax bump
  itself as a lint finding.

``AUDITED_JAX`` is the version the pins were last audited against; keep
it (and pyproject's documented range) in lockstep when re-auditing.
"""

from __future__ import annotations

import inspect

AUDITED_JAX = "0.4.37"

_verified: set[tuple[int, tuple[str, ...]]] = set()


def pin_signature(obj, expected: tuple[str, ...], audited: str = AUDITED_JAX):
    """Raise unless ``obj``'s parameter names equal ``expected`` exactly.

    The FULL tuple is compared, not a prefix: an appended (defaulted)
    parameter that jax's own wrappers supply but our call sites don't must
    fail too. Verification is cached per (object, expected) pair, so
    hot-path callers pay ``inspect.signature`` once per process while a
    second call site pinning the SAME symbol against a different tuple
    still gets checked.
    """
    key = (id(obj), expected)
    if key in _verified:
        return obj
    got = tuple(inspect.signature(obj).parameters)
    if got != expected:
        missing = [p for p in expected if p not in got]
        added = [p for p in got if p not in expected]
        raise RuntimeError(
            f"private jax API {getattr(obj, '__name__', obj)!r} drifted from "
            f"the pinned signature (audited against jax {audited}): "
            f"removed {missing or 'nothing'}, added {added or 'nothing'}, "
            f"installed order {got}; re-audit every positional call site "
            "before updating the pin"
        )
    _verified.add(key)
    return obj
