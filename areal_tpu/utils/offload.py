"""Host offload/onload for colocated generation+training (task: free one
chip's HBM while the other engine runs).

Reference role: torch_memory_saver pause/resume (fsdp_engine.py:691-722,
server /release_memory_occupation). TPU-native mechanism: transfer arrays to
the host memory space via ``jax.device_put`` with a ``pinned_host`` memory
kind — the sharding layout is preserved so onload is a pure H2D copy, no
resharding. Backends without memory-kind support (CPU tests) fall back to
plain host numpy copies.
"""

from __future__ import annotations

import jax
import numpy as np

from areal_tpu.utils import logging as alog

logger = alog.getLogger("offload")


def _supports_memory_kind() -> bool:
    try:
        dev = jax.devices()[0]
        return "pinned_host" in {m.kind for m in dev.addressable_memories()}
    except Exception:  # noqa: BLE001
        return False


def offload_tree(tree):
    """Move a pytree of device arrays to host memory. Returns (host_tree,
    mode) where mode is 'pinned_host' or 'numpy' (fallback)."""
    if tree is None:
        return None, "none"
    if _supports_memory_kind():
        def to_host(x):
            if not isinstance(x, jax.Array):
                return x
            s = x.sharding.with_memory_kind("pinned_host")
            return jax.device_put(x, s)

        out = jax.tree.map(to_host, tree)
        jax.block_until_ready(out)
        return out, "pinned_host"
    # fallback: host numpy (frees device buffers once old refs drop)
    out = jax.tree.map(
        lambda x: np.asarray(jax.device_get(x)) if isinstance(x, jax.Array) else x,
        tree,
    )
    return out, "numpy"


def onload_tree(host_tree, shardings, mode: str):
    """Move an offloaded pytree back onto device with target shardings.
    ``shardings`` is a matching pytree of jax.sharding.Sharding (or None to
    reuse each array's own device sharding in pinned_host mode)."""
    if host_tree is None:
        return None
    if mode == "pinned_host" and shardings is None:
        def back(x):
            if not isinstance(x, jax.Array):
                return x
            return jax.device_put(x, x.sharding.with_memory_kind("device"))

        out = jax.tree.map(back, host_tree)
    else:
        out = jax.tree.map(
            lambda x, s: jax.device_put(x, s), host_tree, shardings
        )
    jax.block_until_ready(out)
    return out
