"""Import-from-string, used for workflows/reward fns/engine classes.

Parity: reference areal/utils/dynamic_import.py.
"""

from __future__ import annotations

import importlib


def import_from_string(path: str):
    """``"pkg.module.Attr"`` -> the attribute. Raises ImportError with a
    helpful message on failure."""
    if ":" in path:
        module_name, attr = path.split(":", 1)
    else:
        module_name, _, attr = path.rpartition(".")
    if not module_name:
        raise ImportError(f"not a dotted import path: {path!r}")
    module = importlib.import_module(module_name)
    try:
        obj = module
        for part in attr.split("."):
            obj = getattr(obj, part)
        return obj
    except AttributeError:
        raise ImportError(f"module {module_name!r} has no attribute {attr!r}")
