"""Hierarchical scoped statistics tracker with denominators.

Behavioral parity with reference areal/utils/stats_tracker.py:150-304:
- ``denominator(**masks)`` registers boolean masks;
- ``stat(denominator=..., **values)`` records masked value tensors whose
  AVG/MIN/MAX are computed w.r.t. the mask;
- ``scalar(**values)`` records plain python scalars (averaged on export);
- scopes nest via ``scope("name")`` context managers, producing keys like
  ``actor/importance_weight/avg``.

Distributed aggregation: ``export(reduce_fn=...)`` accepts an optional
callable mapping {key: (sum, count, min, max)} across hosts — on TPU this is
host-level (jax collectives are inside jit; cross-host stats ride the
controller RPC instead of a gloo group).
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict
from contextlib import contextmanager
from enum import Enum

import numpy as np


class ReduceType(Enum):
    AVG = "avg"
    SUM = "sum"
    MIN = "min"
    MAX = "max"
    SCALAR = "scalar"


_PREFIX_HOOK = None  # () -> str; see infra/workflow_context


def register_prefix_hook(fn) -> None:
    """Install the task-context scope hook (one slot; latest wins)."""
    global _PREFIX_HOOK
    _PREFIX_HOOK = fn


class StatsTracker:
    def __init__(self):
        self._lock = threading.RLock()
        self._scope = threading.local()
        self._denoms: dict[str, list[np.ndarray]] = defaultdict(list)
        # each stat entry pairs the value with the mask snapshot active at
        # record time (the denominator's most recently registered mask)
        self._stats: dict[str, list[tuple[np.ndarray, np.ndarray]]] = defaultdict(list)
        self._scalars: dict[str, list[float]] = defaultdict(list)
        self._reduce_types: dict[str, set[ReduceType]] = defaultdict(
            lambda: {ReduceType.AVG}
        )

    # -- scoping ----------------------------------------------------------
    def _prefix(self) -> str:
        prefix = getattr(self._scope, "prefix", "")
        # optional context hook (installed by infra/workflow_context at its
        # import — keeps this utils module layering-free): prepends e.g.
        # "eval-rollout/" for stats recorded inside an eval rollout task
        hook = _PREFIX_HOOK
        if hook is not None:
            ctx_scope = hook()
            if ctx_scope:
                return f"{ctx_scope}/{prefix}"
        return prefix

    @contextmanager
    def scope(self, name: str):
        # save/restore the RAW thread-local prefix: going through _prefix()
        # would bake a context-derived scope into the thread-local and
        # double-prefix (and permanently misroute) later keys
        old = getattr(self._scope, "prefix", "")
        self._scope.prefix = f"{old}{name}/"
        try:
            yield self
        finally:
            self._scope.prefix = old

    def _key(self, name: str) -> str:
        return f"{self._prefix()}{name}"

    # -- recording --------------------------------------------------------
    def denominator(self, **masks) -> None:
        with self._lock:
            for name, mask in masks.items():
                m = np.asarray(mask)
                assert m.dtype == np.bool_ or m.dtype == bool, (name, m.dtype)
                self._denoms[self._key(name)].append(m)

    def stat(
        self,
        denominator: str,
        reduce_type: ReduceType | None = None,
        **values,
    ) -> None:
        denom_key = self._key(denominator)
        with self._lock:
            if denom_key not in self._denoms:
                raise ValueError(f"unknown denominator {denominator!r}")
            mask = self._denoms[denom_key][-1]
            for name, val in values.items():
                key = self._key(name)
                self._stats[key].append((np.asarray(val, dtype=np.float64), mask))
                if reduce_type is not None:
                    self._reduce_types[key] = {reduce_type}
                elif key not in self._reduce_types:
                    self._reduce_types[key] = {
                        ReduceType.AVG,
                        ReduceType.MIN,
                        ReduceType.MAX,
                    }

    def scalar(self, **values) -> None:
        with self._lock:
            for name, val in values.items():
                self._scalars[self._key(name)].append(float(val))

    # -- export -----------------------------------------------------------
    def export(self, key: str | None = None, reset: bool = True) -> dict[str, float]:
        with self._lock:
            result: dict[str, float] = {}
            for dkey, masks in self._denoms.items():
                if key and not dkey.startswith(key):
                    continue
                total = sum(int(m.sum()) for m in masks)
                result[dkey] = float(total)
            for skey, entries in self._stats.items():
                if key and not skey.startswith(key):
                    continue
                vsum = vcnt = 0.0
                vmin, vmax = float("inf"), float("-inf")
                for val, m in entries:
                    if m.shape != val.shape:
                        m = np.broadcast_to(m, val.shape)
                    cnt = m.sum()
                    if cnt:
                        vsum += float((val * m).sum())
                        vcnt += float(cnt)
                        vmin = min(vmin, float(val[m].min()))
                        vmax = max(vmax, float(val[m].max()))
                kinds = self._reduce_types[skey]
                suffixed = len(kinds) > 1
                if vcnt > 0:
                    if ReduceType.AVG in kinds:
                        result[f"{skey}/avg" if suffixed else skey] = vsum / vcnt
                    if ReduceType.SUM in kinds:
                        result[f"{skey}/sum" if suffixed else skey] = vsum
                    if ReduceType.MIN in kinds:
                        result[f"{skey}/min" if suffixed else skey] = vmin
                    if ReduceType.MAX in kinds:
                        result[f"{skey}/max" if suffixed else skey] = vmax
            for ckey, vals in self._scalars.items():
                if key and not ckey.startswith(key):
                    continue
                if vals:
                    result[ckey] = sum(vals) / len(vals)
            if reset:
                if key is None:
                    self._denoms.clear()
                    self._stats.clear()
                    self._scalars.clear()
                else:
                    for d in (self._denoms, self._stats, self._scalars):
                        for k in [k for k in d if k.startswith(key)]:
                            del d[k]
            return result


DEFAULT_TRACKER = StatsTracker()

scope = DEFAULT_TRACKER.scope
denominator = DEFAULT_TRACKER.denominator
stat = DEFAULT_TRACKER.stat
scalar = DEFAULT_TRACKER.scalar
export = DEFAULT_TRACKER.export

@contextmanager
def record_timing(name: str):
    """Record a wall-clock scope as ``timing/<name>`` seconds (reference
    stats_tracker.record_timing used throughout rl_trainer.py)."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        DEFAULT_TRACKER.scalar(**{f"timing/{name}": time.perf_counter() - t0})


def export_all(reset: bool = True) -> dict[str, float]:
    """Export the default tracker plus every named tracker, name-prefixed."""
    out = DEFAULT_TRACKER.export(reset=reset)
    with _NAMED_LOCK:
        named = list(_NAMED.items())
    for name, tr in named:
        for k, v in tr.export(reset=reset).items():
            out[f"{name}/{k}"] = v
    return out


_NAMED: dict[str, StatsTracker] = {}
_NAMED_LOCK = threading.Lock()


def get(name: str = "") -> StatsTracker:
    """Named tracker registry (reference stats_tracker.get(scope))."""
    if not name:
        return DEFAULT_TRACKER
    with _NAMED_LOCK:
        if name not in _NAMED:
            _NAMED[name] = StatsTracker()
        return _NAMED[name]
