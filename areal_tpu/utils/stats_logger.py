"""Rank-0 metric sink: console table + TensorBoard + optional wandb
(reference areal/utils/stats_logger.py:34-160). wandb/swanlab are gated on
import availability — absent in the TPU image, the logger degrades to
console+tensorboard without error."""

from __future__ import annotations

import os
from typing import Any

from areal_tpu.api.config import StatsLoggerConfig
from areal_tpu.api.io_struct import StepInfo
from areal_tpu.utils import logging as alog

logger = alog.getLogger("stats")


class StatsLogger:
    def __init__(self, config: StatsLoggerConfig, ft_spec=None):
        self.config = config
        self.ft_spec = ft_spec
        self._tb = None
        self._wandb = None
        self._init_backends()

    def _log_dir(self) -> str:
        return os.path.join(
            self.config.fileroot,
            self.config.experiment_name or "exp",
            self.config.trial_name or "trial",
            "logs",
        )

    def _init_backends(self) -> None:
        # tensorboard.path semantics: None = disabled, "" = default log dir
        if self.config.tensorboard and self.config.tensorboard.path is not None:
            try:
                from torch.utils.tensorboard import SummaryWriter

                path = self.config.tensorboard.path or self._log_dir()
                os.makedirs(path, exist_ok=True)
                self._tb = SummaryWriter(log_dir=path)
            except Exception:  # noqa: BLE001 — optional backend
                logger.warning("tensorboard unavailable; console only")
        if self.config.wandb and self.config.wandb.mode != "disabled":
            try:
                import wandb

                w = self.config.wandb
                if w.wandb_base_url:
                    os.environ["WANDB_BASE_URL"] = w.wandb_base_url
                if w.wandb_api_key:
                    os.environ["WANDB_API_KEY"] = w.wandb_api_key
                name = w.name or self.config.trial_name
                wandb.init(
                    mode=w.mode,
                    project=w.project or self.config.experiment_name,
                    name=name,
                    group=w.group,
                    entity=w.entity,
                    job_type=w.job_type,
                    notes=w.notes,
                    tags=w.tags,
                    config=w.config,
                    id=f"{name}_{w.id_suffix}" if w.id_suffix else None,
                    # a fixed id must pair with resume: a recovered trial
                    # re-inits the same id and should append, not collide
                    resume="allow" if w.id_suffix else None,
                    dir=self._log_dir(),
                )
                self._wandb = wandb
            except Exception:  # noqa: BLE001
                logger.warning("wandb unavailable")

    def commit(
        self, epoch: int, step: int, global_step: int, data: dict[str, Any]
    ) -> None:
        flat = {k: float(v) for k, v in sorted(data.items())}
        info = StepInfo(epoch=epoch, epoch_step=step, global_step=global_step)
        lines = [
            f"Epoch {info.epoch + 1} step {info.epoch_step + 1} "
            f"(global step {info.global_step + 1})"
        ]
        width = max((len(k) for k in flat), default=10)
        for k, v in flat.items():
            lines.append(f"  {k:<{width}} {v:.6g}")
        logger.info("\n".join(lines))
        if self._tb is not None:
            for k, v in flat.items():
                self._tb.add_scalar(k, v, global_step)
            self._tb.flush()
        if self._wandb is not None:
            self._wandb.log(flat, step=global_step)

    def close(self) -> None:
        if self._tb is not None:
            self._tb.close()
        if self._wandb is not None:
            self._wandb.finish()
