"""Crash-safe file writes: tmp + fsync + os.replace, and checksummed blobs.

A recover record written with a plain ``open(...).write`` has two crash
windows: a torn write leaves a truncated file, and a crash between writing
``recover_info.pkl`` and ``latest`` leaves the pair inconsistent. Every
durable write here goes through: write to a same-directory tmp file, flush,
``os.fsync``, ``os.replace`` (atomic on POSIX), then fsync the directory so
the rename itself is durable.

Checksummed payloads add end-to-end corruption detection: the wire format
is a magic line, the payload's sha256 hex, a newline, then the raw payload.
:func:`read_checksummed` accepts legacy (unwrapped) files so existing
checkpoints keep loading.
"""

from __future__ import annotations

import hashlib
import os
import tempfile

CHECKSUM_MAGIC = b"ARLCK1\n"


class ChecksumError(ValueError):
    """Stored checksum does not match the payload (corrupt/truncated file)."""


def fsync_dir(path: str) -> None:
    """fsync a directory so a completed rename survives power loss."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return  # platform without directory fds — best effort
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def atomic_write_bytes(path: str, data: bytes, do_fsync: bool = True) -> None:
    """Write ``data`` to ``path`` so readers see the old file or the new
    one, never a torn mix. The tmp file lives in the destination directory
    (os.replace must not cross filesystems)."""
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(prefix=os.path.basename(path) + ".tmp.", dir=d)
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            f.flush()
            if do_fsync:
                os.fsync(f.fileno())
        os.replace(tmp, path)
        if do_fsync:
            fsync_dir(d)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass  # tmp may already have been renamed away
        raise


def atomic_write_text(path: str, text: str, do_fsync: bool = True) -> None:
    atomic_write_bytes(path, text.encode("utf-8"), do_fsync=do_fsync)


def checksum_wrap(payload: bytes) -> bytes:
    digest = hashlib.sha256(payload).hexdigest().encode("ascii")
    return CHECKSUM_MAGIC + digest + b"\n" + payload


def checksum_unwrap(data: bytes) -> bytes:
    """Verify and strip a checksum header. Data without the magic passes
    through unchanged (legacy files written before checksumming)."""
    if not data.startswith(CHECKSUM_MAGIC):
        return data
    head = len(CHECKSUM_MAGIC)
    digest = data[head : head + 64]
    payload = data[head + 64 + 1 :]
    if len(digest) < 64 or data[head + 64 : head + 65] != b"\n":
        raise ChecksumError("truncated checksum header")
    actual = hashlib.sha256(payload).hexdigest().encode("ascii")
    if actual != digest:
        raise ChecksumError(
            f"checksum mismatch: stored {digest[:12]!r}… != actual {actual[:12]!r}…"
        )
    return payload


def write_checksummed(path: str, payload: bytes) -> None:
    atomic_write_bytes(path, checksum_wrap(payload))


def read_checksummed(path: str) -> bytes:
    with open(path, "rb") as f:
        return checksum_unwrap(f.read())
