"""Grid packing: ragged sequences -> fixed-shape [G, L] packed rows.

The TPU-native replacement for the reference's 1D varlen packing
(areal/utils/data.py pack_tensor_dict:273-324 + FFD microbatching :477-598):
sequences are first-fit-decreasing binned into rows of a *bucketed* capacity
L so XLA sees a small set of static shapes (SURVEY §7.3.4), with
``segment_ids`` (1-based, 0 = padding) and per-segment restarting positions
driving attention masking inside the model.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import numpy as np

from areal_tpu.utils import datapack
from areal_tpu.utils.data import TensorDict, is_per_token, round_up_to_bucket, seqlens_of


@dataclasses.dataclass
class Grid:
    """One packed microbatch with fixed [G, L] shape.

    ``data`` holds per-token keys as [G, L] arrays plus per-sequence keys as
    [n_seqs] arrays; ``row_of_seq``/``col_of_seq`` locate each original
    sequence; ``seq_index`` maps local sequence order -> index in the source
    batch (for reassembling forward outputs in input order).
    """

    data: TensorDict
    n_rows: int
    row_len: int
    seq_index: list[int]
    row_of_seq: list[int]
    col_of_seq: list[int]
    seq_lens: list[int]
    # grid-local sequence order -> index in the ENGINE's input batch (set
    # by JaxTrainEngine._make_grids; ``seq_index`` only points into the
    # dict pack_grid was handed, which may be a re-packed sub-batch)
    source_index: list[int] | None = None

    @property
    def segment_ids(self) -> np.ndarray:
        return self.data["segment_ids"]

    def scatter_per_token(self, key: str, grid_values: np.ndarray) -> list[np.ndarray]:
        """[G, L] model output -> list of per-sequence 1D arrays, input order."""
        out: list[np.ndarray | None] = [None] * len(self.seq_index)
        for local, (r, c, n, src) in enumerate(
            zip(self.row_of_seq, self.col_of_seq, self.seq_lens, self.seq_index)
        ):
            out[local] = np.asarray(grid_values[r, c : c + n])
        return out  # type: ignore[return-value]


def pack_grid(
    data: TensorDict,
    row_len: int | None = None,
    n_rows: int | None = None,
    bucket_step: int = 512,
    pad_rows_to: int = 1,
) -> Grid:
    """Pack a padded [B, Lpad] batch into a [G, L] grid.

    Rows are FFD bins of capacity ``row_len`` (default: bucketed max seqlen).
    ``pad_rows_to`` rounds G up (e.g. to the data-parallel degree so the grid
    shards evenly over the mesh "data" axis).
    """
    lens = seqlens_of(data)
    B = len(lens)
    if row_len is None:
        row_len = round_up_to_bucket(int(lens.max()), bucket_step)
    assert int(lens.max()) <= row_len, (int(lens.max()), row_len)

    groups = datapack.ffd_allocate([int(x) for x in lens], row_len, min_groups=1)
    G = len(groups)
    if n_rows is not None:
        assert n_rows >= G, (n_rows, G)
        G = n_rows
    G = -(-G // pad_rows_to) * pad_rows_to

    mask = np.asarray(data["attention_mask"]).astype(bool)
    per_token_keys = [
        k
        for k, v in data.items()
        if k != "attention_mask"
        and is_per_token(k)
        and np.asarray(v).ndim >= 2
        and np.asarray(v).shape[:2] == mask.shape
    ]
    per_seq_keys = [
        k for k, v in data.items() if k not in per_token_keys and k != "attention_mask"
    ]

    out: TensorDict = {}
    for k in per_token_keys:
        v = np.asarray(data[k])
        out[k] = np.zeros((G, row_len, *v.shape[2:]), dtype=v.dtype)
    segment_ids = np.zeros((G, row_len), dtype=np.int32)
    positions = np.zeros((G, row_len), dtype=np.int32)

    seq_index: list[int] = []
    row_of_seq: list[int] = []
    col_of_seq: list[int] = []
    seq_lens: list[int] = []
    for r, grp in enumerate(groups):
        col = 0
        for j, b in enumerate(grp):
            n = int(lens[b])
            for k in per_token_keys:
                out[k][r, col : col + n] = np.asarray(data[k])[b][mask[b]]
            segment_ids[r, col : col + n] = j + 1
            positions[r, col : col + n] = np.arange(n)
            seq_index.append(b)
            row_of_seq.append(r)
            col_of_seq.append(col)
            seq_lens.append(n)
            col += n

    out["segment_ids"] = segment_ids
    out["positions"] = positions
    order = np.argsort(seq_index, kind="stable")
    for k in per_seq_keys:
        v = np.asarray(data[k])
        # reorder to local (pack) order so out[k][i] belongs to local seq i
        out[k] = v[[seq_index[i] for i in range(len(seq_index))]] if v.shape[:1] == (B,) else v
    del order
    return Grid(
        data=out,
        n_rows=G,
        row_len=row_len,
        seq_index=seq_index,
        row_of_seq=row_of_seq,
        col_of_seq=col_of_seq,
        seq_lens=seq_lens,
    )


def grid_total_tokens(lens: Sequence[int], row_len: int) -> int:
    groups = datapack.ffd_allocate([int(x) for x in lens], row_len, min_groups=1)
    return len(groups) * row_len
