"""Chrome-trace performance tracer + per-rollout session tracer.

Plays the role of reference areal/utils/perf_tracer.py (2,123 LoC): emits
catapult JSON ("traceEvents") viewable in chrome://tracing or Perfetto, plus
a JSONL of rollout-session lifecycles. Cross-async propagation uses
ContextVars, so events recorded inside workflow coroutines attach to the
right task/session (reference :28-38).

Surface:
    configure(cfg, rank=..., role=...)      process-level setup
    trace_scope(name, category=..., args=)  sync context manager
    atrace_scope(name, ...)                 async context manager
    instant(name, ...)                      point event
    counter(name, **values)                 counter track
    trace_perf(name, category=...)          decorator
    save(step=..., force=...)               periodic/final flush
    SessionTracer / trace_session("phase")  rollout lifecycle records
"""

from __future__ import annotations

import contextlib
import contextvars
import functools
import json
import os
import threading
import time
from dataclasses import dataclass, field
from enum import Enum
from typing import Any

from areal_tpu.api.config import PerfTracerConfig
from areal_tpu.utils import logging as alog

logger = alog.getLogger("perf_tracer")


class Category(str, Enum):
    COMPUTE = "compute"
    COMM = "comm"
    IO = "io"
    SCHEDULER = "scheduler"
    INSTR = "instr"


_task_id_var: contextvars.ContextVar[str | None] = contextvars.ContextVar(
    "areal_tpu_trace_task", default=None
)
_session_id_var: contextvars.ContextVar[str | None] = contextvars.ContextVar(
    "areal_tpu_trace_session", default=None
)


def set_task_context(task_id: str | None = None, session_id: str | None = None):
    if task_id is not None:
        _task_id_var.set(task_id)
    if session_id is not None:
        _session_id_var.set(session_id)


def get_task_context() -> tuple[str | None, str | None]:
    """(task_id, session_id) of the calling context — the payload that
    observability.tracecontext rides across RPC/HTTP hops."""
    return _task_id_var.get(), _session_id_var.get()


def clear_task_context() -> None:
    """Unconditionally reset both ids in the calling context. Inbound
    request handlers must call this when no trace header arrived: aiohttp
    serves a keep-alive connection's requests from one task, so stale ids
    would otherwise leak into later requests' spans."""
    _task_id_var.set(None)
    _session_id_var.set(None)


class PerfTracer:
    """Catapult JSON event collector for one process."""

    def __init__(self, config: PerfTracerConfig, rank: int = 0, role: str | None = None):
        self.config = config
        self.enabled = config.enabled
        self.rank = rank
        self.role = role
        self._events: list[dict[str, Any]] = []
        self._lock = threading.Lock()
        self._pid = os.getpid()
        self._last_save_step = -1

    # -- event emission ----------------------------------------------------
    def _ts_us(self) -> float:
        return time.perf_counter_ns() / 1e3

    def _base(self, name: str, ph: str, category) -> dict[str, Any]:
        cat = category.value if isinstance(category, Category) else (category or "instr")
        return {
            "name": name,
            "ph": ph,
            "pid": self._pid,
            "tid": threading.get_ident() % 2**31,
            "ts": self._ts_us(),
            "cat": cat,
        }

    def _push(self, ev: dict[str, Any]) -> None:
        with self._lock:
            self._events.append(ev)
            # bound memory on long runs: keep the newest max_events
            cap = getattr(self.config, "max_events", 200_000)
            if cap and len(self._events) > cap:
                del self._events[: len(self._events) - cap]

    @contextlib.contextmanager
    def trace_scope(self, name: str, category=Category.COMPUTE, args: dict | None = None):
        if not self.enabled:
            yield
            return
        ev = self._base(name, "X", category)
        if args or _task_id_var.get() or _session_id_var.get():
            ev["args"] = {**(args or {})}
            if _task_id_var.get():
                ev["args"]["task_id"] = _task_id_var.get()
            # session ids are the cross-process join key: merge_traces
            # output correlates trainer/controller/server spans on them
            if _session_id_var.get():
                ev["args"]["session_id"] = _session_id_var.get()
        t0 = self._ts_us()
        try:
            yield
        finally:
            ev["ts"] = t0
            ev["dur"] = self._ts_us() - t0
            self._push(ev)

    @contextlib.asynccontextmanager
    async def atrace_scope(self, name: str, category=Category.COMPUTE, args: dict | None = None):
        with self.trace_scope(name, category, args):
            yield

    def instant(self, name: str, category=Category.INSTR, args: dict | None = None) -> None:
        if not self.enabled:
            return
        ev = self._base(name, "i", category)
        ev["s"] = "t"
        if args:
            ev["args"] = args
        self._push(ev)

    def counter(self, name: str, **values: float) -> None:
        if not self.enabled:
            return
        ev = self._base(name, "C", Category.INSTR)
        ev["args"] = values
        self._push(ev)

    # -- persistence -------------------------------------------------------
    def _path(self) -> str:
        out = self.config.output_dir or "/tmp/areal_tpu/traces"
        os.makedirs(out, exist_ok=True)
        role = f"{self.role}_" if self.role else ""
        return os.path.join(out, f"trace_{role}rank{self.rank}.json")

    def save(self, step: int | None = None, force: bool = False) -> None:
        if not self.enabled:
            return
        if not force and step is not None:
            if step - self._last_save_step < max(1, self.config.save_freq_steps):
                return
            self._last_save_step = step
        with self._lock:
            events = list(self._events)
        with open(self._path(), "w") as f:
            json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()


@dataclass
class SessionRecord:
    """Lifecycle of one rollout episode (reference SessionTracer :920-1125)."""

    session_id: str
    start_ts: float = field(default_factory=time.time)
    phases: list[dict[str, Any]] = field(default_factory=list)
    status: str | None = None  # accepted | rejected
    end_ts: float | None = None


class SessionTracer:
    def __init__(
        self,
        output_dir: str | None = None,
        enabled: bool = True,
        flush_threshold: int = 1,
    ):
        self.enabled = enabled
        self.output_dir = output_dir or "/tmp/areal_tpu/traces"
        # finalized records buffer until this many are ready (reference
        # SessionTracerConfig.flush_threshold); <=0 falls back to 1
        self.flush_threshold = max(1, flush_threshold)
        self._records: dict[str, SessionRecord] = {}
        self._done: list[dict] = []
        self._lock = threading.Lock()

    def start_session(self, session_id: str) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._records[session_id] = SessionRecord(session_id)
        _session_id_var.set(session_id)

    @contextlib.contextmanager
    def phase(self, name: str, session_id: str | None = None):
        sid = session_id or _session_id_var.get()
        t0 = time.time()
        try:
            yield
        finally:
            if self.enabled and sid is not None:
                with self._lock:
                    rec = self._records.get(sid)
                    if rec is not None:
                        rec.phases.append(
                            {"name": name, "start": t0, "dur": time.time() - t0}
                        )

    def finalize(self, session_id: str, status: str) -> None:
        if not self.enabled:
            return
        with self._lock:
            rec = self._records.pop(session_id, None)
            if rec is None:
                return
            rec.status = status
            rec.end_ts = time.time()
            self._done.append(
                {
                    "session_id": rec.session_id,
                    "start": rec.start_ts,
                    "end": rec.end_ts,
                    "status": rec.status,
                    "phases": rec.phases,
                }
            )
            ready = len(self._done) >= self.flush_threshold
        if ready:
            self.flush()

    def flush(self) -> None:
        """Write buffered finalized records to sessions.jsonl."""
        with self._lock:
            done, self._done = self._done, []
        if not done:
            return
        os.makedirs(self.output_dir, exist_ok=True)
        path = os.path.join(self.output_dir, "sessions.jsonl")
        with open(path, "a") as f:
            for d in done:
                f.write(json.dumps(d) + "\n")


# ---------------------------------------------------------------------------
# module-level default tracer (reference module functions :1858-1940)
# ---------------------------------------------------------------------------

_TRACER = PerfTracer(PerfTracerConfig(enabled=False))
_SESSIONS = SessionTracer(enabled=False)


def configure(config: PerfTracerConfig, rank: int = 0, role: str | None = None) -> None:
    global _TRACER, _SESSIONS
    _TRACER = PerfTracer(config, rank=rank, role=role)
    # session tracing follows its own sub-config when given (reference
    # SessionTracerConfig), else the perf tracer's enabled flag with
    # per-record writes (the pre-knob behavior)
    sess = getattr(config, "session_tracer", None)
    _SESSIONS = SessionTracer(
        config.output_dir,
        enabled=sess.enabled if sess is not None else config.enabled,
        flush_threshold=sess.flush_threshold if sess is not None else 1,
    )


# on-demand device profiling state: one jax.profiler trace at a time per
# process (the profiler itself is a process-global); each capture gets its
# own timestamped dir so postmortem can link individual sessions
_PROFILE_LOCK = threading.Lock()
_PROFILE_DIR: str | None = None


def default_profile_root(output_dir: str | None = None) -> str:
    return os.path.join(
        output_dir or _TRACER.config.output_dir or "/tmp/areal_tpu/traces",
        "xprof",
    )


def start_device_profile(output_dir: str | None = None) -> str:
    """Begin a detailed XLA device profile (jax.profiler trace; view in
    TensorBoard/XProf). Returns the capture dir. Raises RuntimeError when
    a profile is already running (one at a time per process — the HTTP
    endpoint turns this into a 409). Reference knob:
    PerfTracerConfig.profile_steps."""
    global _PROFILE_DIR
    import jax

    with _PROFILE_LOCK:
        if _PROFILE_DIR is not None:
            raise RuntimeError(
                f"device profile already active at {_PROFILE_DIR}"
            )
        d = os.path.join(
            default_profile_root(output_dir),
            f"profile_{int(time.time() * 1000)}",
        )
        os.makedirs(d, exist_ok=True)
        jax.profiler.start_trace(d)
        _PROFILE_DIR = d
    return d


def stop_device_profile(only_dir: str | None = None) -> str | None:
    """End the active capture; returns its dir (None if none active).
    ``only_dir`` stops the capture only if it is still the active one —
    the guard profile_for's background timer needs so a stale timer from
    an early-stopped capture can never truncate a newer unrelated one."""
    global _PROFILE_DIR
    import jax

    with _PROFILE_LOCK:
        if _PROFILE_DIR is None:
            return None
        if only_dir is not None and _PROFILE_DIR != only_dir:
            return None
        try:
            jax.profiler.stop_trace()
        finally:
            d, _PROFILE_DIR = _PROFILE_DIR, None
    return d


def device_profile_active() -> str | None:
    """The active capture's dir, or None."""
    with _PROFILE_LOCK:
        return _PROFILE_DIR


def profile_for(duration_s: float, output_dir: str | None = None) -> str:
    """Start a capture and stop it after ``duration_s`` on a background
    timer thread — the POST /debug/profile implementation. Returns the
    capture dir immediately; the xplane/trace files land at stop time."""
    d = start_device_profile(output_dir)

    def _stop():
        time.sleep(max(0.0, duration_s))
        try:
            stop_device_profile(only_dir=d)
        except Exception:  # noqa: BLE001 — a failed stop must not kill
            # the timer thread silently holding the active slot
            logger.exception("device-profile stop failed")

    threading.Thread(
        target=_stop, name="device-profile-stop", daemon=True
    ).start()
    return d


def get_tracer() -> PerfTracer:
    return _TRACER


def get_session_tracer() -> SessionTracer:
    return _SESSIONS


def trace_scope(name: str, category=Category.COMPUTE, args: dict | None = None):
    return _TRACER.trace_scope(name, category, args)


def atrace_scope(name: str, category=Category.COMPUTE, args: dict | None = None):
    return _TRACER.atrace_scope(name, category, args)


def instant(name: str, category=Category.INSTR, args: dict | None = None) -> None:
    _TRACER.instant(name, category, args)


def counter(name: str, **values: float) -> None:
    _TRACER.counter(name, **values)


def save(step: int | None = None, force: bool = False) -> None:
    _TRACER.save(step=step, force=force)
    _SESSIONS.flush()  # buffered session records ride the same cadence


def trace_perf(name: str, category=Category.COMPUTE):
    """Decorator tracing every call of a function (sync or async)."""

    def deco(fn):
        if _is_coroutine_fn(fn):

            @functools.wraps(fn)
            async def awrapper(*a, **kw):
                with _TRACER.trace_scope(name, category):
                    return await fn(*a, **kw)

            return awrapper

        @functools.wraps(fn)
        def wrapper(*a, **kw):
            with _TRACER.trace_scope(name, category):
                return fn(*a, **kw)

        return wrapper

    return deco


def trace_session(phase_name: str):
    """Decorator recording a session phase (reference @trace_session use in
    workflow/rlvr.py:77,124)."""

    def deco(fn):
        if _is_coroutine_fn(fn):

            @functools.wraps(fn)
            async def awrapper(*a, **kw):
                with _SESSIONS.phase(phase_name):
                    return await fn(*a, **kw)

            return awrapper

        @functools.wraps(fn)
        def wrapper(*a, **kw):
            with _SESSIONS.phase(phase_name):
                return fn(*a, **kw)

        return wrapper

    return deco


def _is_coroutine_fn(fn) -> bool:
    import asyncio

    return asyncio.iscoroutinefunction(fn)


def merge_traces(paths: list[str], out_path: str) -> None:
    """Merge per-rank trace files into one (reference
    tools/perf_trace_converter.py role). pids are remapped per source file so
    ranks appear as separate process tracks."""
    merged: list[dict[str, Any]] = []
    for i, p in enumerate(paths):
        with open(p) as f:
            data = json.load(f)
        for ev in data.get("traceEvents", []):
            ev = dict(ev)
            ev["pid"] = i
            merged.append(ev)
        merged.append(
            {
                "name": "process_name",
                "ph": "M",
                "pid": i,
                "args": {"name": os.path.basename(p)},
            }
        )
    with open(out_path, "w") as f:
        json.dump({"traceEvents": merged, "displayTimeUnit": "ms"}, f)
