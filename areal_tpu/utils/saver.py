"""Timer-gated checkpoint saver + evaluator (reference areal/utils/saver.py
:1-185, evaluator.py:1-35).

Two save modes (docs/fault_tolerance.md "Async checkpointing"):

- ``save`` blocks for the full write (Orbax stages device arrays before
  returning, so the step loop pays D2H + any previous save's tail).
- ``save_async`` blocks ONLY for a host snapshot of params/optimizer
  state, then writes Orbax on a background thread — periodic recover
  dumps stop pausing the step loop. One write in flight at a time; the
  ``on_written`` callback runs after the bytes are durable (the
  RecoverHandler writes its info records there, so a crash mid-write can
  never leave a record pointing at a half-written checkpoint — the
  ``.prev`` fallback generation stays loadable throughout). Emergency
  (preemption) dumps force the sync path.

Step-loop pause per save lands in ``areal_ckpt_save_seconds{mode}``.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable

from areal_tpu.api.config import EvaluatorConfig, SaverConfig
from areal_tpu.api.io_struct import SaveLoadMeta
from areal_tpu.observability import catalog
from areal_tpu.utils import logging as alog
from areal_tpu.utils.timeutil import FrequencyControl

logger = alog.getLogger("saver")


class Saver:
    def __init__(self, config: SaverConfig, ft_spec, for_recover: bool = False):
        self.config = config
        self.ft_spec = ft_spec
        self.for_recover = for_recover
        self.freq_ctl = FrequencyControl(
            freq_epoch=config.freq_epochs,
            freq_step=config.freq_steps,
            freq_sec=config.freq_secs,
        )
        self._metrics = catalog.preemption_metrics()
        self._async_thread: threading.Thread | None = None
        # written by the background writer, consumed by wait_async
        self._async_lock = threading.Lock()
        self._async_exc: BaseException | None = None

    def save_root(self) -> str:
        sub = "recover" if self.for_recover else "checkpoints"
        return os.path.join(
            self.config.fileroot,
            self.config.experiment_name or "exp",
            self.config.trial_name or "trial",
            sub,
        )

    def maybe_save(
        self, engine, epoch: int, step: int, global_step: int, tokenizer=None
    ) -> str | None:
        """Save when a frequency trigger fires; returns the path if saved."""
        if not self.freq_ctl.check(epochs=epoch, steps=global_step + 1):
            return None
        return self.save(engine, epoch, step, global_step, tokenizer)

    def _ckpt_path(self, epoch: int, step: int, global_step: int) -> str:
        name = f"epoch{epoch}epochstep{step}globalstep{global_step}"
        return os.path.join(self.save_root(), name)

    def save(
        self, engine, epoch: int, step: int, global_step: int, tokenizer=None
    ) -> str:
        # a still-running async write must land first: Orbax directories
        # are not versioned per-save here, and the emergency path relies
        # on "save returned == bytes durable"
        self.wait_async()
        t0 = time.monotonic()
        path = self._ckpt_path(epoch, step, global_step)
        os.makedirs(path, exist_ok=True)
        meta = SaveLoadMeta(
            path=path,
            weight_format="orbax" if self.for_recover else "hf",
            with_optim=self.for_recover,
            tokenizer=tokenizer,
        )
        engine.save(meta)
        # the sync pause covers the whole engine.save call (for orbax that
        # includes staging; the background tail, if any, is orbax's own)
        self._metrics.ckpt_save_seconds.labels(mode="sync").observe(
            time.monotonic() - t0
        )
        logger.info(f"saved {'recover ' if self.for_recover else ''}ckpt to {path}")
        return path

    # -- async path (docs/fault_tolerance.md) ------------------------------
    def save_async(
        self,
        engine,
        epoch: int,
        step: int,
        global_step: int,
        tokenizer=None,
        on_written: Callable[[str], None] | None = None,
    ) -> str:
        """Snapshot-to-host now, write Orbax on a background thread.

        Blocks only for the host snapshot (the ``mode="async"`` pause
        observation) plus any previous async write still in flight.
        Engines without the snapshot/write split (and the HF-format
        checkpoint saver) fall back to the sync path — ``on_written``
        fires either way once bytes are durable."""
        snap_fn = getattr(engine, "snapshot_for_save", None)
        write_fn = getattr(engine, "write_snapshot", None)
        if snap_fn is None or write_fn is None or not self.for_recover:
            path = self.save(engine, epoch, step, global_step, tokenizer)
            if on_written is not None:
                on_written(path)
            return path
        self.wait_async()  # one write in flight; also surfaces its error
        t0 = time.monotonic()
        snapshot = snap_fn(with_optim=True)
        self._metrics.ckpt_save_seconds.labels(mode="async").observe(
            time.monotonic() - t0
        )
        path = self._ckpt_path(epoch, step, global_step)
        os.makedirs(path, exist_ok=True)

        def writer():
            try:
                write_fn(snapshot, path)
                logger.info(f"async recover ckpt written to {path}")
                if on_written is not None:
                    on_written(path)
            except BaseException as e:  # noqa: BLE001 — surfaced on the
                # next wait_async/save; a failed write must not be silent
                logger.exception("async checkpoint write failed")
                with self._async_lock:
                    self._async_exc = e

        self._async_thread = threading.Thread(
            target=writer, daemon=True, name="saver-async-write"
        )
        self._async_thread.start()
        return path

    def wait_async(self, timeout: float | None = None) -> None:
        """Join any in-flight async write; re-raise its failure. The
        emergency-dump path calls this first so a preemption never races
        a half-written periodic dump."""
        t = self._async_thread
        if t is not None:
            t.join(timeout)
            if not t.is_alive():
                self._async_thread = None
        with self._async_lock:
            exc, self._async_exc = self._async_exc, None
        if exc is not None:
            raise RuntimeError("async checkpoint write failed") from exc

    def state_dict(self) -> dict:
        return self.freq_ctl.state_dict()

    def load_state_dict(self, state: dict) -> None:
        self.freq_ctl.load_state_dict(state)


class Evaluator:
    """Frequency-gated evaluation trigger (reference utils/evaluator.py)."""

    def __init__(self, config: EvaluatorConfig, ft_spec):
        self.config = config
        self.ft_spec = ft_spec
        self.freq_ctl = FrequencyControl(
            freq_epoch=config.freq_epochs,
            freq_step=config.freq_steps,
            freq_sec=config.freq_secs,
        )

    def maybe_evaluate(self, epoch: int, global_step: int, evaluate_fn) -> bool:
        if not self.freq_ctl.check(epochs=epoch, steps=global_step + 1):
            return False
        evaluate_fn()
        return True

    def state_dict(self) -> dict:
        return self.freq_ctl.state_dict()

    def load_state_dict(self, state: dict) -> None:
        self.freq_ctl.load_state_dict(state)
