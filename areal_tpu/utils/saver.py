"""Timer-gated checkpoint saver + evaluator (reference areal/utils/saver.py
:1-185, evaluator.py:1-35). Orbax handles async staging TPU-side — ``save``
can return before bytes hit disk; ``wait_for_staging`` blocks before params
mutate (reference async_checkpoint.py role)."""

from __future__ import annotations

import os

from areal_tpu.api.config import EvaluatorConfig, SaverConfig
from areal_tpu.api.io_struct import SaveLoadMeta
from areal_tpu.utils import logging as alog
from areal_tpu.utils.timeutil import FrequencyControl

logger = alog.getLogger("saver")


class Saver:
    def __init__(self, config: SaverConfig, ft_spec, for_recover: bool = False):
        self.config = config
        self.ft_spec = ft_spec
        self.for_recover = for_recover
        self.freq_ctl = FrequencyControl(
            freq_epoch=config.freq_epochs,
            freq_step=config.freq_steps,
            freq_sec=config.freq_secs,
        )

    def save_root(self) -> str:
        sub = "recover" if self.for_recover else "checkpoints"
        return os.path.join(
            self.config.fileroot,
            self.config.experiment_name or "exp",
            self.config.trial_name or "trial",
            sub,
        )

    def maybe_save(
        self, engine, epoch: int, step: int, global_step: int, tokenizer=None
    ) -> str | None:
        """Save when a frequency trigger fires; returns the path if saved."""
        if not self.freq_ctl.check(epochs=epoch, steps=global_step + 1):
            return None
        return self.save(engine, epoch, step, global_step, tokenizer)

    def save(
        self, engine, epoch: int, step: int, global_step: int, tokenizer=None
    ) -> str:
        name = f"epoch{epoch}epochstep{step}globalstep{global_step}"
        path = os.path.join(self.save_root(), name)
        os.makedirs(path, exist_ok=True)
        meta = SaveLoadMeta(
            path=path,
            weight_format="orbax" if self.for_recover else "hf",
            with_optim=self.for_recover,
            tokenizer=tokenizer,
        )
        engine.save(meta)
        logger.info(f"saved {'recover ' if self.for_recover else ''}ckpt to {path}")
        return path

    def state_dict(self) -> dict:
        return self.freq_ctl.state_dict()

    def load_state_dict(self, state: dict) -> None:
        self.freq_ctl.load_state_dict(state)


class Evaluator:
    """Frequency-gated evaluation trigger (reference utils/evaluator.py)."""

    def __init__(self, config: EvaluatorConfig, ft_spec):
        self.config = config
        self.ft_spec = ft_spec
        self.freq_ctl = FrequencyControl(
            freq_epoch=config.freq_epochs,
            freq_step=config.freq_steps,
            freq_sec=config.freq_secs,
        )

    def maybe_evaluate(self, epoch: int, global_step: int, evaluate_fn) -> bool:
        if not self.freq_ctl.check(epochs=epoch, steps=global_step + 1):
            return False
        evaluate_fn()
        return True

    def state_dict(self) -> dict:
        return self.freq_ctl.state_dict()

    def load_state_dict(self, state: dict) -> None:
        self.freq_ctl.load_state_dict(state)
