"""Fleet postmortem: merge flight recorders + request timelines into one
Perfetto trace.

When a replica wedges (watchdog, circuit trip, supervision eviction) the
question is always *what was the fleet doing at that moment*. Every
process keeps a bounded flight-recorder ring (observability/timeline.py)
exposed at ``/debug/flight`` — inference servers additionally attach their
recently completed request timelines — and wedge/SIGTERM escalations dump
the same payload to disk via atomic_io. This tool scrapes live endpoints
and/or reads dump files, converts each process into catapult
``traceEvents`` (flight events as instants, timeline stages as spans,
correlated across processes by their ``x-areal-trace`` task/session ids in
``args``), and merges everything through
:mod:`areal_tpu.tools.perf_trace_converter` into ONE trace loadable in
chrome://tracing / Perfetto.

Usage:
    python -m areal_tpu.tools.postmortem --targets host:port,host:port \
        [--files dump1.json ...] [-o incident_trace.json] [--timelines N]
"""

from __future__ import annotations

import argparse
import json
import tempfile
import urllib.request
from pathlib import Path

from areal_tpu.observability.lineage import lineage_to_trace_events
from areal_tpu.observability.timeline import (
    flight_to_trace_events,
    timelines_to_trace_events,
)
from areal_tpu.tools import perf_trace_converter
from areal_tpu.utils import logging as alog

logger = alog.getLogger("postmortem")


def scrape_flight(
    target: str, timeout: float = 5.0, n_timelines: int = 256
) -> dict | None:
    """GET one process's /debug/flight payload; None when unreachable
    (a wedged process may only have its on-disk dump)."""
    url = f"http://{target}/debug/flight?timelines={n_timelines}"
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return json.loads(r.read().decode())
    except Exception as e:  # noqa: BLE001 — a dead target must not kill
        # the postmortem of the rest of the fleet
        logger.warning(f"scrape of {target} failed: {e!r}")
        return None


def snapshot_to_events(snap: dict) -> list[dict]:
    """One process's /debug/flight payload (or dump file) -> traceEvents.

    ``_dup_flight_ring`` (set by :func:`dedup_shared_rings`) suppresses the
    flight events while keeping the timelines: colocated replicas share one
    process-global ring, and merging it once per scraped port would show
    every admission-reject/eviction/commit twice.

    Trajectory-lineage dumps (observability/lineage.py; recognized by
    their ``lineage_records`` key) convert to per-trajectory spans whose
    ``args.task_id`` joins the serving-side request timelines — the merged
    trace then reads generate -> journal -> consume -> update per trace id."""
    if "lineage_records" in snap:
        return lineage_to_trace_events(snap)
    events = [] if snap.get("_dup_flight_ring") else flight_to_trace_events(snap)
    events.extend(timelines_to_trace_events(snap.get("timelines", [])))
    return events


def dedup_shared_rings(snapshots: list[tuple[str, dict]]) -> None:
    """Mark duplicate flight rings in place. Two snapshots are the same
    process's ring when their pids match and they share any recorded event
    (same seq AND same wall-clock stamp — one `record()` call). That covers
    both colocated replicas serving one process-global ring from two ports
    (LocalFleet) and a process that is scraped live AND read back from its
    wedge/SIGTERM dump file. Each duplicate still contributes its own
    timelines; only its flight events are suppressed."""
    # one entry per distinct process: (pid, union of member signatures,
    # the currently unsuppressed snapshot). The union keeps the group
    # matchable by EVERY later member (a live scrape, a wedge dump, and a
    # sigterm dump of one process overlap pairwise but not identically)
    kept: list[tuple[int, set[tuple], dict]] = []
    for label, snap in snapshots:
        pid = snap.get("pid")
        sig = {
            (e.get("seq"), e.get("ts")) for e in snap.get("events", [])
        }
        matches = [
            i
            for i, (k_pid, k_sig, _s) in enumerate(kept)
            if k_pid == pid and (k_sig & sig)
        ]
        if not matches:
            kept.append((pid, sig, snap))
            continue
        # a snapshot can BRIDGE previously disjoint groups (an old wedge
        # dump and a post-rotation live scrape, connected by a sigterm
        # dump covering both): merge every matched group, keep exactly
        # the largest member unsuppressed
        union = set(sig)
        candidates = []
        for i in matches:
            union |= kept[i][1]
            candidates.append(kept[i][2])
        candidates.append(snap)  # last: ties keep the earliest member
        best = max(candidates, key=lambda s: len(s.get("events", [])))
        for s in candidates:
            if s is best:
                s.pop("_dup_flight_ring", None)
            else:
                s["_dup_flight_ring"] = True
        for i in reversed(matches):
            del kept[i]
        kept.append((pid, union, best))
        logger.info(f"{label}: flight ring already merged (pid {pid})")


def build_incident_trace(
    snapshots: list[tuple[str, dict]], output: str | Path
) -> Path:
    """Write per-process catapult files named ``{role}-r{idx}.json`` (the
    rank/role scheme perf_trace_converter parses) and merge them into one
    trace at ``output``."""
    if not snapshots:
        raise ValueError("no flight snapshots to merge")
    with tempfile.TemporaryDirectory(prefix="areal_postmortem_") as td:
        tdir = Path(td)
        for idx, (label, snap) in enumerate(snapshots):
            role = str(snap.get("role") or label or "proc").replace("/", "_")
            # keep only [A-Za-z_] so the converter's role regex matches
            role = "".join(c if c.isalpha() or c == "_" else "_" for c in role)
            path = tdir / f"{role}-r{idx}.json"
            path.write_text(
                json.dumps({"traceEvents": snapshot_to_events(snap)})
            )
        return perf_trace_converter.convert(tdir, output)


def discover_profile_sessions(roots: list[str]) -> list[str]:
    """Find jax.profiler capture session dirs (the dirs holding
    ``plugins/profile/<ts>/*.xplane.pb``) under the given roots. Each
    on-demand capture (``POST /debug/profile``, the trainer's SIGUSR2 /
    profile_steps path) writes one timestamped dir under the perf-tracer
    output root; the incident trace links them so the detailed device
    view sits next to the merged host-side timeline."""
    found: set[str] = set()
    for root in roots:
        p = Path(root)
        if not p.is_dir():
            continue
        for xplane in p.rglob("*.xplane.pb"):
            # .../<capture>/plugins/profile/<session>/<host>.xplane.pb
            session = xplane.parent
            capture = session.parent.parent.parent
            found.add(str(capture if capture != p else session))
    return sorted(found)


def link_device_profiles(trace_path: str | Path, profile_dirs: list[str]) -> None:
    """Stamp capture-dir pointers into the merged trace's ``metadata``
    (catapult tolerates extra top-level keys), so the one incident
    artifact also says WHERE the loadable jax.profiler traces live."""
    p = Path(trace_path)
    data = json.loads(p.read_text())
    data.setdefault("metadata", {})["device_profiles"] = list(profile_dirs)
    p.write_text(json.dumps(data))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument(
        "--targets",
        default="",
        help="comma-separated host:port /debug/flight endpoints",
    )
    p.add_argument(
        "--files",
        nargs="*",
        default=[],
        help="flight dump files (wedge/SIGTERM dumps) and trajectory "
        "lineage dumps (lineage_*.json) to include",
    )
    p.add_argument("-o", "--output", default="incident_trace.json")
    p.add_argument(
        "--timelines",
        type=int,
        default=256,
        help="recent request timelines to pull per target",
    )
    p.add_argument(
        "--profile-dirs",
        nargs="*",
        default=None,
        help="roots to scan for jax.profiler captures (default: the "
        "perf-tracer xprof root); found sessions are linked into the "
        "merged trace's metadata",
    )
    p.add_argument("--timeout", type=float, default=5.0)
    args = p.parse_args(argv)

    snapshots: list[tuple[str, dict]] = []
    for target in [t for t in args.targets.split(",") if t]:
        snap = scrape_flight(
            target, timeout=args.timeout, n_timelines=args.timelines
        )
        if snap is not None:
            snapshots.append((target, snap))
    for f in args.files:
        try:
            snapshots.append((Path(f).stem, json.loads(Path(f).read_text())))
        except (OSError, ValueError) as e:
            logger.warning(f"skipping dump {f}: {e!r}")
    dedup_shared_rings(snapshots)
    if not snapshots:
        print("no reachable targets and no readable dumps")
        return 1
    out = build_incident_trace(snapshots, args.output)
    if args.profile_dirs is None:
        from areal_tpu.utils.perf_tracer import default_profile_root

        roots = [default_profile_root()]
    else:
        roots = list(args.profile_dirs)
    profiles = discover_profile_sessions(roots)
    if profiles:
        link_device_profiles(out, profiles)
    n_ev = sum(
        len(s.get("events", [])) + len(s.get("timelines", []))
        for _, s in snapshots
    )
    print(
        f"wrote {out} ({len(snapshots)} processes, "
        f"{n_ev} flight events + timelines)"
    )
    for d in profiles:
        print(f"device profile: {d}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
