"""Weight-sync microbenchmark: staged bytes/s and stage-vs-pause split.

The hardware probe phase of ``bench.py`` needs a TPU and a 90 s budget; on
flaky hosts it times out and reports nothing. This tool measures the
zero-pause weight-sync protocol (docs/weight_sync.md) end-to-end on CPU in
a few seconds: an in-process multi-replica fleet serves a continuous
generation load while full streamed updates run, and the report splits

  - ``stage_secs``   begin -> last bucket staged (generation RUNNING)
  - ``pause_secs``   the commit fence window (the only availability gap)
  - ``staged_mb_per_s``  wire throughput of the unpaused stream
  - ``tokens_during_update``  fleet tokens emitted while staging
  - ``aborts``       engine-side aborted-request count — 0 under the
    "hold"/"none" fences; >0 (and exit 1) under the legacy "abort" fence,
    which is exactly the availability cost the zero-pause protocol removes

Usage:
  python -m areal_tpu.tools.bench_weight_sync [--replicas 2] [--updates 3]
      [--chunk-mb 1] [--stage-target device|host] [--commit-fence hold|none]
      [--hidden 192] [--layers 4] [--vocab 2048] [--json]

``run_bench`` is importable; ``validate_installation --weight-sync-self-test``
runs it with small settings and asserts the zero-pause property
(pause_secs * 5 <= stage_secs, zero aborts).
"""

from __future__ import annotations

import argparse
import json
import threading
import time

from areal_tpu.utils import logging as alog

logger = alog.getLogger("bench_weight_sync")


def _tiny_model(hidden: int, layers: int, vocab: int):
    from areal_tpu.models import qwen

    return qwen.ModelConfig(
        vocab_size=vocab,
        hidden_size=hidden,
        intermediate_size=2 * hidden,
        num_layers=layers,
        num_heads=4,
        num_kv_heads=2,
        dtype="float32",
        tie_word_embeddings=True,
        rope_theta=10000.0,
    )


def _tree_bytes(params) -> int:
    import jax
    import numpy as np

    return int(
        sum(np.prod(x.shape) * x.dtype.itemsize for x in jax.tree.leaves(params))
    )


def run_bench(
    n_replicas: int = 2,
    n_updates: int = 3,
    chunk_mb: int = 1,
    stage_target: str = "device",
    commit_fence: str = "hold",
    hidden: int = 192,
    layers: int = 4,
    vocab: int = 2048,
    load_tokens: int = 192,
    load_concurrency: int = 2,
) -> dict:
    """Run ``n_updates`` streamed weight updates against an in-process
    ``n_replicas`` fleet under continuous generation load; return the
    measured split. CPU-safe: tiny model, real HTTP + engine stack."""
    import jax
    import numpy as np

    from areal_tpu.api.config import (
        InferenceEngineConfig,
        MeshConfig,
        ServerConfig,
    )
    from areal_tpu.api.io_struct import (
        GenerationHyperparameters,
        ModelRequest,
        StopReason,
        WeightUpdateMeta,
    )
    from areal_tpu.inference.client import RemoteJaxEngine
    from areal_tpu.inference.decode_engine import DecodeEngine
    from areal_tpu.inference.server import ServerThread
    from areal_tpu.models import qwen

    mcfg = _tiny_model(hidden, layers, vocab)
    base = qwen.init_params(jax.random.PRNGKey(0), mcfg)
    servers: list[ServerThread] = []
    client = None
    stop_load = threading.Event()
    stop_reasons: list[str] = []
    version_spans: list[tuple[int, int]] = []
    load_threads: list[threading.Thread] = []
    try:
        for i in range(n_replicas):
            cfg = ServerConfig(
                max_batch_size=4,
                # one attention-window variant total (window == T always):
                # decode-chunk compiles happen once, in the warm-up phase,
                # never inside a measured commit fence
                max_seq_len=512,
                attn_window_step=512,
                decode_steps_per_call=4,
                seed=i,
                weight_stage_target=stage_target,
                mesh=MeshConfig(data=-1, fsdp=1, seq=1, model=1),
            )
            eng = DecodeEngine(cfg, params=base, model_cfg=mcfg)
            eng.initialize()
            st = ServerThread(cfg, eng)
            st.start()
            servers.append(st)
        client = RemoteJaxEngine(
            InferenceEngineConfig(
                max_concurrent_rollouts=load_concurrency,
                consumer_batch_size=1,
                request_timeout=120,
                weight_chunk_mb=chunk_mb,
                weight_commit_fence=commit_fence,
            ),
            addresses=[s.address for s in servers],
        )
        client.initialize()

        def load_loop(seed: int):
            import asyncio

            from areal_tpu.inference.client import close_loop_sessions

            async def run():
                k = 0
                while not stop_load.is_set():
                    k += 1
                    req = ModelRequest(
                        input_ids=[2 + seed, 5, 7 + k % 11],
                        rid=f"bench-load-{seed}-{k}",
                        gconfig=GenerationHyperparameters(
                            max_new_tokens=load_tokens, temperature=1.0
                        ),
                    )
                    try:
                        resp = await client.agenerate(req)
                    except Exception as e:  # noqa: BLE001 — teardown race
                        if not stop_load.is_set():
                            logger.warning(f"bench load request failed: {e!r}")
                        break
                    stop_reasons.append(resp.stop_reason)
                    if resp.output_versions:
                        version_spans.append(
                            (
                                min(resp.output_versions),
                                max(resp.output_versions),
                            )
                        )
                    if resp.stop_reason == StopReason.ABORT.value:
                        break  # an abort under zero-pause = failure signal
                await close_loop_sessions()

            asyncio.run(run())

        for i in range(load_concurrency):
            t = threading.Thread(target=load_loop, args=(i,), daemon=True)
            t.start()
            load_threads.append(t)
        # warm-up: wait until every load thread completed one full request,
        # so all decode-chunk/prefill variants are compiled BEFORE the
        # first measured update (a cold compile inside the commit fence
        # would be measured as pause, which it is not in steady state)
        warm_deadline = time.monotonic() + 180
        while (
            len(stop_reasons) < load_concurrency
            and time.monotonic() < warm_deadline
        ):
            time.sleep(0.05)

        total_bytes = _tree_bytes(base)
        stages, pauses, tokens_during = [], [], []
        for u in range(n_updates):
            new_params = jax.tree.map(
                lambda x: np.asarray(x) + 0.01 * (u + 1), base
            )
            client.update_weights(
                WeightUpdateMeta(type="mem"), params=new_params
            )
            stages.append(client.last_stage_secs)
            pauses.append(client.last_pause_secs)
            tokens_during.append(client.last_update_gen_tokens)
            time.sleep(0.2)
        stop_load.set()
        for t in load_threads:
            t.join(timeout=60)
        # the engine-side counter is the truth: client.agenerate resumes
        # aborted requests transparently, so RESPONSE stop_reasons can
        # never show an abort even under the legacy full-pause fence
        n_aborts = sum(
            int(st.engine.stats.get("aborted", 0)) for st in servers
        )
        assert not any(
            r == StopReason.ABORT.value for r in stop_reasons
        ), "client surfaced a raw abort — interruptible resume loop broken"
        stage_mean = sum(stages) / len(stages) if stages else 0.0
        pause_mean = sum(pauses) / len(pauses) if pauses else 0.0
        mixed = sum(1 for lo, hi in version_spans if hi > lo)
        return {
            "replicas": n_replicas,
            "updates": n_updates,
            "stage_target": stage_target,
            "commit_fence": commit_fence,
            "model_bytes": total_bytes,
            "chunk_mb": chunk_mb,
            "stage_secs": stages,
            "pause_secs": pauses,
            "stage_secs_mean": stage_mean,
            "pause_secs_mean": pause_mean,
            "pause_over_stage": (pause_mean / stage_mean) if stage_mean else None,
            # wire bytes ~= fp32 tree / 2 (bf16) x replicas on the direct
            # fan-out; report trainer-uplink throughput (1x per bucket)
            "staged_mb_per_s": (total_bytes / 2 / (1 << 20)) / stage_mean
            if stage_mean
            else 0.0,
            "tokens_during_update": tokens_during,
            "load_requests": len(stop_reasons),
            "mixed_version_responses": mixed,
            "aborts": n_aborts,
            "final_version": client.get_version(),
        }
    finally:
        stop_load.set()
        if client is not None:
            client.destroy()
        for st in servers:
            st.stop()


def self_test(ratio: float = 5.0) -> str:
    """The zero-pause acceptance gate, sized for CI: the commit fence must
    be at least ``ratio``x smaller than the unpaused staging window, no
    in-flight request may abort, and updates must actually commit."""
    # hidden=256 doubles the streamed bytes over the default bench size:
    # the staging window grows with the model while the fence stays the
    # commit roundtrip, keeping the asserted ratio comfortably off the
    # flake boundary on slow CI hosts
    r = run_bench(n_replicas=2, n_updates=3, chunk_mb=1, hidden=256)
    assert r["aborts"] == 0, f"{r['aborts']} aborted requests under zero-pause sync"
    assert r["final_version"] == r["updates"], r["final_version"]
    assert r["load_requests"] > 0, "generation load never completed a request"
    stage, pause = r["stage_secs_mean"], r["pause_secs_mean"]
    assert pause * ratio <= stage, (
        f"commit fence {pause:.3f}s not {ratio}x smaller than staging "
        f"{stage:.3f}s — pause window is not commit-only"
    )
    return (
        f"stage {stage * 1e3:.0f}ms (unpaused) vs pause {pause * 1e3:.0f}ms "
        f"({r['staged_mb_per_s']:.1f} MB/s, {sum(r['tokens_during_update'])} "
        f"tokens generated during updates, {r['mixed_version_responses']} "
        f"mixed-version responses, 0 aborts)"
    )


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--replicas", type=int, default=2)
    p.add_argument("--updates", type=int, default=3)
    p.add_argument("--chunk-mb", type=int, default=1)
    p.add_argument(
        "--stage-target", default="device", choices=("device", "host")
    )
    p.add_argument(
        "--commit-fence", default="hold", choices=("hold", "none", "abort")
    )
    p.add_argument("--hidden", type=int, default=192)
    p.add_argument("--layers", type=int, default=4)
    p.add_argument("--vocab", type=int, default=2048)
    p.add_argument("--json", action="store_true", help="machine-readable output")
    args = p.parse_args(argv)
    r = run_bench(
        n_replicas=args.replicas,
        n_updates=args.updates,
        chunk_mb=args.chunk_mb,
        stage_target=args.stage_target,
        commit_fence=args.commit_fence,
        hidden=args.hidden,
        layers=args.layers,
        vocab=args.vocab,
    )
    if args.json:
        print(json.dumps(r, indent=2))
    else:
        print(
            f"weight sync over {r['replicas']} replicas "
            f"({r['model_bytes'] / (1 << 20):.1f} MB fp32 tree, "
            f"{r['chunk_mb']} MB buckets, fence={r['commit_fence']}, "
            f"stage_target={r['stage_target']}):"
        )
        print(
            f"  stage  {r['stage_secs_mean'] * 1e3:8.1f} ms  (generation "
            f"running; {r['staged_mb_per_s']:.1f} MB/s uplink)"
        )
        print(f"  pause  {r['pause_secs_mean'] * 1e3:8.1f} ms  (commit fence only)")
        print(
            f"  {sum(r['tokens_during_update'])} tokens generated during "
            f"updates, {r['mixed_version_responses']} mixed-version "
            f"responses, {r['aborts']} aborts over {r['load_requests']} "
            "requests"
        )
    return 0 if r["aborts"] == 0 else 1


if __name__ == "__main__":
    raise SystemExit(main())
