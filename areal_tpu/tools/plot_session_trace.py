"""Summarize session-tracer lifecycles: per-phase latency percentiles and an
accept/reject breakdown (reference areal/tools/plot_session_trace.py role,
text output instead of matplotlib — the TPU image is headless).

Usage: python -m areal_tpu.tools.plot_session_trace SESSIONS.jsonl
"""

from __future__ import annotations

import argparse
import json
from collections import defaultdict
from pathlib import Path


def summarize(path: str | Path) -> dict:
    phases: dict[str, list[float]] = defaultdict(list)
    status: dict[str, int] = defaultdict(int)
    total: list[float] = []
    for line in Path(path).read_text().splitlines():
        if not line.strip():
            continue
        rec = json.loads(line)
        status[rec.get("status", "unknown")] += 1
        if rec.get("start") is not None and rec.get("end") is not None:
            total.append(rec["end"] - rec["start"])
        for ph in rec.get("phases", []):
            if ph.get("start") is not None and ph.get("end") is not None:
                phases[ph["name"]].append(ph["end"] - ph["start"])
    def pct(xs, q):
        xs = sorted(xs)
        return xs[min(len(xs) - 1, int(q * len(xs)))] if xs else 0.0
    return {
        "sessions": dict(status),
        "total_s": {"p50": pct(total, 0.5), "p90": pct(total, 0.9), "p99": pct(total, 0.99)},
        "phases": {
            name: {"n": len(xs), "p50": pct(xs, 0.5), "p90": pct(xs, 0.9)}
            for name, xs in sorted(phases.items())
        },
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("sessions_file")
    args = p.parse_args(argv)
    print(json.dumps(summarize(args.sessions_file), indent=2))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
