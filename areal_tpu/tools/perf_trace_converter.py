"""Merge per-rank/per-role perf-tracer files into one Chrome trace.

Reference: areal/tools/perf_trace_converter.py — collects the rank-qualified
catapult JSON files the PerfTracer writes, remaps pid/tid so ranks render as
separate process rows sorted (role, rank), and emits a single
``traceEvents`` JSON loadable in chrome://tracing / Perfetto.

Usage:  python -m areal_tpu.tools.perf_trace_converter TRACE_DIR [-o out.json]
"""

from __future__ import annotations

import argparse
import json
import re
from pathlib import Path

_FNAME_RE = re.compile(r"(?P<role>[A-Za-z_]+)?-?r(?P<rank>\d+)")


def _load_events(path: Path) -> list[dict]:
    text = path.read_text()
    try:
        payload = json.loads(text)
        if isinstance(payload, dict):
            return payload.get("traceEvents", [])
        return payload
    except json.JSONDecodeError:
        # JSONL: one event per line
        events = []
        for line in text.splitlines():
            line = line.strip().rstrip(",")
            if line:
                try:
                    events.append(json.loads(line))
                except json.JSONDecodeError:
                    continue
        return events


def _rank_role_of(path: Path) -> tuple[int, str]:
    m = _FNAME_RE.search(path.stem)
    if m:
        return int(m.group("rank")), m.group("role") or "rank"
    return 0, path.stem


def convert(trace_dir: str | Path, output: str | Path | None = None) -> Path:
    trace_dir = Path(trace_dir)
    files = sorted(
        p
        for p in trace_dir.glob("**/*")
        if p.suffix in (".json", ".jsonl") and p.is_file()
    )
    if not files:
        raise FileNotFoundError(f"no trace files under {trace_dir}")
    merged: list[dict] = []
    for pid, path in enumerate(sorted(files, key=_rank_role_of)):
        rank, role = _rank_role_of(path)
        merged.append(
            {
                "name": "process_name",
                "ph": "M",
                "pid": pid,
                "args": {"name": f"{role} r{rank}"},
            }
        )
        for ev in _load_events(path):
            ev = dict(ev)
            ev["pid"] = pid
            merged.append(ev)
    output = Path(output) if output else trace_dir / "merged_trace.json"
    output.write_text(json.dumps({"traceEvents": merged}))
    return output


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("trace_dir")
    p.add_argument("-o", "--output", default=None)
    args = p.parse_args(argv)
    out = convert(args.trace_dir, args.output)
    print(f"wrote {out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
