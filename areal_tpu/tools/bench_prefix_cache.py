"""Prefix-cache microbenchmark: hit rate vs prefill throughput on a
shared-prefix workload (ISSUE 5 acceptance harness).

The workload models the dominant RL serving pattern: every prompt carries
the same long system/few-shot prefix and a short per-question tail. A cold
wave prefills from token zero; after its completions publish into the
radix tree, a warm wave of NEW questions over the same prefix aliases the
cached pages and prefills only the tails. The report compares effective
prefill throughput (prompt tokens admitted per second of prefill wall
time) between the two waves — the warm wave should win by roughly the
shared fraction, quantized by prompt buckets.

CPU-safe (tiny model, direct-driven engine, compile warm-up excluded from
every timed window).

Usage:
  python -m areal_tpu.tools.bench_prefix_cache [--prefix-tokens 1632]
      [--suffix-tokens 416] [--requests 4] [--json]

``run_bench`` is importable; ``validate_installation
--prefix-cache-self-test`` runs it small and asserts: the warm wave
prefilled ONLY suffix tokens, warm throughput >= 2x cold, refcounts return
to baseline once the tree is flushed, and a weight commit under the
default policy leaves no stale pages matchable.
"""

from __future__ import annotations

import argparse
import json
import time

from areal_tpu.utils import logging as alog

logger = alog.getLogger("bench_prefix_cache")

_PSZ = 16  # small pages keep the tiny-model workload multi-page


def _build_engine(max_seq_len: int):
    import jax

    from areal_tpu.api.config import MeshConfig, ServerConfig
    from areal_tpu.inference.decode_engine import DecodeEngine
    from areal_tpu.models import qwen

    mcfg = qwen.ModelConfig(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_layers=2,
        num_heads=4,
        num_kv_heads=2,
        dtype="float32",
        tie_word_embeddings=True,
        attention_bias=True,
        rope_theta=10000.0,
    )
    cfg = ServerConfig(
        max_batch_size=4,
        max_seq_len=max_seq_len,
        page_size=_PSZ,
        decode_steps_per_call=4,
        seed=0,
        mesh=MeshConfig(data=-1, fsdp=1, seq=1, model=1),
    )
    eng = DecodeEngine(
        cfg, params=qwen.init_params(jax.random.PRNGKey(0), mcfg), model_cfg=mcfg
    )
    eng.initialize()
    return eng


def _drive(eng, max_chunks=128):
    for _ in range(max_chunks):
        rows = eng._admit_pending()
        eng._apply_slot_updates(rows)
        eng._drain(eng._dispatch_chunk())
        if not any(t is not None for t in eng._slot_task) and not eng._backlog:
            break


def _admit_wave(eng, prompts) -> float:
    """Submit one wave, time ONLY the admission (prefill dispatch +
    device completion), then drive decode to completion untimed."""
    import jax

    from areal_tpu.api.io_struct import GenerationHyperparameters, ModelRequest

    done = []
    g = GenerationHyperparameters(max_new_tokens=2, greedy=True)
    for ids in prompts:
        eng.submit(ModelRequest(input_ids=list(ids), gconfig=g), done.append)
    t0 = time.monotonic()
    rows = eng._admit_pending()
    jax.block_until_ready(jax.tree.leaves(eng.cache))
    dt = time.monotonic() - t0
    eng._apply_slot_updates(rows)
    _drive(eng)
    assert len(done) == len(prompts), f"{len(done)}/{len(prompts)} finished"
    return dt


def run_bench(
    prefix_tokens: int = 1632,
    suffix_tokens: int = 416,
    n_requests: int = 4,
) -> dict:
    """One cold wave + one warm wave over a shared prefix; returns the
    measured split. ``prefix_tokens`` must be page-aligned so the whole
    prefix is matchable."""
    import numpy as np

    assert prefix_tokens % _PSZ == 0, "prefix must be page-aligned"
    prompt_tokens = prefix_tokens + suffix_tokens
    eng = _build_engine(max_seq_len=2 * prompt_tokens)
    rng = np.random.default_rng(0)

    def wave(prefix):
        return [
            list(prefix) + rng.integers(0, 256, suffix_tokens).tolist()
            for _ in range(n_requests)
        ]

    # compile warm-up: one cold + one warm wave over a THROWAWAY prefix
    # exercises both prefill variants (full-bucket and suffix+prefix-table),
    # so the timed waves below replay compiled programs only
    warm_prefix = rng.integers(0, 256, prefix_tokens).tolist()
    _admit_wave(eng, wave(warm_prefix))
    _admit_wave(eng, wave(warm_prefix))
    eng.flush_prefix_cache()

    prefix = rng.integers(0, 256, prefix_tokens).tolist()
    pf0 = eng.stats["prefill_tokens"]
    cold_dt = _admit_wave(eng, wave(prefix))
    cold_prefilled = eng.stats["prefill_tokens"] - pf0

    pf0 = eng.stats["prefill_tokens"]
    hit0 = eng.stats["prefix_hit_tokens"]
    warm_dt = _admit_wave(eng, wave(prefix))
    warm_prefilled = eng.stats["prefill_tokens"] - pf0
    hit_tokens = eng.stats["prefix_hit_tokens"] - hit0

    total = n_requests * prompt_tokens
    out = {
        "n_requests": n_requests,
        "prompt_tokens": prompt_tokens,
        "shared_fraction": round(prefix_tokens / prompt_tokens, 3),
        "cold_prefill_tok_s": round(total / cold_dt, 1),
        "warm_prefill_tok_s": round(total / warm_dt, 1),
        "speedup": round(cold_dt / warm_dt, 2),
        "cold_prefilled_tokens": int(cold_prefilled),
        "warm_prefilled_tokens": int(warm_prefilled),
        "hit_tokens": int(hit_tokens),
        "hit_rate": round(hit_tokens / (hit_tokens + warm_prefilled), 3),
        "pages_held": eng.prefix_cache_stats()["pages_held"],
        "_engine": eng,  # self_test pokes further; CLI path drops it
    }
    return out


def self_test(
    prefix_tokens: int = 1632, suffix_tokens: int = 416, n_requests: int = 4
) -> str:
    """The ``--prefix-cache-self-test`` body: assert the tentpole's
    acceptance criteria on the bench workload."""
    import numpy as np

    r = run_bench(prefix_tokens, suffix_tokens, n_requests)
    eng = r.pop("_engine")
    # 1. warm admission prefilled ONLY the suffixes
    assert r["warm_prefilled_tokens"] == n_requests * suffix_tokens, r
    assert r["hit_tokens"] == n_requests * prefix_tokens, r
    # 2. suffix-only prefill >= 2x cold prefill throughput
    assert r["speedup"] >= 2.0, f"warm speedup {r['speedup']}x < 2x: {r}"
    # 3. refcounts return to baseline: with all requests finished, every
    # outstanding page is the tree's own; flushing drains the pool to zero
    assert eng.pool.used == r["pages_held"], (eng.pool.used, r)
    eng.flush_prefix_cache()
    assert eng.pool.used == 0, "refcount leak after flush"
    # 4. a weight commit under the default policy leaves no stale-version
    # pages matchable: republish, commit, then probe the tree directly
    rng = np.random.default_rng(1)
    prefix = rng.integers(0, 256, prefix_tokens).tolist()
    _admit_wave(
        eng,
        [prefix + rng.integers(0, 256, suffix_tokens).tolist()],
    )
    assert eng.prefix_cache_stats()["pages_held"] > 0
    from areal_tpu.inference.server import flatten_params

    import jax

    eng.begin_staged_update()
    eng.stage_weight_bucket(
        flatten_params(jax.tree.map(np.asarray, eng.params))
    )
    eng.commit_staged_weights(eng.get_version() + 1)
    assert eng.prefix_cache_stats()["pages_held"] == 0
    matched, _ = eng._radix.match(prefix)
    assert matched == [], "stale pages matchable after a weight commit"
    assert eng.pool.used == 0
    return (
        f"warm {r['warm_prefill_tok_s']:.0f} tok/s vs cold "
        f"{r['cold_prefill_tok_s']:.0f} ({r['speedup']}x) at "
        f"{r['hit_rate']:.0%} hit rate; refcounts clean, commit flushes"
    )


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--prefix-tokens", type=int, default=1632)
    p.add_argument("--suffix-tokens", type=int, default=416)
    p.add_argument("--requests", type=int, default=4)
    p.add_argument("--json", action="store_true")
    args = p.parse_args(argv)
    r = run_bench(args.prefix_tokens, args.suffix_tokens, args.requests)
    r.pop("_engine")
    if args.json:
        print(json.dumps(r))
        return 0
    for k, v in r.items():
        print(f"{k:<24} {v}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
