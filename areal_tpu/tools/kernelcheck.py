"""kernelcheck — standing interpret-vs-XLA parity harness over ops/ kernels.

Every Pallas kernel in ``areal_tpu/ops/`` registers a *case grid* here:
closures that run the kernel in interpret mode (CPU) and an independent
pure-XLA reference over a spread of shapes/dtypes/quantization variants.
``python -m areal_tpu.tools.kernelcheck`` runs the whole grid and exits
nonzero on any divergence — so the next kernel PR (ROADMAP item 2) lands
onto a standing differential harness instead of ad-hoc parity tests, and
a jax bump that changes kernel semantics (not just signatures — PVT
covers those) fails loudly in CI.

Registering a kernel:

    @register_kernel("my_kernel")
    def _cases():
        yield {
            "case": "f32-basic",        # unique within the kernel
            "kernel": lambda: ...,      # interpret-mode launch -> array
            "reference": lambda: ...,   # pure-XLA ground truth -> array
            "tol": 2e-2,                # max |kernel - reference| allowed
        }

The harness materializes both sides, compares max-abs-diff against the
case tolerance, and reports per-case PASS/FAIL. Closures build their own
inputs deterministically (seeded numpy) so runs are reproducible.

CLI:
  --list            enumerate registered kernels and their case counts
  --kernel NAME     run one kernel's grid only
  --json            machine-readable report on stdout
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Callable, Dict, Iterator

import numpy as np

REGISTRY: Dict[str, Callable[[], "Iterator[dict]"]] = {}


def register_kernel(name: str) -> Callable:
    def deco(fn: Callable) -> Callable:
        REGISTRY[name] = fn
        return fn

    return deco


# ---------------------------------------------------------------------------
# paged attention (ops/paged_attention_q8.py): int8 narrow scales + stacked
# ---------------------------------------------------------------------------


def _paged_inputs(S=4, KH=2, G=6, hd=128, psz=16, wp=4, layers=1, seed=0):
    import jax.numpy as jnp

    rng = np.random.default_rng(seed)
    H = KH * G
    N = S * wp + 1
    q = jnp.asarray(rng.normal(0, 1, (S, H, hd)), jnp.float32)
    k = jnp.asarray(rng.normal(0, 1, (layers, KH, N, psz, hd)), jnp.float32)
    v = jnp.asarray(rng.normal(0, 1, (layers, KH, N, psz, hd)), jnp.float32)
    pt = jnp.asarray(1 + np.arange(S * wp).reshape(S, wp), jnp.int32)
    lengths = jnp.asarray(rng.integers(1, wp * psz + 1, S), jnp.int32)
    return q, k, v, lengths, pt


@register_kernel("paged_attention_q8")
def _cases_paged_q8() -> Iterator[dict]:
    from areal_tpu.inference import paged_kv
    from areal_tpu.ops.paged_attention_q8 import paged_attention_q8

    for S, KH, G, label in ((4, 2, 6, "int8-S4-gqa6"), (2, 1, 8, "int8-S2-mha8")):
        q, k, v, lengths, pt = _paged_inputs(S=S, KH=KH, G=G, seed=S)
        kq, ks = paged_kv.quantize_kv(k[0])
        vq, vs = paged_kv.quantize_kv(v[0])
        yield {
            "case": label,
            # the fork takes RAW q (applies 1/sqrt(hd) internally)
            "kernel": lambda q=q, kq=kq, ks=ks, vq=vq, vs=vs, le=lengths, pt=pt: (
                paged_attention_q8(
                    q, kq, ks, vq, vs, le, pt,
                    pages_per_compute_block=2,
                    interpret=True,
                )
            ),
            "reference": lambda q=q, kq=kq, ks=ks, vq=vq, vs=vs, le=lengths, pt=pt: (
                paged_kv.paged_attention_xla(q, kq, vq, le, pt, ks, vs)
            ),
            "tol": 3e-2,
        }


@register_kernel("paged_attention_stacked")
def _cases_paged_stacked() -> Iterator[dict]:
    import jax.numpy as jnp

    from areal_tpu.inference import paged_kv
    from areal_tpu.ops.paged_attention_q8 import paged_attention_stacked

    L = 3
    q, k, v, lengths, pt = _paged_inputs(layers=L, seed=7)

    # bf16 stacked cache (no scales), first and last layer indices
    kb, vb = k.astype(jnp.bfloat16), v.astype(jnp.bfloat16)
    for layer in (0, L - 1):
        yield {
            "case": f"stacked-bf16-layer{layer}",
            "kernel": lambda layer=layer: paged_attention_stacked(
                q, kb, vb, jnp.int32(layer), lengths, pt,
                pages_per_compute_block=2,
                interpret=True,
            ),
            "reference": lambda layer=layer: paged_kv.paged_attention_xla(
                q, kb[layer], vb[layer], lengths, pt
            ),
            "tol": 3e-2,
        }

    # int8 stacked cache with narrow scales
    kq = jnp.stack([paged_kv.quantize_kv(k[i])[0] for i in range(L)])
    ks = jnp.stack([paged_kv.quantize_kv(k[i])[1] for i in range(L)])
    vq = jnp.stack([paged_kv.quantize_kv(v[i])[0] for i in range(L)])
    vs = jnp.stack([paged_kv.quantize_kv(v[i])[1] for i in range(L)])
    for layer in (1, L - 1):
        yield {
            "case": f"stacked-int8-layer{layer}",
            "kernel": lambda layer=layer: paged_attention_stacked(
                q, kq, vq, jnp.int32(layer), lengths, pt,
                pages_per_compute_block=2,
                k_scales=ks, v_scales=vs,
                interpret=True,
            ),
            "reference": lambda layer=layer: paged_kv.paged_attention_xla(
                q, kq[layer], vq[layer], lengths, pt, ks[layer], vs[layer]
            ),
            "tol": 3e-2,
        }


# ---------------------------------------------------------------------------
# paged suffix attention (ops/paged_suffix_attention.py): suffix-prefill
# (chain mask) + tree-verify (ancestor mask) over bf16/int8/fp8 pages
# ---------------------------------------------------------------------------


def _suffix_case(
    S=3, B=6, KH=2, G=2, hd=16, psz=4, wp=4, L=2, layer=1,
    mask="chain", pages="f32", lens="ragged", ppcb=None, seed=0,
):
    """Build one paged_suffix_attention parity case. Returns (params,
    kernel_fn, reference_fn); the params dict is what --case repro wants."""
    import jax.numpy as jnp

    from areal_tpu.inference import paged_kv
    from areal_tpu.ops import paged_suffix_attention as psa

    params = dict(S=S, B=B, KH=KH, G=G, hd=hd, psz=psz, wp=wp, L=L,
                  layer=layer, mask=mask, pages=pages, lens=lens,
                  ppcb=ppcb, seed=seed)
    rng = np.random.default_rng(seed)
    H = KH * G
    N = S * wp + 1
    q = jnp.asarray(rng.normal(0, 1, (S, B, H, hd)), jnp.float32)
    ksf = jnp.asarray(rng.normal(0, 1, (S, B, KH, hd)), jnp.float32)
    vsf = jnp.asarray(rng.normal(0, 1, (S, B, KH, hd)), jnp.float32)
    k = jnp.asarray(rng.normal(0, 1, (L, KH, N, psz, hd)), jnp.float32)
    v = jnp.asarray(rng.normal(0, 1, (L, KH, N, psz, hd)), jnp.float32)
    pt = jnp.asarray(1 + np.arange(S * wp).reshape(S, wp), jnp.int32)
    W = wp * psz
    if lens == "ragged":
        # 0, full, and page-boundary-straddling lengths (NOT multiples of
        # psz or of the ppcb*psz block) in one batch
        pool = [0, W] + [int(x) for x in rng.integers(1, W, max(S, 2))]
        plens = jnp.asarray(pool[:S], jnp.int32)
    else:  # "aligned": page-multiple lengths (radix prefixes)
        plens = jnp.asarray(
            psz * rng.integers(0, wp + 1, S), jnp.int32
        )
    if mask == "chain":
        seg = np.ones((S, B), np.int32)
        seg[:, B - 1] = 0  # one padded suffix row
        m = (
            np.tril(np.ones((B, B), bool))[None]
            & (seg[:, :, None] != 0)
            & (seg[:, None, :] != 0)
        )
    else:  # "tree": random parent-before-child ancestor-or-self mask
        m = np.zeros((S, B, B), bool)
        m[:, np.arange(B), np.arange(B)] = True
        m[:, :, 0] = True
        for s in range(S):
            for r in range(1, B):
                p = int(rng.integers(0, r))
                m[s, r] |= m[s, p]
    m = jnp.asarray(m)

    scales = {}
    if pages in ("int8", "fp8"):
        dt = jnp.int8 if pages == "int8" else jnp.float8_e4m3fn
        kq, ks = paged_kv.quantize_kv(k, dtype=dt)
        vq, vs = paged_kv.quantize_kv(v, dtype=dt)
        k, v = kq, vq
        scales = dict(k_scales=ks, v_scales=vs)
    elif pages == "bf16":
        k, v = k.astype(jnp.bfloat16), v.astype(jnp.bfloat16)

    li = jnp.int32(layer)

    def kernel():
        return psa.paged_suffix_attention(
            q, ksf, vsf, k, v, li, plens, pt, m,
            pages_per_compute_block=ppcb, interpret=True, **scales,
        )

    def reference():
        return psa.paged_suffix_attention_xla(
            q, ksf, vsf, k, v, li, plens, pt, m, **scales,
        )

    return params, kernel, reference


@register_kernel("paged_suffix_attention")
def _cases_paged_suffix() -> Iterator[dict]:
    grid = [
        # (label, overrides, tol) — GQA ratios x ragged/aligned lengths x
        # bf16/int8/fp8 pages x chain/tree masks, page-straddling blocks
        ("chain-f32-gqa2-ragged", dict(), 2e-4),
        ("chain-bf16-mha1-aligned",
         dict(KH=4, G=1, pages="bf16", lens="aligned", seed=1), 2e-2),
        ("chain-f32-gqa4-straddle-ppcb2",
         dict(KH=1, G=4, wp=6, ppcb=2, seed=2), 2e-4),
        ("tree-f32-gqa2-ragged", dict(mask="tree", seed=3), 2e-4),
        ("tree-bf16-gqa2-layer0",
         dict(mask="tree", pages="bf16", layer=0, seed=4), 2e-2),
        ("chain-int8-gqa2-ragged", dict(pages="int8", seed=5), 2e-4),
        ("tree-int8-mha1-straddle",
         dict(mask="tree", pages="int8", KH=4, G=1, wp=6, ppcb=3, seed=6),
         2e-4),
        ("chain-fp8-gqa2-ragged", dict(pages="fp8", seed=7), 2e-4),
        ("tree-fp8-gqa4-aligned",
         dict(mask="tree", pages="fp8", KH=1, G=4, lens="aligned", seed=8),
         2e-4),
    ]
    for label, overrides, tol in grid:
        params, kernel, reference = _suffix_case(**overrides)
        yield {
            "case": label,
            "params": params,
            "kernel": kernel,
            "reference": reference,
            "tol": tol,
        }


# ---------------------------------------------------------------------------
# forward-only flash attention (ops/attention.py)
# ---------------------------------------------------------------------------


@register_kernel("flash_fwd")
def _cases_flash_fwd() -> Iterator[dict]:
    import jax.numpy as jnp

    from areal_tpu.ops import attention

    rng = np.random.default_rng(11)
    G, L, H, d = 1, 128, 2, 128
    q = jnp.asarray(rng.normal(0, 1, (G, L, H, d)), jnp.float32)
    k = jnp.asarray(rng.normal(0, 1, (G, L, H, d)), jnp.float32)
    v = jnp.asarray(rng.normal(0, 1, (G, L, H, d)), jnp.float32)
    grids = {
        "f32-one-segment": np.ones((G, L), np.int32),
        "f32-packed-two-segments": np.concatenate(
            [np.ones((G, L // 2), np.int32), 2 * np.ones((G, L // 2), np.int32)],
            axis=1,
        ),
    }
    for label, seg_np in grids.items():
        seg = jnp.asarray(seg_np)
        # same semantics as the kernel: causal AND same segment AND seg != 0
        qi = np.arange(L)[:, None]
        ki = np.arange(L)[None, :]
        mask = (
            (qi >= ki)
            & (seg_np[:, :, None] == seg_np[:, None, :])
            & (seg_np[:, :, None] != 0)
        )[:, None]  # [G, 1, L, L]
        yield {
            "case": label,
            "kernel": lambda seg=seg: attention.flash_fwd_pallas(
                q, k, v, seg, interpret=True
            ),
            "reference": lambda mask=mask: attention.sdpa_xla(
                q, k, v, jnp.asarray(mask), d
            ),
            "tol": 2e-4,
        }


# ---------------------------------------------------------------------------
# block-sparse tree attention (ops/tree_attention.py)
# ---------------------------------------------------------------------------


@register_kernel("tree_attention")
def _cases_tree_attention() -> Iterator[dict]:
    import jax
    import jax.numpy as jnp

    from areal_tpu.ops import tree_attention as ta

    rng = np.random.default_rng(13)
    N, H, d = 128, 2, 128
    q = jnp.asarray(rng.normal(0, 1, (N, H, d)), jnp.float32)
    k = jnp.asarray(rng.normal(0, 1, (N, H, d)), jnp.float32)
    v = jnp.asarray(rng.normal(0, 1, (N, H, d)), jnp.float32)

    # a chain tree (parent = i-1) makes the ancestor mask exactly causal;
    # a branching tree exercises the sparse-block path
    chain = np.arange(-1, N - 1)
    branchy = np.where(np.arange(N) % 4 == 0, np.maximum(np.arange(N) - 4, -1),
                       np.arange(N) - 1).astype(np.int64)
    for label, parent in (("chain-causal", chain), ("branching", branchy)):
        words_np, block_any_np = ta.pack_ancestor_bits(parent)
        words = jnp.asarray(words_np)
        block_any = jnp.asarray(block_any_np)
        # dense reference from the same ancestor bits
        bits = np.unpackbits(
            words_np.view(np.uint8), bitorder="little", axis=1
        )[:, :N].astype(bool)  # [N, N] ancestor mask
        mask = jnp.asarray(bits)[None]  # [1, N, N], broadcast over heads

        def ref(mask=mask):
            logits = jnp.einsum("qhd,khd->hqk", q, k) * d**-0.5
            probs = jax.nn.softmax(jnp.where(mask, logits, -1e30), axis=-1)
            return jnp.einsum("hqk,khd->qhd", probs, v)

        yield {
            "case": label,
            "kernel": lambda w=words, b=block_any: ta.tree_attention(
                q, k, v, w, b, interpret=True
            ),
            "reference": ref,
            "tol": 2e-4,
        }


# ---------------------------------------------------------------------------
# harness
# ---------------------------------------------------------------------------


def run_kernel(name: str, case: "int | str | None" = None) -> list[dict]:
    """Run one kernel's case grid; never raises on divergence — every
    case reports {kernel, index, case, max_abs_diff, tol, ok, error?,
    params?}. ``case`` filters to a single grid point by index or label
    (repro of one failing case without re-running the grid)."""
    results: list[dict] = []
    for idx, spec in enumerate(REGISTRY[name]()):
        if case is not None and case != idx and case != spec["case"]:
            continue
        rec: dict[str, Any] = {
            "kernel": name, "index": idx, "case": spec["case"],
            "tol": spec["tol"],
        }
        if "params" in spec:
            rec["params"] = spec["params"]
        try:
            got = np.asarray(spec["kernel"](), np.float32)
            want = np.asarray(spec["reference"](), np.float32)
            if got.shape != want.shape:
                rec.update(ok=False, error=f"shape {got.shape} vs {want.shape}")
            else:
                diff = float(np.max(np.abs(got - want)))
                rec.update(max_abs_diff=diff, ok=diff <= spec["tol"])
        except Exception as e:  # noqa: BLE001 — a crash IS a parity failure
            rec.update(ok=False, error=f"{type(e).__name__}: {e}")
        results.append(rec)
    return results


def run_all(
    only: str | None = None, case: "int | str | None" = None
) -> list[dict]:
    names = [only] if only else sorted(REGISTRY)
    out: list[dict] = []
    for name in names:
        out.extend(run_kernel(name, case=case))
    return out


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="kernelcheck",
        description="interpret-vs-XLA parity for every registered ops/ kernel",
    )
    ap.add_argument("--list", action="store_true", help="enumerate kernels")
    ap.add_argument("--kernel", help="run one kernel's grid only")
    ap.add_argument(
        "--case",
        help="run a single grid point (index or label; requires --kernel) — "
        "re-run one failing case in isolation",
    )
    ap.add_argument("--json", action="store_true", help="JSON report")
    args = ap.parse_args(argv)

    if args.list:
        for name in sorted(REGISTRY):
            n = sum(1 for _ in REGISTRY[name]())
            print(f"{name}: {n} case(s)")
        return 0
    if args.kernel and args.kernel not in REGISTRY:
        print(f"unknown kernel {args.kernel!r}; known: {sorted(REGISTRY)}",
              file=sys.stderr)
        return 2
    case: int | str | None = None
    if args.case is not None:
        if not args.kernel:
            print("--case requires --kernel", file=sys.stderr)
            return 2
        case = int(args.case) if args.case.isdigit() else args.case
        known = list(REGISTRY[args.kernel]())
        if not any(
            case == i or case == c["case"] for i, c in enumerate(known)
        ):
            print(
                f"unknown case {args.case!r} for {args.kernel}; known: "
                f"{[c['case'] for c in known]}",
                file=sys.stderr,
            )
            return 2

    results = run_all(args.kernel, case=case)
    if args.json:
        print(json.dumps({"results": results}, indent=1))
    else:
        for r in results:
            if r["ok"]:
                print(
                    f"PASS {r['kernel']}:{r['case']} "
                    f"max_abs_diff={r.get('max_abs_diff', 0):.2e} tol={r['tol']:.0e}"
                )
            else:
                detail = r.get("error") or (
                    f"max_abs_diff={r['max_abs_diff']:.2e} > tol={r['tol']:.0e}"
                )
                print(f"FAIL {r['kernel']}:{r['case']} {detail}")
                # full repro line: the case-params dict plus the --case
                # incantation that re-runs just this grid point
                if "params" in r:
                    print(f"  params={r['params']}")
                print(
                    f"  repro: python -m areal_tpu.tools.kernelcheck "
                    f"--kernel {r['kernel']} --case {r['index']}"
                )
    failed = [r for r in results if not r["ok"]]
    if failed:
        print(f"kernelcheck: {len(failed)}/{len(results)} case(s) DIVERGED",
              file=sys.stderr)
        return 1
    # in --json mode stdout is the document; keep it parseable
    print(f"kernelcheck: {len(results)} case(s) ok",
          file=sys.stderr if args.json else sys.stdout)
    return 0


if __name__ == "__main__":
    sys.exit(main())
