"""Standing microbench registry + noise-aware regression gate.

The kernel observatory's second half (docs/perf.md "Kernel observatory"):
where observability/kernel_probe.py attributes *production* decode steps,
this module pins each hot-path kernel in isolation so a regression shows
up as one number moving, not as a 3%% end-to-end drift nobody can bisect.

Registered benches (fast set — the committed CPU baseline under
benchmarks/):

    paged_decode_step    one forward_decode_paged step over all slots
    paged_attention_interpret  interpret-mode stacked paged kernel alone
    suffix_prefill       radix-suffix prefill over a cached prefix
    int8_kv_dequant      KV quantize->dequantize round trip
    tree_verify_forward  ancestor-masked forest forward (no_grad)
    spec_decode_step     oracle-draft speculative verify + accept walk
    radix_match          host-side radix prefix walk (no device work)
    weight_stage_encode  weight-bucket wire encoding (server push path)

Heavy benches (``--heavy`` / named via ``--benches``; engine- or
trainer-level, minutes not seconds — these subsume the retired root
prof_* scripts, see docs/perf.md "Reproduction"):

    decode_engine_steady  live DecodeEngine steady-state tok/s + the
                          probe's achieved roofline   (was prof_decode /
                          prof_r3 phase_decode; BENCH_QUANT=int8 covers
                          prof_r4 phase_int8)
    train_step            fwd+bwd+CE optimizer-shaped step (prof_r3
                          phase_train)
    tree_train            grad through the ancestor-mask forward
                          (prof_r5 phase_tree)
    weight_update         paused LoRA-delta fold + one full mem-path
                          push on a live engine (prof_r4 phase_wu)

Every bench emits ``{wall_s, tok_s, flops, bytes, roofline_frac,
noise_frac}`` measured with warm-up + median-of-N (the PR 12 lesson:
first-call compile and cache replay must never land in the measured
window; timing syncs by pulling a host scalar because
``block_until_ready`` does not synchronize on the axon backend).

``--compare BASELINE.json`` applies a noise-aware relative threshold per
bench — regression iff ``cur > base * (1 + max(threshold, 2*noise)) +
floor`` — and exits nonzero iff any bench regresses; new/missing entries
are warnings, not failures, so adding a bench never breaks CI.

Modes (ported from the retired scripts):

    --ladder      unattended measurement ladder (was prof_ladder.py):
                  SIGALRM-raising children, TPU probe between steps,
                  done-file resume under .bench_cache/ladder_done.json
    --learn-gate  on-chip RL learning gate through the full stack
                  (was prof_learn.py); excluded from --compare

Dims default tiny (CPU-runnable, the committed baseline);
``MICROBENCH_FULL=1`` switches to bench.py's MODEL_KW (Qwen2.5-1.5B).
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time
from typing import Any, Callable

import numpy as np

DEFAULT_ITERS = 7
DEFAULT_WARMUP = 2
# relative slack below which a move is never a regression. Measured on
# this image: identical back-to-back suites differ up to ~45% on ms-scale
# kernels — the variance is CROSS-PROCESS (container CPU contention slows
# a whole run), so neither median-of-N nor min-of-N inside one process can
# average it away. The gate therefore targets kernel-scale regressions
# (a 2x is always flagged: 2.0 > 1.6 + floor) and stays silent on drift
# smaller than the machine's own run-to-run wobble; the per-bench measured
# noise_frac widens the margin further for intrinsically jumpy benches.
DEFAULT_THRESHOLD = 0.6
# absolute floor: sub-millisecond medians can move tens of µs on one
# scheduler hiccup regardless of the kernel under test
NOISE_FLOOR_S = 5e-5

REGISTRY: dict[str, dict[str, Any]] = {}


def register(name: str, *, heavy: bool = False) -> Callable:
    """Class-of-one decorator: the registered fn is a SETUP fn returning
    ``{"run": closure, "tokens"?, "flops"?, "bytes"?}`` (the harness times
    ``run``), or ``{"entry": {...}}`` for benches that self-measure (the
    engine-level heavies, where one "iteration" is a multi-second run)."""

    def deco(fn: Callable) -> Callable:
        REGISTRY[name] = {"fn": fn, "heavy": heavy, "doc": (fn.__doc__ or "").strip().splitlines()[0] if fn.__doc__ else ""}
        return fn

    return deco


def _sync(x: Any) -> Any:
    """Force completion by pulling one host scalar (NOT block_until_ready,
    which does not synchronize on the axon backend — docs/perf.md)."""
    import jax

    return np.asarray(jax.tree.leaves(x)[0]).ravel()[0]


def model_cfg():
    """Tiny CPU-runnable dims by default; MICROBENCH_FULL=1 uses bench.py's
    MODEL_KW (Qwen2.5-1.5B) so the TPU ladder measures the real model."""
    from areal_tpu.models import qwen

    if os.environ.get("MICROBENCH_FULL"):
        from bench import MODEL_KW  # bench.py owns the 1.5B dims

        return qwen.ModelConfig(**MODEL_KW)
    return qwen.ModelConfig(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_layers=2,
        num_heads=4,
        num_kv_heads=2,
        dtype="float32",
        tie_word_embeddings=True,
        attention_bias=True,
        rope_theta=10000.0,
    )


_CTX: dict[str, Any] = {}


def _ctx() -> dict[str, Any]:
    """Shared per-process setup (params init + jit are the expensive part;
    every bench reuses one tree)."""
    if _CTX:
        return _CTX
    import jax

    from areal_tpu.models import qwen

    cfg = model_cfg()
    params = jax.jit(lambda k: qwen.init_params(k, cfg))(jax.random.PRNGKey(0))
    _sync(params)
    full = bool(os.environ.get("MICROBENCH_FULL"))
    _CTX.update(
        cfg=cfg,
        params=params,
        full=full,
        page_size=128 if full else 16,
        n_slots=32 if full else 8,
    )
    return _CTX


# ---------------------------------------------------------------------------
# fast benches (the committed CPU baseline)
# ---------------------------------------------------------------------------


@register("paged_decode_step")
def bench_paged_decode_step() -> dict:
    """One forward_decode_paged step for all slots over a warm paged KV."""
    import jax
    import jax.numpy as jnp

    from areal_tpu.inference.paged_kv import init_paged_cache
    from areal_tpu.models import qwen
    from areal_tpu.observability import hw_accounting as hw

    c = _ctx()
    cfg, psz, S = c["cfg"], c["page_size"], 4 * c["n_slots"]
    ctx_len = 7 * psz  # seven warm pages per slot
    wp = ctx_len // psz + 1
    n_pages = S * wp + 1
    cache = init_paged_cache(cfg, n_pages, psz)
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(1, cfg.vocab_size, S), jnp.int32)
    pos = jnp.full((S,), ctx_len, jnp.int32)
    table = jnp.asarray(
        1 + np.arange(S * wp, dtype=np.int32).reshape(S, wp)
    )
    use_kernel = jax.default_backend() == "tpu"
    step = jax.jit(
        lambda i, p, kv, t: qwen.forward_decode_paged(
            c["params"], cfg, i, p, kv, t, page_size=psz, use_kernel=use_kernel
        )[0]
    )
    costs = hw.decode_step_costs(cfg, 1, S, float(ctx_len))
    return {
        "run": lambda: _sync(step(ids, pos, cache, table)),
        "tokens": S,
        "flops": costs["flops"],
        "bytes": costs["bytes"],
    }


@register("paged_attention_interpret")
def bench_paged_attention_interpret() -> dict:
    """Revived interpret-mode stacked paged-attention kernel in isolation
    (ISSUE 17 burn-down): the same Pallas body the TPU runs, executed via
    the interpreter so the CPU baseline pins the kernel's own cost — a
    signature or index-map regression shows up here before any TPU job."""
    import jax
    import jax.numpy as jnp

    c = _ctx()
    from areal_tpu.ops.paged_attention_q8 import paged_attention_stacked

    S, KH, G, hd, psz, wp, L = 4, 2, 6, 128, c["page_size"], 4, 2
    H = KH * G
    N = S * wp + 1
    rng = np.random.default_rng(3)
    q = jnp.asarray(rng.normal(0, 1, (S, H, hd)), jnp.float32)
    k = jnp.asarray(rng.normal(0, 1, (L, KH, N, psz, hd)), jnp.bfloat16)
    v = jnp.asarray(rng.normal(0, 1, (L, KH, N, psz, hd)), jnp.bfloat16)
    pt = jnp.asarray(1 + np.arange(S * wp, dtype=np.int32).reshape(S, wp))
    ctx = wp * psz  # every slot fully warm
    lengths = jnp.full((S,), ctx, jnp.int32)
    fn = jax.jit(
        lambda q, k, v, le, t: paged_attention_stacked(
            q, k, v, jnp.int32(0), le, t,
            pages_per_compute_block=2,
            interpret=True,
        )
    )
    # QK^T + AV over the warm context, one query row per slot
    flops = 4.0 * S * H * hd * ctx
    bytes_ = 2.0 * KH * S * ctx * hd * k.dtype.itemsize + q.nbytes * 2
    return {
        "run": lambda: _sync(fn(q, k, v, lengths, pt)),
        "tokens": S,
        "flops": flops,
        "bytes": bytes_,
    }


def _suffix_prefill_bench(use_kernel: bool) -> dict:
    import jax
    import jax.numpy as jnp

    from areal_tpu.inference.paged_kv import init_paged_cache
    from areal_tpu.models import qwen
    from areal_tpu.observability import hw_accounting as hw

    c = _ctx()
    cfg, psz = c["cfg"], c["page_size"]
    A, B = 4, 2 * psz  # suffix bucket: two pages of new tokens per row
    wp = 4
    cache = init_paged_cache(cfg, A * wp + 1, psz)
    rng = np.random.default_rng(1)
    ids = jnp.asarray(rng.integers(1, cfg.vocab_size, (A, B)), jnp.int32)
    offs = np.full((A,), psz, np.int32)  # one page already cached
    positions = jnp.asarray(offs[:, None] + np.arange(B, dtype=np.int32))
    seg = jnp.ones((A, B), jnp.int32)
    table = jnp.asarray(1 + np.arange(A * wp, dtype=np.int32).reshape(A, wp))
    fn = jax.jit(
        lambda i, p, s, kv, t, o: qwen.forward_prefill_paged(
            c["params"], cfg, i, p, s, kv, t, o, use_kernel=use_kernel
        )[1]
    )
    offs_d = jnp.asarray(offs)
    costs = hw.prefill_costs(cfg, float(A * B))
    return {
        "run": lambda: _sync(fn(ids, positions, seg, cache, table, offs_d)),
        "tokens": A * B,
        "flops": costs["flops"],
        "bytes": costs["bytes"],
    }


@register("suffix_prefill")
def bench_suffix_prefill() -> dict:
    """Radix-suffix prefill: queries attend over one cached prefix page."""
    return _suffix_prefill_bench(False)


@register("suffix_prefill_kernel")
def bench_suffix_prefill_kernel() -> dict:
    """Radix-suffix prefill through the Pallas suffix-prefill kernel
    (ops/paged_suffix_attention.py, chain-mask launch). On CPU this runs
    the kernel body in interpret mode — the honest bar is parity-not-perf;
    the HBM-bound win is measured on TPU (docs/perf.md)."""
    return _suffix_prefill_bench(True)


@register("int8_kv_dequant")
def bench_int8_kv_dequant() -> dict:
    """KV int8 quantize -> dequantize round trip (the serving KV-cache
    compression path; decode reads pay the dequant side every step)."""
    import jax

    from areal_tpu.inference.paged_kv import dequantize_kv, quantize_kv

    c = _ctx()
    cfg = c["cfg"]
    n_tok = 16384 if c["full"] else 8192
    x = jax.numpy.asarray(
        np.random.default_rng(2).normal(
            0, 1, (cfg.num_layers, cfg.num_kv_heads, n_tok, cfg.head_dim_)
        ).astype(np.float32)
    )
    rt = jax.jit(lambda t: dequantize_kv(*quantize_kv(t), t.dtype))
    nelem = float(x.size)
    return {
        "run": lambda: _sync(rt(x)),
        "tokens": None,
        # abs/max/scale/rint/clip on the way down, one fma on the way up
        "flops": 8.0 * nelem,
        # f32 read + int8 write + int8 read + f32 write (+ scales, small)
        "bytes": 10.0 * nelem,
    }


@register("tree_verify_forward")
def bench_tree_verify_forward() -> dict:
    """Ancestor-masked forest forward (no_grad): the tree-verify step of
    speculative/tree decoding — shared prefixes scored once."""
    import jax
    import jax.numpy as jnp

    from areal_tpu.models import qwen
    from areal_tpu.models.tree import build_tree
    from areal_tpu.observability import hw_accounting as hw

    c = _ctx()
    cfg = c["cfg"]
    rng = np.random.default_rng(3)
    base = 48 if c["full"] else 16
    seqs = [list(rng.integers(1, cfg.vocab_size, base + int(rng.integers(0, 8)))) for _ in range(8)]
    for i in range(4, 8):  # force shared prefixes: real GRPO-group shape
        seqs[i] = seqs[i - 4][: base // 2] + seqs[i]
    pack = build_tree(seqs)
    N = pack.n_nodes
    ids = jnp.asarray(pack.tokens, jnp.int32)[None]
    pos = jnp.asarray(pack.depth, jnp.int32)[None]
    seg = jnp.ones((1, N), jnp.int32)
    mask = jnp.asarray(pack.ancestor_mask())[None, None]
    fn = jax.jit(
        lambda i, s, p, m: qwen.forward(
            c["params"], cfg, i, s, p, attn_mask=m, no_grad=True
        )
    )
    costs = hw.prefill_costs(cfg, float(N))
    return {
        "run": lambda: _sync(fn(ids, seg, pos, mask)),
        "tokens": N,
        "flops": costs["flops"],
        "bytes": costs["bytes"],
    }


def _spec_decode_step_bench(use_kernel: bool) -> dict:
    import jax
    import jax.numpy as jnp

    from areal_tpu.inference.paged_kv import init_paged_cache
    from areal_tpu.models import qwen
    from areal_tpu.observability import hw_accounting as hw

    c = _ctx()
    cfg, psz, S = c["cfg"], c["page_size"], 4 * c["n_slots"]
    K = 4  # SpeculativeConfig.spec_depth default
    B = K + 1
    ctx_len = 7 * psz  # seven warm pages per slot (= paged_decode_step)
    wp = (ctx_len + B) // psz + 1
    n_pages = S * wp + 1
    cache = init_paged_cache(cfg, n_pages, psz)
    rng = np.random.default_rng(0)
    pending = jnp.asarray(rng.integers(1, cfg.vocab_size, S), jnp.int32)
    table = jnp.asarray(1 + np.arange(S * wp, dtype=np.int32).reshape(S, wp))
    prefix_lens = jnp.full((S,), ctx_len, jnp.int32)
    positions = jnp.broadcast_to(
        ctx_len + jnp.arange(B, dtype=jnp.int32)[None], (S, B)
    )
    # chain tree: row j attends rows 0..j (lower-triangular ancestor mask)
    mask = jnp.broadcast_to(
        jnp.asarray(np.tril(np.ones((B, B), bool)))[None], (S, B, B)
    )

    def verify(drafts):
        ids_nodes = jnp.concatenate([pending[:, None], drafts], 1)
        hidden, _ks, _vs = qwen.forward_verify_paged(
            c["params"], cfg, ids_nodes, positions, mask, cache, table,
            prefix_lens, use_kernel=use_kernel,
        )
        logits = qwen.compute_logits(c["params"], cfg, hidden)
        targets = jnp.argmax(logits, -1).astype(jnp.int32)  # [S, B]
        hit = (targets[:, :-1] == drafts).astype(jnp.int32)
        accepted = jnp.cumprod(
            jnp.concatenate([jnp.ones((S, 1), jnp.int32), hit], 1), axis=1
        )
        return targets, accepted.sum(1)  # emitted tokens per slot

    fn = jax.jit(verify)
    # oracle: each pass fixes one more chain position (target at depth d
    # depends only on draft rows < d), so K+1 passes reach a fixed point
    drafts = jnp.asarray(rng.integers(1, cfg.vocab_size, (S, K)), jnp.int32)
    for _ in range(K + 1):
        targets, _em = fn(drafts)
        drafts = targets[:, :K]
    _targets, emitted = fn(drafts)
    assert int(np.asarray(emitted).min()) == B, "oracle draft did not converge"
    costs = hw.decode_step_costs(cfg, 1, S * B, float(ctx_len))
    return {
        "run": lambda: _sync(fn(drafts)),
        "tokens": S * B,
        "flops": costs["flops"],
        "bytes": costs["bytes"],
    }


@register("spec_decode_step")
def bench_spec_decode_step() -> dict:
    """Speculative verify step at full acceptance: forward_verify_paged
    over K+1 chain rows per slot plus the greedy accept walk. Setup
    iterates the verify fn to the model's own self-consistent greedy
    chain (an oracle draft), so every row lands and one timed call emits
    (K+1) x slots tokens — divide this bench's tok/s by
    paged_decode_step's for the raw speculation multiplier."""
    return _spec_decode_step_bench(False)


@register("spec_decode_step_kernel")
def bench_spec_decode_step_kernel() -> dict:
    """Oracle-draft speculative verify through the Pallas tree-verify
    launch (ops/paged_suffix_attention.py, ancestor-mask operand). On CPU
    the kernel runs in interpret mode — parity is the bar here, the
    HBM-bound win is a TPU measurement (docs/perf.md)."""
    return _spec_decode_step_bench(True)


@register("radix_match")
def bench_radix_match() -> dict:
    """Host-side radix prefix walk: the admission-time lookup kernel_probe
    times as the radix_match phase. Pure host — no device work."""
    from areal_tpu.inference.paged_kv import PagePool, RadixPrefixCache

    c = _ctx()
    psz = c["page_size"]
    depth = 8  # pages per published prompt
    n_pub, n_probe = 64, 32
    pool = PagePool(n_pub * depth + 64)
    cache = RadixPrefixCache(pool, psz, max_pages=n_pub * depth)
    rng = np.random.default_rng(4)
    shared = rng.integers(1, 200, 4 * psz)
    pubs = []
    for _ in range(n_pub):
        tail = rng.integers(1, 200, (depth - 4) * psz)
        pubs.append(np.concatenate([shared, tail]))
    for ids in pubs:
        pages = pool.alloc(depth)
        assert pages is not None
        cache.insert(ids, pages, [0] * depth)
    probes = [pubs[i % n_pub][: (depth - 1) * psz] for i in range(n_probe)]

    def run() -> int:
        hits = 0
        for p in probes:
            pages, _v = cache.match(p)
            hits += len(pages)
        return hits

    return {
        "run": run,
        "tokens": sum(len(p) for p in probes),
        "flops": None,
        "bytes": None,
    }


@register("weight_stage_encode")
def bench_weight_stage_encode() -> dict:
    """Weight-bucket wire encoding: the per-bucket host cost of a staged
    mem-mode weight push (server.encode_weight_bucket)."""
    from areal_tpu.inference.server import encode_weight_bucket

    c = _ctx()
    mb = 64 if c["full"] else 4
    arr = np.random.default_rng(5).normal(0, 1, (mb * 256 * 1024,)).astype(np.float32)
    entries = [("layers/wq", arr), ("layers/wo", arr[: arr.size // 2])]
    nbytes = float(sum(a.nbytes for _n, a in entries))
    return {
        "run": lambda: len(encode_weight_bucket(entries)),
        "tokens": None,
        "flops": None,
        "bytes": 2.0 * nbytes,  # one read + one write of the payload
    }


# ---------------------------------------------------------------------------
# heavy benches (engine/trainer level; subsume the retired prof_* scripts)
# ---------------------------------------------------------------------------


def _make_engine():
    import jax

    from areal_tpu.api.config import MeshConfig, ServerConfig
    from areal_tpu.inference.decode_engine import DecodeEngine

    c = _ctx()
    full = c["full"]
    scfg = ServerConfig(
        max_batch_size=128 if full else 8,
        max_seq_len=512 if full else 128,
        decode_steps_per_call=32 if full else 8,
        quantization=os.environ.get("BENCH_QUANT", "none"),
        mesh=MeshConfig(data=-1, fsdp=1, seq=1, model=1),
    )
    # the engine's weight-update paths DONATE the served buffers (the LoRA
    # fold frees the fold base) — hand it a private host copy so the shared
    # _ctx() tree survives for later benches in the same process
    host = jax.tree.map(np.asarray, c["params"])
    eng = DecodeEngine(scfg, params=host, model_cfg=c["cfg"])
    eng.initialize()
    return eng, scfg, host


@register("decode_engine_steady", heavy=True)
def bench_decode_engine_steady() -> dict:
    """Live DecodeEngine steady state: continuous-batched tok/s plus the
    kernel probe's achieved roofline over the same window (was
    prof_decode / prof_r3 phase_decode; BENCH_QUANT=int8 gives the
    prof_r4 phase_int8 comparison)."""
    import threading

    from areal_tpu.api.io_struct import GenerationHyperparameters, ModelRequest

    c = _ctx()
    eng, scfg, _host = _make_engine()
    eng.start()
    try:
        rng = np.random.default_rng(0)
        n_req = 128 if c["full"] else 32
        new_tokens = 128 if c["full"] else 32
        plen = scfg.max_seq_len // 4
        done = threading.Event()
        results: list = []
        lock = threading.Lock()

        def cb(resp):
            with lock:
                results.append(resp)
                if len(results) == n_req:
                    done.set()

        warm = ModelRequest(
            input_ids=rng.integers(1, c["cfg"].vocab_size, plen).tolist(),
            gconfig=GenerationHyperparameters(max_new_tokens=8, greedy=True),
        )
        eng.generate_sync(warm, timeout=600.0)
        t0 = time.monotonic()
        for _ in range(n_req):
            eng.submit(
                ModelRequest(
                    input_ids=rng.integers(1, c["cfg"].vocab_size, plen).tolist(),
                    gconfig=GenerationHyperparameters(
                        max_new_tokens=new_tokens, temperature=1.0
                    ),
                ),
                cb,
            )
        done.wait(timeout=1200.0)
        dt = max(1e-9, time.monotonic() - t0)
        with lock:
            gen = sum(len(r.output_tokens) for r in results)
        ks = eng.kernel_stats()
        return {
            "entry": {
                "wall_s": dt,
                "tok_s": gen / dt,
                "flops": ks.get("flops_total"),
                "bytes": None,
                "roofline_frac": ks.get("roofline_fraction"),
                "noise_frac": 0.0,
                "dominant_phase": ks.get("dominant_phase"),
                "requests_done": len(results),
            }
        }
    finally:
        eng.stop()


@register("train_step", heavy=True)
def bench_train_step() -> dict:
    """Fwd+bwd cross-entropy step — the optimizer-shaped FLOPs path (was
    prof_r3 phase_train)."""
    import jax
    import jax.numpy as jnp

    from areal_tpu.models import qwen
    from areal_tpu.observability import hw_accounting as hw

    c = _ctx()
    cfg = c["cfg"]
    B, T = (8, 512) if c["full"] else (4, 64)
    rng = np.random.default_rng(6)
    ids = jnp.asarray(rng.integers(1, cfg.vocab_size, (B, T)), jnp.int32)
    labels = jnp.asarray(rng.integers(1, cfg.vocab_size, (B, T)), jnp.int32)
    seg = jnp.ones((B, T), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))

    def loss_fn(p, i, l):
        logits = qwen.forward(p, cfg, i, seg, pos)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32))
        return -jnp.take_along_axis(logp, l[..., None], -1).mean()

    grad = jax.jit(jax.grad(loss_fn))
    return {
        "run": lambda: _sync(grad(c["params"], ids, labels)),
        "tokens": B * T,
        "flops": hw.train_step_flops(cfg, float(B * T)),
        "bytes": None,
        "warmup": 1,
    }


@register("tree_train", heavy=True)
def bench_tree_train() -> dict:
    """Grad through the ancestor-mask forest forward — the tree-training
    FLOP-reduction path (was prof_r5 phase_tree)."""
    import jax
    import jax.numpy as jnp

    from areal_tpu.models import qwen
    from areal_tpu.models.tree import build_tree
    from areal_tpu.observability import hw_accounting as hw

    c = _ctx()
    cfg = c["cfg"]
    rng = np.random.default_rng(7)
    base = 64 if c["full"] else 20
    seqs = [list(rng.integers(1, cfg.vocab_size, base)) for _ in range(8)]
    for i in range(4, 8):
        seqs[i] = seqs[i - 4][: base // 2] + seqs[i]
    pack = build_tree(seqs)
    N = pack.n_nodes
    ids = jnp.asarray(pack.tokens, jnp.int32)[None]
    pos = jnp.asarray(pack.depth, jnp.int32)[None]
    seg = jnp.ones((1, N), jnp.int32)
    mask = jnp.asarray(pack.ancestor_mask())[None, None]
    labels = jnp.asarray(np.roll(pack.tokens, -1), jnp.int32)[None]

    def loss_fn(p):
        logits = qwen.forward(p, cfg, ids, seg, pos, attn_mask=mask)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32))
        return -jnp.take_along_axis(logp, labels[..., None], -1).mean()

    grad = jax.jit(jax.grad(loss_fn))
    return {
        "run": lambda: _sync(grad(c["params"])),
        "tokens": N,
        "flops": hw.train_step_flops(cfg, float(N)),
        "bytes": None,
        "warmup": 1,
    }


@register("weight_update", heavy=True)
def bench_weight_update() -> dict:
    """Paused weight-update latency on a live engine: the LoRA-delta fold
    (measured, LoRA FIRST — any full update invalidates the engine's
    delta-fold base by design) plus one full mem-path push reported as
    ``full_update_s`` (was prof_r4 phase_wu)."""
    import jax

    c = _ctx()
    eng, _scfg, host = _make_engine()
    eng.start()
    try:
        rng = np.random.default_rng(8)
        lora = {}
        for t in ("wq", "wk", "wv", "wo"):
            L, d_in, d_out = c["params"]["layers"][t].shape
            lora[f"layers/{t}_lora_a"] = rng.normal(0, 0.01, (L, d_in, 32)).astype(np.float32)
            # b == 0: repeated folds leave the served weights unchanged
            lora[f"layers/{t}_lora_b"] = np.zeros((L, 32, d_out), np.float32)
        version = [1]

        def fold():
            version[0] += 1
            eng.pause_generation()
            eng.update_weights_lora(lora, scale=0.5, version=version[0])
            eng.continue_generation()
            _sync(eng.params["layers"]["wq"])

        fold()  # warm the fold-fn compile outside the measured window
        wall, noise, _s = _measure(fold, iters=3, warmup=0)
        # one full mem-path push, measured once (it invalidates the LoRA
        # base, so it must come LAST)
        t0 = time.monotonic()
        eng.pause_generation()
        eng.update_weights_from_params(host, version=version[0] + 1)
        eng.continue_generation()
        _sync(eng.params["layers"]["wq"])
        full_s = time.monotonic() - t0
        return {
            "entry": {
                "wall_s": wall,
                "tok_s": None,
                "flops": None,
                "bytes": float(sum(a.nbytes for a in lora.values())),
                "roofline_frac": None,
                "noise_frac": noise,
                "full_update_s": full_s,
            }
        }
    finally:
        eng.stop()


# ---------------------------------------------------------------------------
# harness
# ---------------------------------------------------------------------------


def _measure(fn: Callable, *, iters: int, warmup: int) -> tuple[float, float, list[float]]:
    """Warm-up + median-of-N; noise_frac = MAD/median of the measured
    samples (robust against single outliers — a max-based spread on
    sub-ms benches reads one scheduler hiccup as 50-80%% "noise" and
    would widen the compare margin past a genuine 2x regression)."""
    for _ in range(warmup):
        fn()
    samples = []
    for _ in range(max(1, iters)):
        t0 = time.monotonic()
        fn()
        samples.append(time.monotonic() - t0)
    med = statistics.median(samples)
    mad = statistics.median([abs(s - med) for s in samples])
    noise = mad / med if med > 0 else 0.0
    return med, noise, samples


def _peaks() -> dict[str, Any]:
    import jax

    from areal_tpu.observability import hw_accounting as hw

    dev = jax.devices()[0]
    pf = hw.chip_peak_flops(dev)
    pb = hw.chip_peak_membw(dev)
    if pf is not None:
        return {"flops": pf, "membw": pb, "source": "spec"}
    cf, cb = hw.calibrate_host_peaks()
    return {"flops": cf, "membw": cb, "source": "calibrated"}


def run_bench(name: str, *, iters: int, warmup: int, peaks: dict) -> dict:
    from areal_tpu.observability import kernel_probe

    spec = REGISTRY[name]
    b = spec["fn"]()
    if "entry" in b:
        return b["entry"]
    wall, noise, _samples = _measure(
        b["run"], iters=iters, warmup=b.get("warmup", warmup)
    )
    tokens = b.get("tokens")
    flops = b.get("flops")
    nbytes = b.get("bytes")
    return {
        "wall_s": wall,
        "tok_s": (tokens / wall) if tokens else None,
        "flops": flops,
        "bytes": nbytes,
        "roofline_frac": kernel_probe.roofline_fraction(
            flops or 0.0, nbytes or 0.0, wall, peaks["flops"], peaks["membw"]
        ),
        "noise_frac": noise,
    }


def run_suite(
    names: list[str], *, iters: int = DEFAULT_ITERS, warmup: int = DEFAULT_WARMUP
) -> dict:
    import jax

    peaks = _peaks()
    out = {
        "schema": 1,
        "backend": jax.default_backend(),
        "full": bool(os.environ.get("MICROBENCH_FULL")),
        "peaks": peaks,
        "benches": {},
    }
    for name in names:
        t0 = time.monotonic()
        entry = run_bench(name, iters=iters, warmup=warmup, peaks=peaks)
        out["benches"][name] = entry
        rf = entry.get("roofline_frac")
        print(
            f"[microbench] {name}: wall={entry['wall_s']:.6f}s"
            + (f" tok/s={entry['tok_s']:.1f}" if entry.get("tok_s") else "")
            + (f" roofline={rf:.4f}" if rf is not None else "")
            + f" (setup+run {time.monotonic()-t0:.1f}s)",
            flush=True,
        )
    return out


# ---------------------------------------------------------------------------
# compare gate
# ---------------------------------------------------------------------------


def compare(
    current: dict, baseline: dict, threshold: float = DEFAULT_THRESHOLD
) -> dict:
    """Noise-aware regression check of ``current`` against ``baseline``.

    Per shared bench: regression iff
    ``cur.wall_s > base.wall_s * (1 + max(threshold, 2*noise)) + floor``
    where noise is the larger of the two runs' measured noise_frac.
    Entries only in current are "new", only in baseline "missing" — both
    are warnings (a renamed bench must not hard-fail the gate; the
    baseline refresh is the reviewed fix)."""
    cur = current.get("benches", {})
    base = baseline.get("benches", {})
    out: dict[str, list] = {"regressions": [], "ok": [], "new": [], "missing": []}
    for name, c in cur.items():
        b = base.get(name)
        if b is None:
            out["new"].append(name)
            continue
        noise = max(
            float(c.get("noise_frac") or 0.0), float(b.get("noise_frac") or 0.0)
        )
        margin = max(threshold, 2.0 * noise)
        limit = float(b["wall_s"]) * (1.0 + margin) + NOISE_FLOOR_S
        if float(c["wall_s"]) > limit:
            out["regressions"].append(
                {
                    "bench": name,
                    "wall_s": float(c["wall_s"]),
                    "baseline_s": float(b["wall_s"]),
                    "limit_s": limit,
                    "margin": margin,
                }
            )
        else:
            out["ok"].append(name)
    out["missing"] = sorted(set(base) - set(cur))
    return out


def _print_compare(result: dict) -> None:
    for r in result["regressions"]:
        print(
            f"[microbench] REGRESSION {r['bench']}: {r['wall_s']:.6f}s vs"
            f" baseline {r['baseline_s']:.6f}s (limit {r['limit_s']:.6f}s,"
            f" margin {r['margin']:.0%})",
            flush=True,
        )
    for n in result["new"]:
        print(f"[microbench] WARN new bench not in baseline: {n}", flush=True)
    for n in result["missing"]:
        print(f"[microbench] WARN baseline bench not run: {n}", flush=True)
    print(
        f"[microbench] compare: {len(result['ok'])} ok,"
        f" {len(result['regressions'])} regression(s),"
        f" {len(result['new'])} new, {len(result['missing'])} missing",
        flush=True,
    )


# ---------------------------------------------------------------------------
# --learn-gate: on-chip RL learning gate (was prof_learn.py)
# ---------------------------------------------------------------------------

LEARN_TARGET = 7
LEARN_GROUP = 4


def _learn_reward(prompt, completions, prompt_ids, completion_ids, **kw):
    return 1.0 if LEARN_TARGET in completion_ids else 0.0


def learn_gate() -> int:
    """Full-stack learning smoke on the REAL backend: a tiny from-scratch
    policy must learn to emit LEARN_TARGET through DecodeEngine-over-HTTP,
    staleness-gated async rollout, GRPO advantages, and mem-mode weight
    updates. Prints ``LEARN_RESULT {json}``; exit 0 iff it learned.
    (No pretrained weights exist in the zero-egress image, so this is the
    hardware-validated stand-in for a benchmark reward curve.)"""
    import tempfile

    import jax

    from areal_tpu.api.config import (
        DatasetConfig,
        EvaluatorConfig,
        InferenceEngineConfig,
        MeshConfig,
        MicroBatchSpec,
        NormConfig,
        OptimizerConfig,
        PPOActorConfig,
        PPOConfig,
        RecoverConfig,
        SaverConfig,
        ServerConfig,
        StatsLoggerConfig,
    )
    from areal_tpu.api.io_struct import (
        FinetuneSpec,
        GenerationHyperparameters,
        ModelRequest,
    )
    from areal_tpu.engine.train_engine import JaxTrainEngine
    from areal_tpu.inference.client import RemoteJaxEngine
    from areal_tpu.inference.decode_engine import DecodeEngine
    from areal_tpu.inference.server import ServerThread
    from areal_tpu.models import qwen
    from areal_tpu.trainer.rl_trainer import PPOTrainer
    from areal_tpu.workflow.rlvr import RLVRWorkflow

    platform = jax.default_backend()
    print(f"[learn] backend={platform}", flush=True)
    model_cfg_ = qwen.ModelConfig(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_layers=2,
        num_heads=4,
        num_kv_heads=2,
        dtype="float32",
        tie_word_embeddings=True,
        attention_bias=True,
        rope_theta=10000.0,
    )
    root = tempfile.mkdtemp(prefix="learn_gate_")
    actor_cfg = PPOActorConfig(
        init_from_scratch=True,
        dtype="float32",
        param_dtype="float32",
        mesh=MeshConfig(data=-1, fsdp=1, seq=1, model=1),
        optimizer=OptimizerConfig(lr=2e-2, lr_scheduler_type="constant"),
        mb_spec=MicroBatchSpec(max_tokens_per_mb=100_000),
        bucket_step=64,
        group_size=LEARN_GROUP,
        ppo_n_minibatches=1,
        adv_norm=NormConfig(
            mean_level="group", std_level="group", group_size=LEARN_GROUP
        ),
        kl_ctl=0.0,
        use_decoupled_loss=True,
        prox_logp_mode="recompute",
        eps_clip=0.4,
        temperature=1.0,
    )
    engine = JaxTrainEngine(actor_cfg, model_config=model_cfg_)
    engine.initialize(FinetuneSpec(1, 32, 8))
    scfg = ServerConfig(
        max_batch_size=8,
        max_seq_len=64,
        decode_steps_per_call=4,
        seed=0,
        mesh=MeshConfig(data=-1, fsdp=1, seq=1, model=1),
    )
    dec = DecodeEngine(
        scfg, params=jax.tree.map(np.asarray, engine.params), model_cfg=model_cfg_
    )
    dec.initialize()
    server = ServerThread(scfg, dec)
    server.start()
    rollout = RemoteJaxEngine(
        InferenceEngineConfig(
            max_concurrent_rollouts=8,
            consumer_batch_size=4,
            max_head_offpolicyness=2,
            request_timeout=300,
        ),
        addresses=[server.address],
    )
    rollout.initialize()
    cfg = PPOConfig(
        experiment_name="learn_onchip",
        trial_name="t0",
        total_train_epochs=12,
        weight_update_mode="mem",
        gconfig=GenerationHyperparameters(
            n_samples=LEARN_GROUP, max_new_tokens=4, temperature=1.0
        ),
        train_dataset=DatasetConfig(batch_size=4, shuffle=True),
        actor=actor_cfg,
        saver=SaverConfig(fileroot=root),
        checkpointer=SaverConfig(fileroot=root),
        evaluator=EvaluatorConfig(fileroot=root),
        recover=RecoverConfig(mode="disabled", fileroot=root),
        stats_logger=StatsLoggerConfig(fileroot=root),
    )
    cfg.cluster.fileroot = root
    rng = np.random.default_rng(0)
    dataset = [{"prompt_ids": rng.integers(20, 200, 4).tolist()} for _ in range(32)]
    trainer = PPOTrainer(cfg, dataset, rollout=rollout, actor_engine=engine)

    def hit_rate(n=16):
        import asyncio

        async def probe_fn():
            reqs = [
                ModelRequest(
                    input_ids=row["prompt_ids"],
                    gconfig=GenerationHyperparameters(
                        n_samples=1, max_new_tokens=4, greedy=True
                    ),
                )
                for row in dataset[:n]
            ]
            resps = await asyncio.gather(*[rollout.agenerate(r) for r in reqs])
            return float(np.mean([LEARN_TARGET in r.output_tokens for r in resps]))

        return asyncio.run(probe_fn())

    t0 = time.monotonic()
    before = hit_rate()
    trainer.train(workflow=RLVRWorkflow(_learn_reward, cfg.gconfig))
    after = hit_rate()
    dt = time.monotonic() - t0
    ok = after > max(0.5, before + 0.3)
    print(
        "LEARN_RESULT "
        + json.dumps(
            {
                "backend": platform,
                "before": before,
                "after": after,
                "learned": ok,
                "secs": round(dt, 1),
                "versions": engine.get_version(),
            }
        ),
        flush=True,
    )
    server.stop()
    return 0 if ok else 1


# ---------------------------------------------------------------------------
# --ladder: unattended on-chip measurement ladder (was prof_ladder.py)
# ---------------------------------------------------------------------------

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_MB = (
    "import os; os.environ.setdefault('MICROBENCH_FULL', '1')\n"
    "from areal_tpu.tools import microbench\n"
)

# (name, child budget seconds, code). Ordering: the round's must-have (a
# full valid bench) FIRST; on-chip kernel parity SECOND; component
# microbenches after. The mb_* steps replace the retired prof_r3/r4/r5
# scripts with registry entries (docs/perf.md "Reproduction").
LADDER_STEPS = [
    ("bench_full", 1600, "import bench; bench.main()"),
    (
        "tests_tpu",
        1500,
        "import pytest\n"
        "rc = pytest.main(['tests_tpu', '-x', '-q', '--no-header'])\n"
        "raise SystemExit(int(rc))",
    ),
    (
        "bench_decode_int8",
        700,
        "import os; os.environ['BENCH_QUANT'] = 'int8'\n"
        "import bench; raise SystemExit(bench._run_phase_child('decode'))",
    ),
    (
        "bench_longctx_int8kv",
        500,
        "import os\n"
        "os.environ['BENCH_QUANT'] = 'int8'\n"
        "os.environ['BENCH_KV_QUANT'] = 'int8'\n"
        "import bench; raise SystemExit(bench._run_phase_child('longctx'))",
    ),
    (
        "mb_fast",
        900,
        _MB + "raise SystemExit(microbench.main(['--out', '/tmp/mb_fast_tpu.json']))",
    ),
    (
        "mb_decode_steady",
        1500,
        _MB
        + "raise SystemExit(microbench.main(['--benches', 'decode_engine_steady',"
        " '--out', '/tmp/mb_decode_steady.json']))",
    ),
    (
        "mb_weight_update",
        900,
        _MB
        + "raise SystemExit(microbench.main(['--benches', 'weight_update',"
        " '--out', '/tmp/mb_weight_update.json']))",
    ),
    (
        "mb_train_step",
        2400,
        _MB
        + "raise SystemExit(microbench.main(['--benches', 'train_step',"
        " '--out', '/tmp/mb_train_step.json']))",
    ),
    (
        "mb_tree_train",
        1500,
        _MB
        + "raise SystemExit(microbench.main(['--benches', 'tree_train',"
        " '--out', '/tmp/mb_tree_train.json']))",
    ),
    (
        "rl_learn_onchip",
        1200,
        "from areal_tpu.tools import microbench\n"
        "raise SystemExit(microbench.main(['--learn-gate']))",
    ),
]

# the alarm handler must RAISE (not default-terminate): only a normal
# interpreter exit runs the PJRT client teardown that releases the remote
# pool lease — an abrupt signal death wedges it like a SIGKILL does
_ALARM_PREAMBLE = (
    "import signal, sys, os\n"
    "def _die(s, f):\n"
    "    raise SystemExit('ladder alarm: budget exceeded')\n"
    "signal.signal(signal.SIGALRM, _die)\n"
)

# persistent compile cache shared with bench.py phase children (replays
# from prior green runs keep cold starts inside the step budgets); the
# helper gates on backend==tpu so a CPU fallback can't poison the cache
_CACHE_LINE = (
    "from areal_tpu.utils.compile_cache import enable_persistent_cache\n"
    "enable_persistent_cache()\n"
)

PROBE_CODE = (
    _ALARM_PREAMBLE
    + "signal.alarm(110)\n"
    "import jax, jax.numpy as jnp, numpy as np\n"
    "x = jnp.ones((128, 128), jnp.bfloat16)\n"
    "v = np.asarray((x @ x))[0, 0]\n"
    "print('PROBE_OK', jax.default_backend(), flush=True)\n"
)

_DONE_PATH = os.path.join(REPO, ".bench_cache", "ladder_done.json")


def _ladder_log(msg: str) -> None:
    print(f"[ladder {time.strftime('%H:%M:%S')}] {msg}", flush=True)


def _ladder_probe() -> bool:
    import subprocess

    try:
        p = subprocess.run(
            [sys.executable, "-c", PROBE_CODE],
            cwd=REPO,
            capture_output=True,
            text=True,
            timeout=180,
        )
        ok = "PROBE_OK tpu" in p.stdout
    except subprocess.TimeoutExpired:
        # child wedged in C past its in-child alarm — report blocked so the
        # ladder stops cleanly instead of queueing more hangs
        ok = False
    _ladder_log(f"probe: {'OK' if ok else 'blocked'}")
    return ok


def _ladder_run_step(name: str, budget: int, code: str) -> bool:
    import signal
    import subprocess

    # _CACHE_LINE initializes a TPU client, which CLAIMS the pool lease —
    # bench_full is a phase-SPAWNING parent whose children must make their
    # own claims, so the parent must not hold the lease against them
    cache = "" if name == "bench_full" else _CACHE_LINE
    child = (
        _ALARM_PREAMBLE
        + f"signal.alarm({budget})\n"
        + "sys.path.insert(0, %r)\n" % REPO
        + cache
    ) + code
    _ladder_log(f"step {name} (budget {budget}s)")
    t0 = time.monotonic()
    out_path = f"/tmp/ladder_{name}.log"
    with open(out_path, "w") as f:
        proc = subprocess.Popen(
            [sys.executable, "-u", "-c", child],
            cwd=REPO,
            stdout=f,
            stderr=subprocess.STDOUT,
            start_new_session=True,
        )
        try:
            rc = proc.wait(timeout=budget + 180)
        except subprocess.TimeoutExpired:
            _ladder_log(f"step {name}: HARD TIMEOUT, SIGKILL (lease at risk)")
            try:
                os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
            proc.wait()
            return False
    dt = time.monotonic() - t0
    _ladder_log(f"step {name}: rc={rc} in {dt:.0f}s -> {out_path}")
    return rc == 0


def _ladder_load_done() -> dict:
    try:
        with open(_DONE_PATH) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return {}


def _ladder_mark_done(name: str) -> None:
    done = _ladder_load_done()
    done[name] = time.strftime("%Y-%m-%dT%H:%M:%S")
    os.makedirs(os.path.dirname(_DONE_PATH), exist_ok=True)
    with open(_DONE_PATH, "w") as f:
        json.dump(done, f, indent=1)


def ladder_main(start: int = 0, force: bool = False) -> int:
    """Run LADDER_STEPS unattended: every child exits CLEANLY on overrun
    (SIGALRM raises), a TPU probe runs between steps, and completed steps
    are recorded under .bench_cache/ so reruns skip them."""
    done = {} if force else _ladder_load_done()
    for i, (name, budget, code) in enumerate(LADDER_STEPS[start:], start):
        if name in done:
            _ladder_log(f"step {name}: already completed {done[name]}, skipping")
            continue
        if not _ladder_probe():
            _ladder_log(f"tunnel blocked before step {i} ({name}); stopping ladder")
            return 1
        ok = _ladder_run_step(name, budget, code)
        if name == "bench_full":
            # bench.main() exits 0 even when every phase died (the driver
            # contract: always print one JSON line) — success for
            # done-marking means the harvested payload carries a real LIVE
            # pipeline number, not a cache fallback or 0.0
            payload = None
            try:
                lines = open(f"/tmp/ladder_{name}.log").read().splitlines()
                for ln in reversed(lines):
                    if not (ln.startswith("{") and '"metric"' in ln):
                        continue
                    try:
                        payload = json.loads(ln)  # a truncated line must not
                    except json.JSONDecodeError:  # poison the snapshot
                        continue
                    with open(os.path.join(REPO, "BENCH_mid.json"), "w") as f:
                        json.dump(payload, f)
                        f.write("\n")
                    _ladder_log(f"BENCH_mid.json written: {ln[:120]}")
                    break
            except OSError as e:
                _ladder_log(f"snapshot harvest failed: {e}")
            srcs = (payload or {}).get("detail", {}).get("sources", {})
            ok = (
                payload is not None
                and payload.get("value", 0) > 0
                and srcs.get("decode", "live") == "live"
                and srcs.get("train", "live") == "live"
            )
        if ok:
            _ladder_mark_done(name)
        if not ok and not _ladder_probe():
            _ladder_log(f"tunnel died during {name}; stopping ladder")
            return 1
    _ladder_log("ladder complete")
    return 0


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def fast_names() -> list[str]:
    return [n for n, s in REGISTRY.items() if not s["heavy"]]


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="microbench", description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter
    )
    ap.add_argument("--list", action="store_true", help="list registered benches")
    ap.add_argument(
        "--benches", help="comma-separated bench names (default: all fast benches)"
    )
    ap.add_argument(
        "--heavy", action="store_true", help="include the heavy engine-level benches"
    )
    ap.add_argument("--iters", type=int, default=DEFAULT_ITERS)
    ap.add_argument("--warmup", type=int, default=DEFAULT_WARMUP)
    ap.add_argument("--out", help="write results JSON here")
    ap.add_argument("--compare", help="baseline JSON; exit 1 on regression")
    ap.add_argument("--threshold", type=float, default=DEFAULT_THRESHOLD)
    ap.add_argument(
        "--learn-gate", action="store_true", help="run the on-chip RL learning gate"
    )
    ap.add_argument(
        "--ladder", action="store_true", help="run the unattended measurement ladder"
    )
    ap.add_argument("--from", dest="ladder_from", type=int, default=0, metavar="N")
    ap.add_argument("--force", action="store_true", help="ladder: ignore done-file")
    args = ap.parse_args(argv)

    if args.list:
        for n, s in REGISTRY.items():
            kind = "heavy" if s["heavy"] else "fast"
            print(f"{n:22s} [{kind}] {s['doc']}")
        return 0
    if args.learn_gate:
        return learn_gate()
    if args.ladder:
        return ladder_main(args.ladder_from, args.force)

    if args.benches:
        names = [n.strip() for n in args.benches.split(",") if n.strip()]
        unknown = [n for n in names if n not in REGISTRY]
        if unknown:
            print(f"[microbench] unknown bench(es): {unknown}", file=sys.stderr)
            return 2
    else:
        names = [
            n for n, s in REGISTRY.items() if args.heavy or not s["heavy"]
        ]

    result = run_suite(names, iters=args.iters, warmup=args.warmup)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f, indent=1)
            f.write("\n")
        print(f"[microbench] wrote {args.out}", flush=True)

    if args.compare:
        with open(args.compare) as f:
            baseline = json.load(f)
        cmp_res = compare(result, baseline, threshold=args.threshold)
        _print_compare(cmp_res)
        return 1 if cmp_res["regressions"] else 0
    print(json.dumps({"benches": result["benches"]}, indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
