"""arealint CLI — run the areal_tpu static-analysis suite.

Usage:
    python -m areal_tpu.tools.arealint [paths ...] [options]

With no paths, analyzes the installed ``areal_tpu`` package. Options:

    --format {text,json,sarif}
                           output format (default text); sarif emits a
                           SARIF 2.1.0 document for CI code-scanning
                           annotation
    --rules CSV            restrict to rule families (ASY,JAX,THR,CFG,OBS,
                           EXC,SIG,PRF,DON,SHD,RCP) or individual ids
    --baseline PATH        baseline file (default: areal_tpu/analysis/
                           baseline.json)
    --no-baseline          report every finding, ignoring the baseline
    --write-baseline       rewrite the baseline from the current findings
                           (reasons for persisting entries are carried over;
                           new entries get an empty reason to fill in)
    --changed-only         restrict the run to .py files the working tree
                           changed vs HEAD (staged, unstaged, and
                           untracked), intersected with the requested
                           paths — the fast local/CI-diff iteration mode
    --list-rules           print the rule catalog and exit

Exit codes (the CI contract):
    0  clean — no findings beyond the baseline. A --changed-only run
       whose changed set is EMPTY also exits 0 ("nothing to check" is
       clean by definition; it prints a note so a misconfigured CI diff
       doesn't silently pass) — gate jobs that must always scan
       everything simply omit the flag
    1  at least one non-baselined finding
    2  usage or internal error (bad path, malformed baseline, not a git
       worktree under --changed-only, …)
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys
from pathlib import Path

from areal_tpu.analysis import (
    Analyzer,
    default_baseline_path,
    default_package_root,
)
from areal_tpu.analysis.core import load_baseline, render_baseline

EXIT_CLEAN = 0
EXIT_FINDINGS = 1
EXIT_ERROR = 2

_SARIF_LEVELS = {"error": "error", "warning": "warning"}


def changed_python_files(repo_root: Path) -> list[Path] | None:
    """Absolute paths of .py files the working tree changed vs HEAD:
    staged + unstaged (``git diff HEAD``) plus untracked. None when git
    is unavailable or the directory is not a worktree.

    ``--relative`` keeps the diff output relative to ``repo_root`` (and
    scoped to its subtree) even when the git toplevel is a parent
    directory — without it a monorepo layout would join
    toplevel-relative names onto repo_root, drop every file as
    non-existent, and silently report "nothing to check".
    ``ls-files`` is cwd-relative already."""
    def git(*args: str) -> list[str] | None:
        try:
            out = subprocess.run(
                ["git", *args],
                cwd=repo_root,
                capture_output=True,
                text=True,
                timeout=30,
            )
        except (OSError, subprocess.TimeoutExpired):
            return None
        if out.returncode != 0:
            return None
        return [ln for ln in out.stdout.splitlines() if ln.strip()]

    diff = git("diff", "--name-only", "--relative", "HEAD", "--", "*.py")
    if diff is None:
        # HEAD may be unborn (fresh repo before the first commit): diff
        # against the canonical empty tree so staged files still count,
        # instead of mis-reporting "not a git worktree"
        if git("rev-parse", "--is-inside-work-tree") is not None:
            diff = git(
                "diff", "--name-only", "--relative",
                "4b825dc642cb6eb9a060e54bf8d69288fbee4904", "--", "*.py",
            )
    untracked = git("ls-files", "--others", "--exclude-standard", "--", "*.py")
    if diff is None or untracked is None:
        return None
    seen: dict[str, None] = {}
    for rel in diff + untracked:
        seen.setdefault(rel)
    return [repo_root / rel for rel in seen if (repo_root / rel).exists()]


def render_sarif(result, rule_table: dict[str, str]) -> dict:
    """Minimal SARIF 2.1.0 document: one run, one result per finding,
    rule metadata from the catalog. CI annotators (GitHub code scanning,
    reviewdog) consume this directly."""
    rules_used = sorted({f.rule for f in result.findings})
    return {
        "$schema": (
            "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
            "master/Schemata/sarif-schema-2.1.0.json"
        ),
        "version": "2.1.0",
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "arealint",
                        "informationUri": "docs/static_analysis.md",
                        "rules": [
                            {
                                "id": rid,
                                "shortDescription": {
                                    "text": rule_table.get(rid, rid)
                                },
                            }
                            for rid in rules_used
                        ],
                    }
                },
                "results": [
                    {
                        "ruleId": f.rule,
                        "level": _SARIF_LEVELS.get(f.severity, "error"),
                        "message": {"text": f.message},
                        "locations": [
                            {
                                "physicalLocation": {
                                    "artifactLocation": {"uri": f.path},
                                    "region": {"startLine": f.line},
                                }
                            }
                        ],
                        # line-independent identity for annotation dedup
                        "partialFingerprints": {"arealintKey": f.key},
                    }
                    for f in result.findings
                ],
            }
        ],
    }


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="arealint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument("paths", nargs="*", help="files/directories to analyze")
    p.add_argument("--format", choices=("text", "json", "sarif"), default="text")
    p.add_argument("--rules", default=None, help="comma-separated families/ids")
    p.add_argument("--baseline", default=None, help="baseline json path")
    p.add_argument("--no-baseline", action="store_true")
    p.add_argument("--write-baseline", action="store_true")
    p.add_argument(
        "--changed-only",
        action="store_true",
        help="restrict to .py files changed vs HEAD (plus untracked)",
    )
    p.add_argument("--list-rules", action="store_true")
    args = p.parse_args(argv)

    if args.write_baseline and args.changed_only:
        # the changed set sees a slice of the findings; writing it as THE
        # baseline would delete every entry outside the diff
        print(
            "arealint: --write-baseline cannot be combined with "
            "--changed-only (a diff-scoped run would drop all other "
            "baseline entries)",
            file=sys.stderr,
        )
        return EXIT_ERROR

    if args.write_baseline and args.rules:
        # a rule-filtered run sees only a slice of the findings; writing it
        # as THE baseline would silently delete every other entry (and its
        # hand-written reason)
        print(
            "arealint: --write-baseline cannot be combined with --rules "
            "(a filtered run would drop all other baseline entries)",
            file=sys.stderr,
        )
        return EXIT_ERROR

    rules = args.rules.split(",") if args.rules else None
    try:
        analyzer = Analyzer(rules=rules)
    except Exception as e:  # noqa: BLE001 — bad rule selection / context build
        print(f"arealint: {e}", file=sys.stderr)
        return EXIT_ERROR

    if args.list_rules:
        for rid, title in analyzer.rule_table().items():
            print(f"{rid}  {title}")
        return EXIT_CLEAN

    paths = [Path(s) for s in args.paths] or [default_package_root()]
    for path in paths:
        if not path.exists():
            print(f"arealint: no such path: {path}", file=sys.stderr)
            return EXIT_ERROR

    if args.changed_only:
        repo_root = analyzer.context.repo_root
        changed = changed_python_files(repo_root)
        if changed is None:
            print(
                f"arealint: --changed-only needs a git worktree at "
                f"{repo_root}",
                file=sys.stderr,
            )
            return EXIT_ERROR

        def under_requested(f: Path) -> bool:
            rf = f.resolve()
            for root in paths:
                r = root.resolve()
                if rf == r:
                    return True
                try:
                    rf.relative_to(r)
                    return True
                except ValueError:
                    continue
            return False

        paths = [f for f in changed if under_requested(f)]
        if not paths:
            # exit-code contract: an empty changed set is CLEAN (0) — but
            # loudly, so a misconfigured diff in CI is visible in the log
            print(
                "arealint: --changed-only: no changed .py files under the "
                "requested paths; nothing to check (exit 0)"
            )
            return EXIT_CLEAN

    baseline_path = Path(args.baseline) if args.baseline else default_baseline_path()
    baseline = None
    if not args.no_baseline and not args.write_baseline and baseline_path.exists():
        try:
            baseline = load_baseline(baseline_path)
        except (ValueError, json.JSONDecodeError) as e:
            print(f"arealint: malformed baseline {baseline_path}: {e}", file=sys.stderr)
            return EXIT_ERROR

    result = analyzer.run(paths, baseline=baseline)
    if args.changed_only:
        # a diff-scoped run cannot observe findings outside the changed
        # set, so unmatched baseline entries are OUT OF SCOPE, not stale
        # — reporting them (with --write-baseline advice this mode
        # rejects) would train CI readers to ignore the real signal
        result.stale_baseline = []

    if args.write_baseline:
        old = None
        if baseline_path.exists():
            try:
                old = load_baseline(baseline_path)
            except (ValueError, json.JSONDecodeError):
                old = None
        doc = render_baseline(result.findings, old=old)
        if old:
            # entries for files OUTSIDE the analyzed paths are preserved:
            # this run could not have observed them, and dropping them
            # would delete their hand-written reasons
            repo_root = analyzer.context.repo_root.resolve()
            prefixes = []
            for path in paths:
                try:
                    prefixes.append(
                        path.resolve().relative_to(repo_root).as_posix()
                    )
                except ValueError:
                    prefixes.append(path.as_posix())

            def in_scope(p: str) -> bool:
                return any(
                    p == pre or p.startswith(pre.rstrip("/") + "/")
                    for pre in prefixes
                )

            kept = [
                e for e in old["findings"] if not in_scope(e.get("path", ""))
            ]
            doc["findings"] = sorted(
                kept + doc["findings"],
                key=lambda e: (e.get("path", ""), e.get("rule", ""), e.get("key", "")),
            )
        baseline_path.parent.mkdir(parents=True, exist_ok=True)
        baseline_path.write_text(json.dumps(doc, indent=2) + "\n")
        print(
            f"arealint: wrote {len(doc['findings'])} baseline entries to "
            f"{baseline_path}"
        )
        return EXIT_CLEAN

    if args.format == "json":
        print(json.dumps(result.to_dict(), indent=2))
    elif args.format == "sarif":
        print(json.dumps(render_sarif(result, analyzer.rule_table()), indent=2))
    else:
        for f in result.findings:
            print(f.render())
        tail = (
            f"arealint: {len(result.findings)} finding(s), "
            f"{len(result.baselined)} baselined, "
            f"{len(result.suppressed)} suppressed, "
            f"{result.files_checked} file(s) checked"
        )
        print(tail)
        for entry in result.stale_baseline:
            print(
                "arealint: stale baseline entry (no longer triggered): "
                f"{entry.get('rule')} {entry.get('path')} — consider "
                "regenerating with --write-baseline"
            )
    return EXIT_CLEAN if result.ok else EXIT_FINDINGS


if __name__ == "__main__":
    try:
        raise SystemExit(main())
    except BrokenPipeError:
        # output was piped into a pager/head that closed early: the
        # receiver saw a TRUNCATED report, so fail closed — exiting 0 here
        # would let a `... | head` CI pipeline read real findings as clean
        import os

        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stderr.fileno())
        raise SystemExit(EXIT_ERROR)
