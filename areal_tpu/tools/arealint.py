"""arealint CLI — run the areal_tpu static-analysis suite.

Usage:
    python -m areal_tpu.tools.arealint [paths ...] [options]

With no paths, analyzes the installed ``areal_tpu`` package. Options:

    --format {text,json}   output format (default text)
    --rules CSV            restrict to rule families (ASY,JAX,THR,CFG,OBS)
                           or individual ids (ASY001,...)
    --baseline PATH        baseline file (default: areal_tpu/analysis/
                           baseline.json)
    --no-baseline          report every finding, ignoring the baseline
    --write-baseline       rewrite the baseline from the current findings
                           (reasons for persisting entries are carried over;
                           new entries get an empty reason to fill in)
    --list-rules           print the rule catalog and exit

Exit codes (the CI contract):
    0  clean — no findings beyond the baseline
    1  at least one non-baselined finding
    2  usage or internal error (bad path, malformed baseline, …)
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from areal_tpu.analysis import (
    Analyzer,
    default_baseline_path,
    default_package_root,
)
from areal_tpu.analysis.core import load_baseline, render_baseline

EXIT_CLEAN = 0
EXIT_FINDINGS = 1
EXIT_ERROR = 2


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="arealint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument("paths", nargs="*", help="files/directories to analyze")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--rules", default=None, help="comma-separated families/ids")
    p.add_argument("--baseline", default=None, help="baseline json path")
    p.add_argument("--no-baseline", action="store_true")
    p.add_argument("--write-baseline", action="store_true")
    p.add_argument("--list-rules", action="store_true")
    args = p.parse_args(argv)

    if args.write_baseline and args.rules:
        # a rule-filtered run sees only a slice of the findings; writing it
        # as THE baseline would silently delete every other entry (and its
        # hand-written reason)
        print(
            "arealint: --write-baseline cannot be combined with --rules "
            "(a filtered run would drop all other baseline entries)",
            file=sys.stderr,
        )
        return EXIT_ERROR

    rules = args.rules.split(",") if args.rules else None
    try:
        analyzer = Analyzer(rules=rules)
    except Exception as e:  # noqa: BLE001 — bad rule selection / context build
        print(f"arealint: {e}", file=sys.stderr)
        return EXIT_ERROR

    if args.list_rules:
        for rid, title in analyzer.rule_table().items():
            print(f"{rid}  {title}")
        return EXIT_CLEAN

    paths = [Path(s) for s in args.paths] or [default_package_root()]
    for path in paths:
        if not path.exists():
            print(f"arealint: no such path: {path}", file=sys.stderr)
            return EXIT_ERROR

    baseline_path = Path(args.baseline) if args.baseline else default_baseline_path()
    baseline = None
    if not args.no_baseline and not args.write_baseline and baseline_path.exists():
        try:
            baseline = load_baseline(baseline_path)
        except (ValueError, json.JSONDecodeError) as e:
            print(f"arealint: malformed baseline {baseline_path}: {e}", file=sys.stderr)
            return EXIT_ERROR

    result = analyzer.run(paths, baseline=baseline)

    if args.write_baseline:
        old = None
        if baseline_path.exists():
            try:
                old = load_baseline(baseline_path)
            except (ValueError, json.JSONDecodeError):
                old = None
        doc = render_baseline(result.findings, old=old)
        if old:
            # entries for files OUTSIDE the analyzed paths are preserved:
            # this run could not have observed them, and dropping them
            # would delete their hand-written reasons
            repo_root = analyzer.context.repo_root.resolve()
            prefixes = []
            for path in paths:
                try:
                    prefixes.append(
                        path.resolve().relative_to(repo_root).as_posix()
                    )
                except ValueError:
                    prefixes.append(path.as_posix())

            def in_scope(p: str) -> bool:
                return any(
                    p == pre or p.startswith(pre.rstrip("/") + "/")
                    for pre in prefixes
                )

            kept = [
                e for e in old["findings"] if not in_scope(e.get("path", ""))
            ]
            doc["findings"] = sorted(
                kept + doc["findings"],
                key=lambda e: (e.get("path", ""), e.get("rule", ""), e.get("key", "")),
            )
        baseline_path.parent.mkdir(parents=True, exist_ok=True)
        baseline_path.write_text(json.dumps(doc, indent=2) + "\n")
        print(
            f"arealint: wrote {len(doc['findings'])} baseline entries to "
            f"{baseline_path}"
        )
        return EXIT_CLEAN

    if args.format == "json":
        print(json.dumps(result.to_dict(), indent=2))
    else:
        for f in result.findings:
            print(f.render())
        tail = (
            f"arealint: {len(result.findings)} finding(s), "
            f"{len(result.baselined)} baselined, "
            f"{len(result.suppressed)} suppressed, "
            f"{result.files_checked} file(s) checked"
        )
        print(tail)
        for entry in result.stale_baseline:
            print(
                "arealint: stale baseline entry (no longer triggered): "
                f"{entry.get('rule')} {entry.get('path')} — consider "
                "regenerating with --write-baseline"
            )
    return EXIT_CLEAN if result.ok else EXIT_FINDINGS


if __name__ == "__main__":
    try:
        raise SystemExit(main())
    except BrokenPipeError:
        # output was piped into a pager/head that closed early: the
        # receiver saw a TRUNCATED report, so fail closed — exiting 0 here
        # would let a `... | head` CI pipeline read real findings as clean
        import os

        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stderr.fileno())
        raise SystemExit(EXIT_ERROR)
