"""Installation validator (reference areal/tools/validate_installation.py):
checks imports, device availability, a tiny jit, and the HTTP stack; prints
a PASS/FAIL table and exits nonzero on failure.

Usage: python -m areal_tpu.tools.validate_installation [--tpu]
"""

from __future__ import annotations

import argparse
import sys


def _check(name, fn, results):
    try:
        detail = fn() or ""
        results.append((name, True, str(detail)))
    except Exception as e:  # noqa: BLE001
        results.append((name, False, f"{type(e).__name__}: {e}"))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--tpu", action="store_true", help="require a TPU backend")
    args = p.parse_args(argv)
    results: list[tuple[str, bool, str]] = []

    def imports():
        import aiohttp  # noqa: F401
        import flax  # noqa: F401
        import optax  # noqa: F401
        import orbax.checkpoint  # noqa: F401
        import transformers  # noqa: F401

        import areal_tpu  # noqa: F401

        return "core deps + areal_tpu"

    _check("imports", imports, results)

    def devices():
        import jax

        devs = jax.devices()
        if args.tpu and devs[0].platform != "tpu":
            raise RuntimeError(f"expected tpu, got {devs[0].platform}")
        return f"{len(devs)}x {devs[0].platform}"

    _check("devices", devices, results)

    def tiny_jit():
        import jax
        import jax.numpy as jnp

        y = jax.jit(lambda x: (x @ x).sum())(jnp.ones((128, 128), jnp.bfloat16))
        return f"jit ok ({float(y):.0f})"

    _check("jit", tiny_jit, results)

    def engine_contract():
        from areal_tpu.api.engine_api import InferenceEngine, TrainEngine
        from areal_tpu.engine.train_engine import JaxTrainEngine
        from areal_tpu.inference.client import RemoteJaxEngine

        assert issubclass(JaxTrainEngine, TrainEngine)
        assert issubclass(RemoteJaxEngine, InferenceEngine)
        return "contracts wired"

    _check("contracts", engine_contract, results)

    def metrics_lint():
        """Static metric-name lint is arealint's OBS family now (one source
        of truth: registration outside the catalog, naming convention,
        missing help, duplicate names, dangling references). Here we invoke
        it over the package, then keep the one check that is inherently
        runtime: the registry's Prometheus rendering must round-trip
        through its own parser."""
        from areal_tpu.analysis import (
            default_baseline_path,
            default_package_root,
            run_analysis,
        )
        from areal_tpu.observability import catalog
        from areal_tpu.observability.metrics import (
            Registry,
            parse_prometheus_text,
        )

        res = run_analysis(
            [default_package_root()],
            rules=["OBS"],
            baseline_path=default_baseline_path(),
        )
        if not res.ok:
            raise RuntimeError(
                "; ".join(f.render() for f in res.findings[:5])
                + (f" (+{len(res.findings) - 5} more)" if len(res.findings) > 5 else "")
            )
        reg = catalog.register_all(Registry())
        parse_prometheus_text(reg.render_prometheus())
        return (
            f"arealint OBS clean over {res.files_checked} files; "
            f"{len(reg.families())} families render round-trip"
        )

    _check("metrics", metrics_lint, results)

    def native_kernels():
        from areal_tpu.native import datapack_lib
        from areal_tpu.utils.datapack import ffd_allocate

        lib = datapack_lib()
        bins = ffd_allocate(list(range(1, 200)), capacity=512)
        assert sorted(i for b in bins for i in b) == list(range(199))
        return "C++ datapack" if lib is not None else "python fallback (no g++?)"

    _check("native", native_kernels, results)

    width = max(len(n) for n, _, _ in results)
    ok = True
    for name, passed, detail in results:
        ok &= passed
        print(f"{name:<{width}}  {'PASS' if passed else 'FAIL'}  {detail}")
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
