"""Installation validator (reference areal/tools/validate_installation.py):
checks imports, device availability, a tiny jit, and the HTTP stack; prints
a PASS/FAIL table and exits nonzero on failure.

``--chaos-self-test`` additionally spins up a 3-replica in-process
inference fleet (tiny model, CPU) behind a seeded FaultInjector dropping
10% of requests, and asserts a rollout batch completes through the
retrying transport — a one-command smoke test of the fault-tolerance
layer for CI.

``--weight-sync-self-test`` streams full weight updates against a
2-replica in-process fleet while generation runs, and asserts the
zero-pause property (docs/weight_sync.md): the commit fence is >= 5x
smaller than the unpaused staging window and no in-flight request aborts.

``--prefix-cache-self-test`` runs the shared-prefix workload of
``tools/bench_prefix_cache`` and asserts cross-request radix reuse: a warm
admission wave prefills only suffix tokens at >= 2x the cold prefill
throughput, refcounts return to baseline, and a weight commit under the
default policy leaves no stale-version pages matchable.

``--overload-self-test`` drives a small in-process fleet at ~2x its
sustained capacity with the chaos stall injector running, and asserts the
overload-safety contract (docs/request_lifecycle.md): shed requests get
clean 429 + Retry-After, admitted work keeps a bounded p99, the deadline
reaper fires on the flood, and the PagePool ends with zero leaked pages.

``--train-obs-self-test`` runs a short synchronous-mode CPU RL loop under
a chaos-throttled rollout and asserts the trainer goodput observatory
(docs/observability.md "Trainer observatory"): the step-phase breakdown
identity with >= 90% measured named-phase coverage, a non-zero measured
rollout_wait bubble, a populated HBM ledger (analytic CPU fallback), and
live XLA compile counters.

``--learning-obs-self-test`` runs a short CPU RL loop with FORCED
staleness (eta > 0: the rollout pipeline pre-generates several versions
ahead of the trainer) and asserts the learning-health observatory
(docs/observability.md "Learning-health observatory"): the high-lag
bucket shows strictly higher measured behave-|KL| than lag-0, the behave
importance-weight cap leaves a non-zero cap-hit tail, and the trajectory
lineage ring joins journal frames to training-step loss stats by trace
id (generate -> journal -> consume -> update for one task id).

``--routing-self-test`` drives a 3-replica in-process fleet under seeded
chaos with an 80%-shared-prefix multi-turn workload through BOTH routing
policies (docs/serving.md "Cache-aware routing"), and asserts the routing
brain end to end: cache-aware measurably raises warm suffix-only prefill
(radix hit tokens) over round-robin, every decision lands in the flight
ring with a reason, and an evict -> respawn cycle yields zero routes to
the evicted replica while it is down (with its shadow prefix index read
as cold after the rejoin).

``--gateway-tier-self-test`` stands up the horizontally-sharded gateway
tier (docs/serving.md "Gateway tier"): 3 gateway shards over a small
in-process fleet, driven by the ring-hashing tier client while seeded
chaos kills one shard mid-run. Asserts the tier's whole fault story:
every session completes or terminates with a real terminal status (zero
responseless requests), the clients re-hash their sessions onto the
surviving shards (failovers observed, the keyspace the victim owned is
served by survivors), and the membership view converges to the two
survivors.

``--microbench-self-test`` exercises the kernel observatory (docs/perf.md
"Kernel observatory") on CPU: the fast microbench registry runs end to
end with non-null analytic rooflines, the compare gate stays silent on a
self-compare and flags a seeded 2x regression on every bench, and a live
tiny engine's per-step phase breakdown obeys the exact-sum identity
(named phases + other_s == step wall) with a non-null steady-state
roofline fraction via the calibrated CPU peak fallback.

``--spec-decode-self-test`` runs a spec-enabled tiny engine over an
acceptance-friendly repetitive workload (docs/serving.md "Speculative
decoding") and asserts acceptance rate > 0, zero leaked KV pages after
settling, draft/verify stage coverage in the request timelines, and the
kernel probe's exact-sum identity over the widened phase taxonomy.

``--kernelcheck`` runs every registered ops/ Pallas kernel's full
differential case grid in interpret mode against its XLA reference
(docs/perf.md "Paged suffix-attention kernel family") — the numerics
companion to the always-on static ``kernel_lint`` check.

Usage: python -m areal_tpu.tools.validate_installation [--tpu]
    [--chaos-self-test] [--weight-sync-self-test] [--prefix-cache-self-test]
    [--overload-self-test] [--timeline-self-test] [--train-obs-self-test]
    [--learning-obs-self-test] [--preemption-self-test] [--routing-self-test]
    [--microbench-self-test] [--spec-decode-self-test]
    [--gateway-tier-self-test] [--kernelcheck]
"""

from __future__ import annotations

import argparse
import sys


def tiny_model_config():
    """The toy model every in-process self-test fleet serves (shared with
    tools/bench_gateway's LocalFleet — one definition, or the self-tests
    and the bench silently measure different models)."""
    from areal_tpu.models import qwen

    return qwen.ModelConfig(
        vocab_size=128,
        hidden_size=32,
        intermediate_size=64,
        num_layers=2,
        num_heads=2,
        num_kv_heads=1,
        dtype="float32",
        tie_word_embeddings=True,
        rope_theta=10000.0,
    )


def _check(name, fn, results):
    try:
        detail = fn() or ""
        results.append((name, True, str(detail)))
    except Exception as e:  # noqa: BLE001
        results.append((name, False, f"{type(e).__name__}: {e}"))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--tpu", action="store_true", help="require a TPU backend")
    p.add_argument(
        "--chaos-self-test",
        action="store_true",
        help="run a 3-replica local fleet under 10%% injected faults and "
        "assert a rollout batch completes",
    )
    p.add_argument(
        "--weight-sync-self-test",
        action="store_true",
        help="run streamed weight updates against a 2-replica local fleet "
        "under live generation load and assert the zero-pause property "
        "(commit fence >= 5x smaller than the staging window, no aborts)",
    )
    p.add_argument(
        "--prefix-cache-self-test",
        action="store_true",
        help="run the shared-prefix workload (tools/bench_prefix_cache) and "
        "assert radix reuse: warm admission prefills suffixes only at >= 2x "
        "cold throughput, zero refcount leaks, and a weight commit leaves "
        "no stale pages matchable",
    )
    p.add_argument(
        "--overload-self-test",
        action="store_true",
        help="drive a small local fleet at ~2x sustained capacity with "
        "chaos stalls and assert overload safety: clean 429 + Retry-After "
        "for shed work, bounded p99 for admitted work, deadline reaping, "
        "and zero leaked KV pages",
    )
    p.add_argument(
        "--timeline-self-test",
        action="store_true",
        help="run a short serve (incl. a weight-commit hold fence) and "
        "assert the request-timeline observatory: stage sums ≈ wall time "
        "per request, fence stalls attributed, and zero unterminated "
        "timelines",
    )
    p.add_argument(
        "--train-obs-self-test",
        action="store_true",
        help="run a short CPU RL loop under a throttled rollout and assert "
        "the trainer goodput observatory: step-phase breakdown sums to the "
        "step wall time with >= 90%% named-phase coverage, non-zero "
        "rollout_wait (the async bubble), and a populated HBM ledger",
    )
    p.add_argument(
        "--routing-self-test",
        action="store_true",
        help="3-replica fleet under seeded chaos: cache-aware routing "
        "must raise warm suffix-only prefill vs round-robin, audit every "
        "decision to the flight ring, and never route to an evicted "
        "replica (docs/serving.md)",
    )
    p.add_argument(
        "--autopilot-self-test",
        action="store_true",
        help="seeded chaos-stall fleet at ~2x gateway capacity with the "
        "goodput autopilot on: the admission controller must widen the "
        "interactive headroom, the interactive shed rate must drop in the "
        "second measured window, and every setpoint change must be "
        "auditable in the flight ring (docs/autopilot.md) — all on CPU",
    )
    p.add_argument(
        "--learning-obs-self-test",
        action="store_true",
        help="short CPU RL run with forced staleness (eta>0) asserting "
        "the learning-health observatory: high-lag behave-|KL| strictly "
        "above lag-0, non-zero behave-cap tail mass, and lineage records "
        "joining journal frames to step loss stats by trace id — all "
        "measured, deterministic under seeded chaos",
    )
    p.add_argument(
        "--microbench-self-test",
        action="store_true",
        help="run the fast microbench registry on CPU (non-null analytic "
        "rooflines), assert the compare gate flags a seeded 2x regression "
        "per bench and stays silent on self-compare, and assert the live "
        "engine's decode phase breakdown obeys the exact-sum identity",
    )
    p.add_argument(
        "--kernelcheck",
        action="store_true",
        help="run every registered ops/ Pallas kernel's full kernelcheck "
        "case grid (interpret mode vs XLA reference — for "
        "paged_suffix_attention: GQA ratios x ragged lengths x "
        "bf16/int8/fp8 x chain/tree masks) and fail on any divergence; "
        "the numerics companion to the static kernel_lint check "
        "(docs/perf.md 'Paged suffix-attention kernel family')",
    )
    p.add_argument(
        "--spec-decode-self-test",
        action="store_true",
        help="run a spec-enabled tiny engine over a repetitive workload "
        "and assert the speculative-decoding contract: acceptance rate "
        "> 0, zero leaked KV pages after settling, draft/verify stages "
        "in the request timelines, and the kernel probe's exact-sum "
        "identity over the widened phase taxonomy",
    )
    p.add_argument(
        "--gateway-tier-self-test",
        action="store_true",
        help="3 gateway shards over a small fleet under seeded chaos: one "
        "shard is killed mid-run and every session must complete or "
        "terminate with a real terminal (zero responseless requests) "
        "while the survivors absorb the re-hashed load "
        "(docs/serving.md 'Gateway tier')",
    )
    p.add_argument(
        "--preemption-self-test",
        action="store_true",
        help="run a tiny CPU fleet + trainer, deliver a REAL SIGTERM "
        "mid-step, and assert the preemption contract: trainer emergency-"
        "dumps and exits cleanly, the replica drains (0 leaked pages, all "
        "timelines terminated), a relaunch replays journaled trajectories, "
        "and the async save path pauses the step loop <= 1/5 of a sync save",
    )
    args = p.parse_args(argv)
    results: list[tuple[str, bool, str]] = []

    def imports():
        import aiohttp  # noqa: F401
        import flax  # noqa: F401
        import optax  # noqa: F401
        import orbax.checkpoint  # noqa: F401
        import transformers  # noqa: F401

        import areal_tpu  # noqa: F401

        return "core deps + areal_tpu"

    _check("imports", imports, results)

    def devices():
        import jax

        devs = jax.devices()
        if args.tpu and devs[0].platform != "tpu":
            raise RuntimeError(f"expected tpu, got {devs[0].platform}")
        return f"{len(devs)}x {devs[0].platform}"

    _check("devices", devices, results)

    def tiny_jit():
        import jax
        import jax.numpy as jnp

        y = jax.jit(lambda x: (x @ x).sum())(jnp.ones((128, 128), jnp.bfloat16))
        return f"jit ok ({float(y):.0f})"

    _check("jit", tiny_jit, results)

    def engine_contract():
        from areal_tpu.api.engine_api import InferenceEngine, TrainEngine
        from areal_tpu.engine.train_engine import JaxTrainEngine
        from areal_tpu.inference.client import RemoteJaxEngine

        assert issubclass(JaxTrainEngine, TrainEngine)
        assert issubclass(RemoteJaxEngine, InferenceEngine)
        return "contracts wired"

    _check("contracts", engine_contract, results)

    def metrics_lint():
        """Static metric-name lint is arealint's OBS family now (one source
        of truth: registration outside the catalog, naming convention,
        missing help, duplicate names, dangling references). Here we invoke
        it over the package, then keep the one check that is inherently
        runtime: the registry's Prometheus rendering must round-trip
        through its own parser."""
        from areal_tpu.analysis import (
            default_baseline_path,
            default_package_root,
            run_analysis,
        )
        from areal_tpu.observability import catalog
        from areal_tpu.observability.metrics import (
            Registry,
            parse_prometheus_text,
        )

        res = run_analysis(
            [default_package_root()],
            rules=["OBS"],
            baseline_path=default_baseline_path(),
        )
        if not res.ok:
            raise RuntimeError(
                "; ".join(f.render() for f in res.findings[:5])
                + (f" (+{len(res.findings) - 5} more)" if len(res.findings) > 5 else "")
            )
        reg = catalog.register_all(Registry())
        parse_prometheus_text(reg.render_prometheus())
        return (
            f"arealint OBS clean over {res.files_checked} files; "
            f"{len(reg.families())} families render round-trip"
        )

    _check("metrics", metrics_lint, results)

    def perf_lint():
        """The dataflow-aware performance families (PRF hot-path syncs,
        DON donation, SHD sharding specs, RCP recompile risk) over the
        package, against the checked-in baseline — the static half of
        what the PR 9 observatory measures at runtime
        (docs/static_analysis.md)."""
        from areal_tpu.analysis import (
            default_baseline_path,
            default_package_root,
            run_analysis,
        )

        res = run_analysis(
            [default_package_root()],
            rules=["PRF", "DON", "SHD", "RCP"],
            baseline_path=default_baseline_path(),
        )
        if not res.ok:
            raise RuntimeError(
                "; ".join(f.render() for f in res.findings[:5])
                + (f" (+{len(res.findings) - 5} more)" if len(res.findings) > 5 else "")
            )
        return (
            f"PRF/DON/SHD/RCP clean over {res.files_checked} files "
            f"({len(res.suppressed)} reasoned suppressions)"
        )

    _check("perf_lint", perf_lint, results)

    def wire_lint():
        """The distributed-control-plane families (WIRE wire-contract
        drift between the HTTP-coupled processes, LCK lock/fence
        ordering in the threaded engine) over the package — the static
        half of what the scale-out e2e tests exercise at runtime
        (docs/static_analysis.md)."""
        from areal_tpu.analysis import (
            default_baseline_path,
            default_package_root,
            run_analysis,
        )

        res = run_analysis(
            [default_package_root()],
            rules=["WIRE", "LCK"],
            baseline_path=default_baseline_path(),
        )
        if not res.ok:
            raise RuntimeError(
                "; ".join(f.render() for f in res.findings[:5])
                + (f" (+{len(res.findings) - 5} more)" if len(res.findings) > 5 else "")
            )
        return (
            f"WIRE/LCK clean over {res.files_checked} files "
            f"({len(res.suppressed)} reasoned suppressions)"
        )

    _check("wire_lint", wire_lint, results)

    def kernel_lint():
        """The kernel-arc families (KRN Pallas launch-site safety, PVT
        private-jax signature pins re-verified against the INSTALLED jax,
        MSH collective/mesh consistency) over the package — run on the
        deployment's actual jax, this is the install-time check that a
        jax bump has not drifted any pinned private kernel signature
        (docs/static_analysis.md)."""
        from areal_tpu.analysis import (
            default_baseline_path,
            default_package_root,
            run_analysis,
        )

        res = run_analysis(
            [default_package_root()],
            rules=["KRN", "PVT", "MSH"],
            baseline_path=default_baseline_path(),
        )
        if not res.ok:
            raise RuntimeError(
                "; ".join(f.render() for f in res.findings[:5])
                + (f" (+{len(res.findings) - 5} more)" if len(res.findings) > 5 else "")
            )
        return (
            f"KRN/PVT/MSH clean over {res.files_checked} files "
            f"({len(res.suppressed)} reasoned suppressions)"
        )

    _check("kernel_lint", kernel_lint, results)

    def native_kernels():
        from areal_tpu.native import datapack_lib
        from areal_tpu.utils.datapack import ffd_allocate

        lib = datapack_lib()
        bins = ffd_allocate(list(range(1, 200)), capacity=512)
        assert sorted(i for b in bins for i in b) == list(range(199))
        return "C++ datapack" if lib is not None else "python fallback (no g++?)"

    _check("native", native_kernels, results)

    if args.kernelcheck:

        def kernelcheck():
            from areal_tpu.tools.kernelcheck import run_all

            recs = run_all()
            bad = [r for r in recs if not r["ok"]]
            if bad:
                raise RuntimeError(
                    "; ".join(
                        f"{r['kernel']}[{r['case']}]: "
                        + r.get("error", f"diff {r.get('max_abs_diff')}")
                        for r in bad[:5]
                    )
                    + (f" (+{len(bad) - 5} more)" if len(bad) > 5 else "")
                )
            kernels = sorted({r["kernel"] for r in recs})
            return f"{len(recs)} cases green over {len(kernels)} kernels"

        _check("kernelcheck", kernelcheck, results)

    if args.chaos_self_test:
        _check("chaos", chaos_self_test, results)

    if args.weight_sync_self_test:

        def weight_sync():
            from areal_tpu.tools.bench_weight_sync import self_test

            return self_test()

        _check("weight_sync", weight_sync, results)

    if args.prefix_cache_self_test:

        def prefix_cache():
            from areal_tpu.tools.bench_prefix_cache import self_test

            return self_test()

        _check("prefix_cache", prefix_cache, results)

    if args.overload_self_test:
        _check("overload", overload_self_test, results)

    if args.timeline_self_test:
        _check("timeline", timeline_self_test, results)

    if args.train_obs_self_test:
        _check("train_obs", train_obs_self_test, results)

    if args.learning_obs_self_test:
        _check("learning_obs", learning_obs_self_test, results)

    if args.preemption_self_test:
        _check("preemption", preemption_self_test, results)

    if args.routing_self_test:
        _check("routing", routing_self_test, results)

    if args.autopilot_self_test:
        _check("autopilot", autopilot_self_test, results)

    if args.microbench_self_test:
        _check("microbench", microbench_self_test, results)

    if args.spec_decode_self_test:
        _check("spec_decode", spec_decode_self_test, results)

    if args.gateway_tier_self_test:
        _check("gateway_tier", gateway_tier_self_test, results)

    width = max(len(n) for n, _, _ in results)
    ok = True
    for name, passed, detail in results:
        ok &= passed
        print(f"{name:<{width}}  {'PASS' if passed else 'FAIL'}  {detail}")
    return 0 if ok else 1


def chaos_self_test(
    n_replicas: int = 3, drop_prob: float = 0.1, n_prompts: int = 6, seed: int = 42
) -> str:
    """3-replica in-process fleet + seeded 10%-drop FaultInjector: a rollout
    batch must complete through retries/failover, and the chaos harness must
    actually have fired (otherwise the test proves nothing)."""
    import jax

    from areal_tpu.api.config import (
        ChaosConfig,
        FaultToleranceConfig,
        InferenceEngineConfig,
        MeshConfig,
        ServerConfig,
    )
    from areal_tpu.api.io_struct import GenerationHyperparameters
    from areal_tpu.inference.client import RemoteJaxEngine
    from areal_tpu.inference.decode_engine import DecodeEngine
    from areal_tpu.inference.server import ServerThread
    from areal_tpu.models import qwen
    from areal_tpu.robustness import FaultInjector
    from areal_tpu.workflow.rlvr import RLVRWorkflow

    tiny = tiny_model_config()
    params = qwen.init_params(jax.random.PRNGKey(0), tiny)
    servers = []
    client = None
    try:
        for i in range(n_replicas):
            cfg = ServerConfig(
                max_batch_size=4,
                max_seq_len=64,
                decode_steps_per_call=4,
                seed=i,
                mesh=MeshConfig(data=-1, fsdp=1, seq=1, model=1),
            )
            eng = DecodeEngine(cfg, params=params, model_cfg=tiny)
            eng.initialize()
            st = ServerThread(cfg, eng)
            st.start()
            servers.append(st)
        client = RemoteJaxEngine(
            InferenceEngineConfig(
                max_concurrent_rollouts=4,
                consumer_batch_size=2,
                max_head_offpolicyness=100,
                request_timeout=60,
                request_retries=5,
                fault_tolerance=FaultToleranceConfig(
                    backoff_base_s=0.05, backoff_max_s=0.5
                ),
            ),
            addresses=[s.address for s in servers],
        )
        client.initialize()
        injector = FaultInjector(
            ChaosConfig(enabled=True, seed=seed, drop_prob=drop_prob)
        )
        client.install_fault_injector(injector)
        wf = RLVRWorkflow(
            lambda *a, **k: 1.0,
            GenerationHyperparameters(max_new_tokens=4, greedy=True),
        )
        batch = client.rollout_batch(
            [{"prompt_ids": [2 + i, 5, 7]} for i in range(n_prompts)],
            workflow=wf,
        )
        assert batch["input_ids"].shape[0] == n_prompts, batch["input_ids"].shape
        stats = injector.stats()
        assert stats["drop"] > 0, "fault injector never fired"
        return (
            f"{n_prompts} rollouts over {n_replicas} replicas survived "
            f"{stats['drop']} injected drops ({stats['requests_seen']} requests)"
        )
    finally:
        if client is not None:
            client.destroy()
        for st in servers:
            st.stop()


def overload_self_test(
    n_interactive: int = 4,
    n_flood: int = 6,
    flood_deadline_s: float = 2.0,
    p99_bound_s: float = 60.0,
    seed: int = 99,
) -> str:
    """One lifecycle-enabled server (2 slots, queue cap 3) driven at ~2x
    sustained capacity — a flood of effectively-unbounded generations on
    short deadlines rides alongside short interactive requests, with the
    chaos stall injector perturbing every post. Asserts the overload
    contract end to end; the tier-1 acceptance test
    (tests/test_request_lifecycle.py::test_overload_acceptance) adds the
    greedy byte-identity check against a lifecycle-disabled twin."""
    import asyncio
    import time

    import aiohttp
    import jax

    from areal_tpu.api.config import (
        ChaosConfig,
        MeshConfig,
        RequestLifecycleConfig,
        ServerConfig,
    )
    from areal_tpu.inference.decode_engine import DecodeEngine
    from areal_tpu.inference.server import ServerThread
    from areal_tpu.models import qwen
    from areal_tpu.robustness import FaultInjector

    tiny = tiny_model_config()
    params = qwen.init_params(jax.random.PRNGKey(0), tiny)
    cfg = ServerConfig(
        max_batch_size=2,
        max_seq_len=256,
        decode_steps_per_call=4,
        mesh=MeshConfig(data=-1, fsdp=1, seq=1, model=1),
        lifecycle=RequestLifecycleConfig(
            max_queue_depth=3, retry_after_s=0.1, watchdog_s=30.0
        ),
    )
    eng = DecodeEngine(cfg, params=params, model_cfg=tiny)
    eng.initialize()
    srv = ServerThread(cfg, eng)
    srv.start()
    inj = FaultInjector(
        ChaosConfig(enabled=True, seed=seed, stall_prob=0.3, stall_s=0.15)
    )
    stats = {"s429": 0, "latency": []}

    async def one(i: int, ids, n_new: int, deadline_s: float | None, tag: str):
        payload = {
            "input_ids": ids,
            "rid": f"{tag}-{i}",
            "sampling_params": {"max_new_tokens": n_new, "greedy": True},
        }
        headers = {}
        if deadline_s is not None:
            from areal_tpu.api import wire

            headers[wire.DEADLINE_HEADER] = f"{time.time() + deadline_s:.6f}"
        t0 = time.monotonic()
        async with aiohttp.ClientSession() as s:
            for _ in range(200):  # bounded retry: no hung client
                await inj.aperturb(srv.address, "/generate")
                async with s.post(
                    f"http://{srv.address}/generate",
                    json=payload,
                    headers=headers,
                ) as r:
                    if r.status == 429:
                        stats["s429"] += 1
                        ra = r.headers.get("Retry-After")
                        if ra is None or float(ra) <= 0:
                            raise AssertionError("429 without Retry-After")
                        await asyncio.sleep(float(ra))
                        continue
                    assert r.status == 200, await r.text()
                    await r.json()
                    break
            else:
                raise AssertionError("client starved: 200 rejections")
        if tag == "interactive":
            stats["latency"].append(time.monotonic() - t0)

    async def drive():
        # 2 slots + queue cap 3 vs. n_interactive + n_flood concurrent
        # requests (the flood ignores EOS) = ~2x sustained capacity
        await asyncio.gather(
            *[
                one(i, [3 + i, 14 + i, 15], 8, None, "interactive")
                for i in range(n_interactive)
            ],
            *[
                one(i, [40 + i, 2, 2], 100_000, flood_deadline_s, "flood")
                for i in range(n_flood)
            ],
        )

    try:
        asyncio.run(drive())
        if stats["s429"] == 0:
            raise AssertionError("overload never shed — not a 2x run")
        p99 = max(stats["latency"])  # == p99 at this sample count
        if p99 >= p99_bound_s:
            raise AssertionError(f"admitted p99 {p99:.1f}s >= {p99_bound_s}s")
        if eng.stats["deadline_exceeded"] == 0:
            raise AssertionError("deadline reaper never fired on the flood")
        if inj.stats()["stall"] == 0:
            raise AssertionError("chaos stalls never fired")
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            snap = eng.admission_snapshot()
            if snap["queue_depth"] == 0 and snap["active_slots"] == 0:
                break
            time.sleep(0.05)
        held = (
            eng.prefix_cache_stats()["pages_held"]
            if eng._radix is not None
            else 0
        )
        leaked = eng.pool.used - held
        if leaked != 0:
            raise AssertionError(f"{leaked} KV pages leaked after overload")
        return (
            f"{n_interactive}+{n_flood} reqs @2x: {stats['s429']} clean 429s, "
            f"admitted p99 {p99:.1f}s, "
            f"{eng.stats['deadline_exceeded']} deadline reaps, "
            f"{inj.stats()['stall']} stalls, 0 leaked pages"
        )
    finally:
        srv.stop()


def timeline_self_test(
    n_short: int = 4, coverage_floor: float = 0.5
) -> str:
    """Short serve over one tiny engine asserting the request-timeline
    observatory end to end (docs/observability.md "Request timelines"):

    - every request's named stages (queue_wait + prefill + decode +
      fence_stall) cover >= ``coverage_floor`` of its wall time — i.e.
      the explicit ``other_s`` residual is small, so timelines actually
      attribute latency instead of hiding it;
    - a weight-commit hold fence mid-decode lands in ``fence_stall_s``;
    - zero unterminated timelines once the engine drains (every request
      that entered the engine left through a terminal stage)."""
    import threading
    import time

    import jax

    from areal_tpu.api.config import MeshConfig, ServerConfig
    from areal_tpu.api.io_struct import (
        GenerationHyperparameters,
        ModelRequest,
    )
    from areal_tpu.inference.decode_engine import DecodeEngine
    from areal_tpu.models import qwen

    tiny = tiny_model_config()
    params = qwen.init_params(jax.random.PRNGKey(0), tiny)
    cfg = ServerConfig(
        max_batch_size=4,
        max_seq_len=256,
        decode_steps_per_call=4,
        seed=1,
        mesh=MeshConfig(data=-1, fsdp=1, seq=1, model=1),
    )
    eng = DecodeEngine(cfg, params=params, model_cfg=tiny)
    eng.initialize()
    eng.start()
    try:
        # short mixed-priority wave (warms the compiled programs too, so
        # the fence request below measures serving, not compilation)
        for i in range(n_short):
            resp = eng.generate_sync(
                ModelRequest(
                    input_ids=[3 + i, 7, 9],
                    gconfig=GenerationHyperparameters(
                        max_new_tokens=8, greedy=True
                    ),
                    metadata={"priority": "rollout" if i % 2 else "interactive"},
                ),
                timeout=120,
            )
            assert resp.queue_wait_s >= 0 and resp.decode_s >= 0
        # long request with a hold fence dropped mid-decode
        done = threading.Event()
        box = []
        eng.submit(
            ModelRequest(
                input_ids=[5, 6, 7],
                gconfig=GenerationHyperparameters(
                    max_new_tokens=200, greedy=True, ignore_eos=True
                ),
            ),
            lambda r: (box.append(r), done.set()),
        )
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if any(
                t is not None and t.out_tokens for t in eng._slot_task
            ):
                break
            time.sleep(0.01)
        eng.pause_generation(mode="hold")
        eng.wait_fence_ack(10.0)
        time.sleep(0.4)  # the measurable stall
        eng.continue_generation()
        assert done.wait(120), "fence request never completed"
        fenced = box[0]
        if fenced.fence_stall_s < 0.2:
            raise AssertionError(
                f"hold fence not attributed: fence_stall_s="
                f"{fenced.fence_stall_s:.3f}s (held ~0.4s)"
            )
        # settle, then audit the recorder
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            snap = eng.admission_snapshot()
            if snap["queue_depth"] == 0 and snap["active_slots"] == 0:
                break
            time.sleep(0.05)
        stats = eng.timeline.stats()
        if stats["unterminated"] != 0:
            raise AssertionError(
                f"{stats['unterminated']} unterminated timelines "
                f"(started {stats['started']}, completed {stats['completed']})"
            )
        worst, n_audited = 1.0, 0
        for rec in eng.timeline.recent():
            bd = rec["breakdown"]
            if bd["total_s"] <= 0 or rec["terminal_reason"] not in (
                "stop",
                "length",
            ):
                continue
            n_audited += 1
            covered = 1.0 - bd["other_s"] / bd["total_s"]
            worst = min(worst, covered)
        if n_audited == 0:
            raise AssertionError("no completed timelines to audit")
        if worst < coverage_floor:
            raise AssertionError(
                f"stage coverage {worst:.0%} < {coverage_floor:.0%} of "
                "wall time — timelines are not attributing latency"
            )
        return (
            f"{stats['completed']} timelines terminated cleanly, stage "
            f"coverage >= {worst:.0%}, fence stall "
            f"{fenced.fence_stall_s:.2f}s attributed"
        )
    finally:
        eng.stop()


def train_obs_self_test(
    n_steps: int = 2, coverage_floor: float = 0.9, stall_s: float = 0.1
) -> str:
    """Short CPU RL run asserting the trainer goodput observatory
    (docs/observability.md "Trainer observatory") with MEASURED numbers:

    - every completed step's phase breakdown satisfies the identity
      (named phases + other_s == step wall time) and the named phases
      cover >= ``coverage_floor`` of it — the residual attributes, it
      doesn't hide;
    - rollout_wait is non-zero under a throttled rollout (a seeded chaos
      stall injector on every client POST — the async bubble measured,
      not mocked);
    - the trainer HBM ledger itemizes params + optimizer state (analytic
      CPU fallback) and the XLA compile counters saw this run's compiles.
    """
    import jax
    import numpy as np

    from areal_tpu.api.config import (
        ChaosConfig,
        DatasetConfig,
        InferenceEngineConfig,
        MeshConfig,
        MicroBatchSpec,
        OptimizerConfig,
        PPOActorConfig,
        PPOConfig,
        RecoverConfig,
        SaverConfig,
        ServerConfig,
        StatsLoggerConfig,
    )
    from areal_tpu.api.io_struct import FinetuneSpec, GenerationHyperparameters
    from areal_tpu.engine.train_engine import JaxTrainEngine
    from areal_tpu.inference.client import RemoteJaxEngine
    from areal_tpu.inference.decode_engine import DecodeEngine
    from areal_tpu.inference.server import ServerThread
    from areal_tpu.models import qwen
    from areal_tpu.robustness import FaultInjector
    from areal_tpu.trainer.rl_trainer import PPOTrainer
    from areal_tpu.utils.compile_cache import compile_stats
    from areal_tpu.workflow.rlvr import RLVRWorkflow

    import tempfile

    root = tempfile.mkdtemp(prefix="areal_train_obs_selftest_")
    tiny = tiny_model_config()
    actor_cfg = PPOActorConfig(
        init_from_scratch=True,
        dtype="float32",
        param_dtype="float32",
        mesh=MeshConfig(data=-1, fsdp=1, seq=1, model=1),
        optimizer=OptimizerConfig(lr=1e-3, lr_scheduler_type="constant"),
        mb_spec=MicroBatchSpec(max_tokens_per_mb=100_000),
        bucket_step=64,
        group_size=1,
        ppo_n_minibatches=1,
        adv_norm=None,
        kl_ctl=0.0,
        use_decoupled_loss=False,
        recompute_logprob=False,
    )
    engine = JaxTrainEngine(actor_cfg, model_config=tiny)
    engine.initialize(FinetuneSpec(1, 16, 2))
    scfg = ServerConfig(
        max_batch_size=4,
        max_seq_len=128,
        decode_steps_per_call=4,
        seed=0,
        mesh=MeshConfig(data=-1, fsdp=1, seq=1, model=1),
    )
    dec = DecodeEngine(
        scfg, params=jax.tree.map(np.asarray, engine.params), model_cfg=tiny
    )
    dec.initialize()
    server = ServerThread(scfg, dec)
    server.start()
    rollout = RemoteJaxEngine(
        InferenceEngineConfig(
            max_concurrent_rollouts=4,
            consumer_batch_size=2,
            # SYNCHRONOUS mode: with any lookahead the async pipeline
            # pre-generates the next batch during this step's compute and
            # the bubble (correctly!) vanishes — offpolicyness 0 forces
            # every step to sit in prepare_batch so the test can assert
            # the bubble is MEASURED, not merely absent
            max_head_offpolicyness=0,
            request_timeout=120,
        ),
        addresses=[server.address],
    )
    rollout.initialize()
    # the throttle: every client POST eats a deterministic stall, so the
    # prepare_batch wait (rollout_wait) is guaranteed measurable
    rollout.install_fault_injector(
        FaultInjector(
            ChaosConfig(enabled=True, seed=7, stall_prob=1.0, stall_s=stall_s)
        )
    )
    cfg = PPOConfig(
        experiment_name="train-obs",
        trial_name="t0",
        total_train_epochs=50,
        total_train_steps=n_steps,
        weight_update_mode="mem",
        gconfig=GenerationHyperparameters(
            n_samples=1, max_new_tokens=4, greedy=True
        ),
        train_dataset=DatasetConfig(batch_size=2, shuffle=True),
        actor=actor_cfg,
        saver=SaverConfig(fileroot=root),
        checkpointer=SaverConfig(fileroot=root),
        recover=RecoverConfig(mode="disabled", fileroot=root),
        stats_logger=StatsLoggerConfig(fileroot=root),
    )
    cfg.evaluator.fileroot = root
    cfg.cluster.fileroot = root
    rng = np.random.default_rng(0)
    dataset = [
        {"prompt_ids": rng.integers(2, 100, 3).tolist()} for _ in range(16)
    ]
    wf = RLVRWorkflow(
        lambda *a, **k: 1.0,
        GenerationHyperparameters(max_new_tokens=4, greedy=True),
    )
    trainer = PPOTrainer(cfg, dataset, rollout=rollout, actor_engine=engine)
    try:
        c0 = compile_stats()["compiles"]
        trainer.train(workflow=wf)
        recent = trainer.step_recorder.recent()
        if len(recent) < n_steps:
            raise AssertionError(
                f"{len(recent)} step timelines recorded, expected {n_steps}"
            )
        worst_cov, min_wait = 1.0, float("inf")
        from areal_tpu.observability.step_timeline import PHASES

        for rec in recent:
            bd = rec["breakdown"]
            named = sum(bd[f"{p}_s"] for p in PHASES)
            if abs(named + bd["other_s"] - bd["total_s"]) > 1e-6:
                raise AssertionError(
                    f"breakdown identity violated at step {rec['step']}: "
                    f"{named + bd['other_s']:.6f} != {bd['total_s']:.6f}"
                )
            worst_cov = min(worst_cov, named / bd["total_s"])
            min_wait = min(min_wait, bd["rollout_wait_s"])
        if worst_cov < coverage_floor:
            raise AssertionError(
                f"phase coverage {worst_cov:.0%} < {coverage_floor:.0%} of "
                "step wall time — the timeline is not attributing latency"
            )
        if min_wait < stall_s / 2:
            raise AssertionError(
                f"rollout_wait {min_wait * 1e3:.0f}ms under a throttled "
                "rollout — the async bubble is not being measured"
            )
        ledger = trainer.last_hbm_ledger
        if ledger is None:
            raise AssertionError("no HBM ledger recorded")
        comp = ledger["components"]
        if comp.get("params", 0) <= 0 or comp.get("opt_state", 0) <= 0:
            raise AssertionError(f"HBM ledger not itemized: {comp}")
        if ledger["bytes_in_use"] <= 0:
            raise AssertionError("HBM ledger has no in-use accounting")
        compiled = compile_stats()["compiles"] - c0
        if compiled <= 0:
            raise AssertionError("compile counters saw no XLA compiles")
        bubbles = [r["breakdown"]["bubble_fraction"] for r in recent]
        return (
            f"{len(recent)} steps: phase coverage >= {worst_cov:.0%}, "
            f"bubble {min(bubbles):.0%}..{max(bubbles):.0%} "
            f"(rollout_wait >= {min_wait * 1e3:.0f}ms under throttle), "
            f"hbm ledger params {comp['params'] / 1e3:.0f}kB + opt "
            f"{comp['opt_state'] / 1e3:.0f}kB ({ledger['source']}), "
            f"{compiled} compiles counted"
        )
    finally:
        trainer.close()
        server.stop()


def learning_obs_self_test(n_steps: int = 6, eta: int = 4) -> str:
    """Short CPU RL run with FORCED staleness asserting the learning-health
    observatory (docs/observability.md "Learning-health observatory") with
    MEASURED numbers:

    - eta=4 lets the rollout pipeline pre-generate ~(eta+1)*bs trajectories
      at version 0; FIFO consumption then trains them at lags 0..eta, so
      several lag buckets fill without any mocking;
    - the highest populated lag bucket must show strictly higher windowed
      behave-|KL| than lag-0 (the decoupled-loss drift the staleness bound
      is supposed to keep corrigible), and a tight behave importance-weight
      cap must leave a non-zero cap-hit tail;
    - the trajectory lineage ring must join journal frames to train-step
      loss stats by trace id: generate -> journal -> consume -> update for
      the same task id, with per-trajectory clip fraction attributed.
    """
    import os
    import tempfile

    import jax
    import numpy as np

    from areal_tpu.api.config import (
        ChaosConfig,
        DatasetConfig,
        InferenceEngineConfig,
        MeshConfig,
        MicroBatchSpec,
        OptimizerConfig,
        PPOActorConfig,
        PPOConfig,
        RecoverConfig,
        SaverConfig,
        ServerConfig,
        StatsLoggerConfig,
    )
    from areal_tpu.api.io_struct import FinetuneSpec, GenerationHyperparameters
    from areal_tpu.autopilot.signals import labeled_total
    from areal_tpu.engine.train_engine import JaxTrainEngine
    from areal_tpu.infra.staleness_manager import LAG_BUCKET_LABELS
    from areal_tpu.inference.client import RemoteJaxEngine
    from areal_tpu.inference.decode_engine import DecodeEngine
    from areal_tpu.inference.server import ServerThread
    from areal_tpu.observability import lineage as lineage_mod
    from areal_tpu.observability.metrics import (
        get_registry,
        parse_prometheus_text,
    )
    from areal_tpu.robustness import FaultInjector
    from areal_tpu.trainer.rl_trainer import PPOTrainer
    from areal_tpu.workflow.rlvr import RLVRWorkflow

    root = tempfile.mkdtemp(prefix="areal_learning_obs_selftest_")
    tiny = tiny_model_config()
    actor_cfg = PPOActorConfig(
        init_from_scratch=True,
        dtype="float32",
        param_dtype="float32",
        mesh=MeshConfig(data=-1, fsdp=1, seq=1, model=1),
        # the lr IS the experiment: the policy must measurably move per
        # version so lag maps to drift (behave KL)
        optimizer=OptimizerConfig(lr=2e-2, lr_scheduler_type="constant"),
        mb_spec=MicroBatchSpec(max_tokens_per_mb=100_000),
        bucket_step=64,
        group_size=1,
        ppo_n_minibatches=1,
        adv_norm=None,
        kl_ctl=0.0,
        use_decoupled_loss=True,
        prox_logp_mode="recompute",
        # tight cap: a few drifted tokens must hit it (the tail-mass assert)
        behav_imp_weight_cap=1.01,
    )
    engine = JaxTrainEngine(actor_cfg, model_config=tiny)
    engine.initialize(FinetuneSpec(1, 16, 2))
    scfg = ServerConfig(
        max_batch_size=8,
        max_seq_len=128,
        decode_steps_per_call=4,
        seed=0,
        mesh=MeshConfig(data=-1, fsdp=1, seq=1, model=1),
    )
    dec = DecodeEngine(
        scfg, params=jax.tree.map(np.asarray, engine.params), model_cfg=tiny
    )
    dec.initialize()
    server = ServerThread(scfg, dec)
    server.start()
    rollout = RemoteJaxEngine(
        InferenceEngineConfig(
            # wide open concurrency + eta>0: the whole staleness budget
            # ((eta + v + 1) * bs accepted) pre-generates at version 0 and
            # drains FIFO over the next eta steps — lag 0..eta, measured
            max_concurrent_rollouts=16,
            consumer_batch_size=2,
            max_head_offpolicyness=eta,
            request_timeout=120,
        ),
        addresses=[server.address],
    )
    rollout.initialize()
    # seeded chaos: deterministic small stalls on the client POSTs — the
    # asserts below must hold under perturbed timing, not a quiet lab
    rollout.install_fault_injector(
        FaultInjector(
            ChaosConfig(enabled=True, seed=11, stall_prob=0.2, stall_s=0.01)
        )
    )
    cfg = PPOConfig(
        experiment_name="learning-obs",
        trial_name="t0",
        total_train_epochs=50,
        total_train_steps=n_steps,
        weight_update_mode="mem",
        # SAMPLED generation: greedy slots run at temp->0, whose sampling
        # distribution is deterministic and reports ~0 logprobs — no
        # behavior policy to be off of. RL rollouts sample; so does this.
        gconfig=GenerationHyperparameters(
            n_samples=1, max_new_tokens=4, greedy=False
        ),
        train_dataset=DatasetConfig(batch_size=2, shuffle=True),
        actor=actor_cfg,
        saver=SaverConfig(fileroot=root),
        checkpointer=SaverConfig(fileroot=root),
        recover=RecoverConfig(mode="disabled", fileroot=root),
        stats_logger=StatsLoggerConfig(fileroot=root),
    )
    cfg.evaluator.fileroot = root
    cfg.cluster.fileroot = root
    # the journal is part of the lineage chain under test
    cfg.rollout.journal.enabled = True
    cfg.rollout.journal.dir = os.path.join(root, "journal")
    cfg.rollout.journal.fsync = False
    rng = np.random.default_rng(0)
    dataset = [
        {"prompt_ids": rng.integers(2, 100, 3).tolist()} for _ in range(16)
    ]
    wf = RLVRWorkflow(
        lambda *a, **k: 1.0,
        GenerationHyperparameters(max_new_tokens=4, greedy=False),
    )

    def lag_counters() -> dict[str, dict[str, float]]:
        samples = parse_prometheus_text(get_registry().render_prometheus())
        out: dict[str, dict[str, float]] = {}
        for label in LAG_BUCKET_LABELS:
            out[label] = {
                "tokens": labeled_total(
                    samples, "areal_train_lag_tokens_total", lag_bucket=label
                )
                or 0.0,
                "kl": labeled_total(
                    samples,
                    "areal_train_lag_behave_kl_sum_total",
                    lag_bucket=label,
                )
                or 0.0,
                "capped": labeled_total(
                    samples, "areal_train_lag_capped_total", lag_bucket=label
                )
                or 0.0,
            }
        return out

    c0 = lag_counters()
    trainer = PPOTrainer(cfg, dataset, rollout=rollout, actor_engine=engine)
    try:
        trainer.train(workflow=wf)
        journal = trainer.journal
        if journal is None:
            raise AssertionError("trajectory journal was not attached")
        journal_tasks = {e.task_id for e in journal.scan()}
    finally:
        trainer.close()
        server.stop()
    c1 = lag_counters()
    delta = {
        label: {k: c1[label][k] - c0[label][k] for k in c0[label]}
        for label in LAG_BUCKET_LABELS
    }
    if delta["0"]["tokens"] <= 0:
        raise AssertionError(f"no lag-0 tokens trained: {delta}")
    high_label = next(
        (l for l in ("4+", "2", "1") if delta[l]["tokens"] > 0), None
    )
    if high_label is None:
        raise AssertionError(
            f"forced staleness produced no off-policy bucket: {delta} — "
            "every trained token was lag 0"
        )
    kl0 = delta["0"]["kl"] / delta["0"]["tokens"]
    klh = delta[high_label]["kl"] / delta[high_label]["tokens"]
    if not klh > kl0:
        raise AssertionError(
            f"no KL separation: lag-0 behave-|KL| {kl0:.5f} vs lag-"
            f"{high_label} {klh:.5f} — staleness is not being measured as "
            "drift"
        )
    capped = sum(d["capped"] for d in delta.values())
    if capped <= 0:
        raise AssertionError(
            f"behave cap {actor_cfg.behav_imp_weight_cap} left zero cap-hit "
            "tail mass — the dead-weight tail is not observed"
        )
    # lineage join: generate -> journal -> consume -> update by trace id
    ring = lineage_mod.get_lineage()
    joined = [
        r
        for r in ring.recent()
        if r.trained_version is not None and r.clip_fraction is not None
    ]
    if not joined:
        raise AssertionError("no lineage record joined to train-step stats")
    chained = [
        r
        for r in joined
        if r.journaled
        and r.consumed_version is not None
        and r.task_id in journal_tasks
    ]
    if not chained:
        raise AssertionError(
            "no lineage record closes the full chain (journaled + consumed "
            f"+ trained): {len(joined)} joined, journal has "
            f"{len(journal_tasks)} tasks"
        )
    lags = sorted(
        {r.lag_at_consume for r in chained if r.lag_at_consume is not None}
    )
    return (
        f"{len(joined)} trajectories joined generate->journal->consume->"
        f"update ({len(chained)} full-chain, consume lags {lags}); "
        f"behave-|KL| lag-0 {kl0:.4f} < lag-{high_label} {klh:.4f}; "
        f"cap-hit tail {capped:.0f} tokens "
        f"(cap {actor_cfg.behav_imp_weight_cap})"
    )


def preemption_self_test(kill_after_version: int = 1) -> str:
    """The whole spot-TPU lifecycle on CPU (docs/fault_tolerance.md):

    1. tiny 1-replica fleet + real PPOTrainer (journal on, recover
       freq_steps=1, async dumps);
    2. a REAL SIGTERM delivered to this process mid-step — the flag-only
       handler + step-loop polling must abort the step, emergency-dump,
       and return from train() cleanly (``trainer.preempted``);
    3. relaunch: a second trainer resumes one step after the dump and
       replays >= 1 journaled in-bound trajectory (re-generation saved);
    4. the replica drains under load: 429s on new admissions, in-flight
       work finished/parked, 0 leaked pages, 0 unterminated timelines;
    5. async-vs-sync checkpoint pause: the async path's step-loop pause
       must be <= 1/5 of the measured sync save time.
    """
    import os
    import signal
    import threading
    import time

    import jax
    import numpy as np

    from areal_tpu.api.config import (
        DatasetConfig,
        InferenceEngineConfig,
        MeshConfig,
        MicroBatchSpec,
        OptimizerConfig,
        PPOActorConfig,
        PPOConfig,
        PreemptionConfig,
        RecoverConfig,
        SaverConfig,
        ServerConfig,
        StatsLoggerConfig,
        TrajectoryJournalConfig,
    )
    from areal_tpu.api.io_struct import (
        FinetuneSpec,
        GenerationHyperparameters,
        ModelRequest,
        StepInfo,
    )
    from areal_tpu.engine.train_engine import JaxTrainEngine
    from areal_tpu.inference.client import RemoteJaxEngine
    from areal_tpu.inference.decode_engine import DecodeEngine
    from areal_tpu.inference.server import ServerThread
    from areal_tpu.models import qwen
    from areal_tpu.trainer.rl_trainer import PPOTrainer
    from areal_tpu.workflow.rlvr import RLVRWorkflow

    import tempfile

    root = tempfile.mkdtemp(prefix="areal_preempt_selftest_")
    tiny = tiny_model_config()

    def make_actor_cfg():
        return PPOActorConfig(
            init_from_scratch=True,
            dtype="float32",
            param_dtype="float32",
            mesh=MeshConfig(data=-1, fsdp=1, seq=1, model=1),
            optimizer=OptimizerConfig(lr=1e-3, lr_scheduler_type="constant"),
            mb_spec=MicroBatchSpec(max_tokens_per_mb=100_000),
            bucket_step=64,
            group_size=1,
            ppo_n_minibatches=1,
            adv_norm=None,
            kl_ctl=0.0,
            use_decoupled_loss=False,
            recompute_logprob=False,
        )

    def make_cfg(actor_cfg):
        cfg = PPOConfig(
            experiment_name="preempt",
            trial_name="t0",
            total_train_epochs=50,
            weight_update_mode="mem",
            gconfig=GenerationHyperparameters(
                n_samples=1, max_new_tokens=4, greedy=True
            ),
            train_dataset=DatasetConfig(batch_size=2, shuffle=True),
            actor=actor_cfg,
            saver=SaverConfig(fileroot=root),
            checkpointer=SaverConfig(fileroot=root),
            recover=RecoverConfig(mode="auto", freq_steps=1, fileroot=root),
            stats_logger=StatsLoggerConfig(fileroot=root),
        )
        cfg.evaluator.fileroot = root
        cfg.cluster.fileroot = root
        cfg.rollout = InferenceEngineConfig(
            max_concurrent_rollouts=4,
            consumer_batch_size=2,
            max_head_offpolicyness=4,
            request_timeout=120,
            journal=TrajectoryJournalConfig(enabled=True),
        )
        cfg.preemption = PreemptionConfig(grace_s=60.0)
        return cfg

    # -- fleet -------------------------------------------------------------
    engine = JaxTrainEngine(make_actor_cfg(), model_config=tiny)
    engine.initialize(FinetuneSpec(1, 16, 2))
    scfg = ServerConfig(
        max_batch_size=4,
        max_seq_len=128,
        decode_steps_per_call=4,
        seed=0,
        mesh=MeshConfig(data=-1, fsdp=1, seq=1, model=1),
    )
    dec = DecodeEngine(
        scfg, params=jax.tree.map(np.asarray, engine.params), model_cfg=tiny
    )
    dec.initialize()
    server = ServerThread(scfg, dec)
    server.start()
    rng = np.random.default_rng(0)
    dataset = [
        {"prompt_ids": rng.integers(2, 100, 3).tolist()} for _ in range(16)
    ]
    wf = RLVRWorkflow(
        lambda *a, **k: 1.0,
        GenerationHyperparameters(max_new_tokens=4, greedy=True),
    )

    def make_rollout():
        r = RemoteJaxEngine(
            make_cfg(make_actor_cfg()).rollout, addresses=[server.address]
        )
        r.initialize()
        return r

    rollout = make_rollout()
    cfg = make_cfg(make_actor_cfg())
    trainer = PPOTrainer(cfg, dataset, rollout=rollout, actor_engine=engine)

    # -- SIGTERM mid-step --------------------------------------------------
    def killer():
        deadline = time.time() + 180
        while time.time() < deadline:
            if rollout.get_version() >= kill_after_version:
                break
            time.sleep(0.05)
        time.sleep(0.2)  # land inside the NEXT step's rollout wait
        os.kill(os.getpid(), signal.SIGTERM)

    kt = threading.Thread(target=killer, daemon=True)
    kt.start()
    trainer.train(workflow=wf)
    kt.join(timeout=10)
    if not trainer.preempted:
        raise AssertionError("SIGTERM did not preempt the trainer")
    pair = trainer.recover_handler.read_recover_info()
    if pair is None:
        raise AssertionError("no loadable recover generation after preemption")
    info, _ = pair
    dumped_step = info.last_step_info.global_step
    journal_stats = trainer.journal.stats()
    trainer.close()

    # -- relaunch: resume + journal replay ---------------------------------
    engine2 = JaxTrainEngine(make_actor_cfg(), model_config=tiny)
    engine2.initialize(FinetuneSpec(1, 16, 2))
    rollout2 = make_rollout()
    trainer2 = PPOTrainer(
        make_cfg(make_actor_cfg()), dataset, rollout=rollout2, actor_engine=engine2
    )
    if trainer2.recover_info is None:
        raise AssertionError("relaunch did not load the recover checkpoint")
    resume_step = trainer2.recover_info.last_step_info.next().global_step
    if resume_step != dumped_step + 1:
        raise AssertionError(
            f"resume at step {resume_step}, expected {dumped_step + 1} "
            "(one recover interval)"
        )
    replayed = len(rollout2.executor._results)
    if replayed < 1:
        raise AssertionError(
            "relaunch replayed no journaled trajectories "
            f"(journal had {journal_stats['appended']} appended)"
        )

    # -- async-vs-sync checkpoint pause ------------------------------------
    sync_saver_dir = os.path.join(root, "pause_probe")
    from areal_tpu.utils.saver import Saver

    probe = Saver(
        SaverConfig(fileroot=sync_saver_dir, freq_steps=1), None, for_recover=True
    )
    t0 = time.monotonic()
    probe.save(engine2, 0, 0, 100)
    engine2.wait_for_save()
    sync_s = time.monotonic() - t0
    t0 = time.monotonic()
    probe.save_async(engine2, 0, 0, 101)
    async_pause_s = time.monotonic() - t0
    probe.wait_async()
    if async_pause_s * 5 > sync_s:
        raise AssertionError(
            f"async save pause {async_pause_s * 1e3:.1f}ms > 1/5 of sync "
            f"save {sync_s * 1e3:.1f}ms"
        )
    trainer2.close()

    # -- replica drain under load ------------------------------------------
    done: list = []
    for i in range(3):
        dec.submit(
            ModelRequest(
                input_ids=[3 + i, 7, 9],
                rid=f"drainload-{i}",
                gconfig=GenerationHyperparameters(
                    max_new_tokens=100_000, greedy=True, ignore_eos=True
                ),
            ),
            done.append,
        )
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if any(t is not None and t.out_tokens for t in dec._slot_task):
            break
        time.sleep(0.01)
    summary = dec.drain(budget_s=2.0)
    admit, reason, _ = dec.check_admission()
    if admit or reason != "draining":
        raise AssertionError(f"drained replica still admits ({reason!r})")
    if len(done) != 3:
        raise AssertionError(
            f"{3 - len(done)} in-flight requests left without a terminal"
        )
    if summary["leaked_pages"] != 0:
        raise AssertionError(f"{summary['leaked_pages']} KV pages leaked")
    if summary["unterminated_timelines"] != 0:
        raise AssertionError(
            f"{summary['unterminated_timelines']} unterminated timelines"
        )
    server.stop()
    return (
        f"SIGTERM mid-step -> emergency dump @ step {dumped_step}, resume @ "
        f"{resume_step}, {replayed} journaled trajectories replayed "
        f"(re-generation saved), drain {summary['drain_seconds']:.2f}s "
        f"(parked {summary['parked']}, 0 leaks), ckpt pause sync "
        f"{sync_s * 1e3:.0f}ms vs async {async_pause_s * 1e3:.0f}ms"
    )


def routing_self_test(
    n_replicas: int = 3, n_sessions: int = 6, turns: int = 3, seed: int = 17
) -> str:
    """Cache-aware routing brain end to end (docs/serving.md "Cache-aware
    routing"): a 3-replica fleet under seeded chaos stalls serves an
    80%-shared-prefix multi-turn workload through BOTH policies.

    Asserts: (1) cache-aware yields measurably more warm suffix-only
    prefill (radix hit tokens) than round-robin on the identical workload;
    (2) router decisions are audited into the flight ring with reasons;
    (3) an evicted replica receives ZERO routes while down, and after the
    respawn/rejoin its shadow prefix index reads cold."""
    import asyncio

    import jax

    from areal_tpu.api.config import (
        ChaosConfig,
        FaultToleranceConfig,
        InferenceEngineConfig,
        MeshConfig,
        RoutingConfig,
        ServerConfig,
    )
    from areal_tpu.api.io_struct import GenerationHyperparameters, ModelRequest
    from areal_tpu.inference.client import RemoteJaxEngine, close_loop_sessions
    from areal_tpu.inference.decode_engine import DecodeEngine
    from areal_tpu.inference.server import ServerThread
    from areal_tpu.models import qwen
    from areal_tpu.observability import timeline as tl_mod
    from areal_tpu.robustness import FaultInjector

    tiny = tiny_model_config()
    params = qwen.init_params(jax.random.PRNGKey(0), tiny)
    servers = []
    clients = []
    psz = 16
    try:
        for i in range(n_replicas):
            cfg = ServerConfig(
                max_batch_size=4,
                max_seq_len=256,
                decode_steps_per_call=4,
                page_size=psz,
                seed=i,
                mesh=MeshConfig(data=-1, fsdp=1, seq=1, model=1),
            )
            eng = DecodeEngine(cfg, params=params, model_cfg=tiny)
            eng.initialize()
            st = ServerThread(cfg, eng)
            st.start()
            servers.append(st)
        addrs = [s.address for s in servers]

        def make_client(policy: str) -> RemoteJaxEngine:
            c = RemoteJaxEngine(
                InferenceEngineConfig(
                    max_concurrent_rollouts=8,
                    consumer_batch_size=2,
                    max_head_offpolicyness=100,
                    request_timeout=60,
                    request_retries=5,
                    routing_policy=policy,
                    routing=RoutingConfig(
                        poll_interval_s=0.25, shadow_page_size=psz
                    ),
                    fault_tolerance=FaultToleranceConfig(
                        backoff_base_s=0.05,
                        backoff_max_s=0.5,
                        probe_interval_s=60.0,
                    ),
                ),
                addresses=list(addrs),
            )
            c.initialize()
            c.install_fault_injector(
                FaultInjector(
                    ChaosConfig(
                        enabled=True,
                        seed=seed,
                        stall_prob=0.2,
                        stall_s=0.05,
                        path_prefix="/generate",
                    )
                )
            )
            clients.append(c)
            return c

        g = GenerationHyperparameters(max_new_tokens=8, greedy=True)

        async def drive(client: RemoteJaxEngine, tag: str) -> None:
            # multi-turn sessions: each turn's prompt extends the previous
            # sequence — the conversation-history prefix structure the
            # router exploits. 80%+ of every turn-2+ prompt is shared
            # with state one replica already holds.
            async def session(s: int) -> None:
                base = [2 + (s % 40), 5] + [
                    3 + ((s * 7 + j) % 90) for j in range(62)
                ]
                ids = list(base)
                for t in range(turns):
                    req = ModelRequest(
                        input_ids=ids,
                        rid=f"{tag}-s{s}-t{t}",
                        gconfig=g,
                    )
                    resp = await client.agenerate(req)
                    ids = ids + list(resp.output_tokens) + [9 + t, 11, 13]
            await asyncio.gather(*[session(s) for s in range(n_sessions)])
            await close_loop_sessions()

        def fleet_stats() -> tuple[int, int]:
            hit = sum(s.engine.stats["prefix_hit_tokens"] for s in servers)
            pf = sum(s.engine.stats["prefill_tokens"] for s in servers)
            return hit, pf

        # --- arm 1: round robin -------------------------------------------
        rr = make_client("round_robin")
        asyncio.run(drive(rr, "rr"))
        rr_hit, rr_pf = fleet_stats()
        # flush every radix tree so the cache-aware arm starts as cold as
        # the round-robin arm did
        for s in servers:
            s.engine.flush_prefix_cache()
        # --- arm 2: cache aware -------------------------------------------
        ca = make_client("cache_aware")
        ca.router.poller.poll_once()  # live snapshots before first choice
        asyncio.run(drive(ca, "ca"))
        ca_hit, ca_pf = fleet_stats()
        ca_hit, ca_pf = ca_hit - rr_hit, ca_pf - rr_pf
        if ca_hit <= rr_hit:
            raise AssertionError(
                f"cache-aware warm prefill did not improve: hit tokens "
                f"{ca_hit} (cache_aware) vs {rr_hit} (round_robin)"
            )
        st = ca.router.stats()
        if st["decisions"].get("prefix_overlap", 0) == 0:
            raise AssertionError(
                f"no prefix_overlap decisions recorded: {st['decisions']}"
            )
        # decisions must be auditable in the flight ring
        ring = tl_mod.get_flight_recorder().snapshot()["events"]
        router_events = [e for e in ring if e.get("kind") == "router_decision"]
        if not router_events:
            raise AssertionError("no router_decision events in flight ring")
        if not all(
            (e.get("data") or {}).get("reason") for e in router_events[-10:]
        ):
            raise AssertionError("router_decision events missing reasons")
        # --- evict -> zero routes while down -> cold after respawn --------
        victim = addrs[0]
        ca.fleet.evict(victim)  # PR 3 supervision's administrative eviction
        routed = {
            ca.choose_server(req=ModelRequest(input_ids=[2, 3, 4 + i], gconfig=g))
            for i in range(24)
        }
        if victim in routed:
            raise AssertionError(f"evicted replica {victim} was routed to")
        # respawn/rejoin: the probe path closes the circuit and resets the
        # replica's router state (its radix tree restarted empty)
        ca.probe_fleet()
        if ca.fleet.state(victim) == "open":
            raise AssertionError("probe did not rejoin the healthy replica")
        if ca.router.shadow.pages_for(victim) != 0:
            raise AssertionError(
                "rejoined replica's shadow index was not reset to cold"
            )
        routed_after = {
            ca.choose_server(req=ModelRequest(input_ids=[2, 3, 4 + i], gconfig=g))
            for i in range(24)
        }
        if victim not in routed_after:
            raise AssertionError("rejoined replica never selected again")
        return (
            f"{n_sessions}x{turns}-turn sessions over {n_replicas} replicas "
            f"under chaos: warm hit tokens {rr_hit} (rr) -> {ca_hit} "
            f"(cache-aware), suffix prefill {rr_pf} -> {ca_pf}, "
            f"{len(router_events)} audited decisions, evicted replica got "
            f"0/24 routes while down and rejoined cold"
        )
    finally:
        for c in clients:
            c.destroy()
        for s in servers:
            s.stop()


def autopilot_self_test(
    window_s: float = 6.0,
    n_interactive: int = 8,
    n_rollout: int = 24,
    seed: int = 23,
) -> str:
    """Goodput autopilot end to end (docs/autopilot.md): one replica
    behind a 4-slot gateway, driven at ~2x capacity by a rollout flood
    under seeded chaos stalls, with the admission controller live.

    Asserts: (1) interactive traffic sheds under the static headroom=0
    start; (2) the controller WIDENS the interactive headroom in response
    (setpoint > 0, applied to the live gateway); (3) the interactive shed
    count drops in the second measured window; (4) every setpoint change
    is auditable in the flight ring (kind=autopilot_decision with
    controller/knob/old/new/reason). All measured on CPU."""
    import asyncio

    from areal_tpu.observability import timeline as tl_mod
    from areal_tpu.tools.bench_gateway import (
        LocalFleet,
        bench_autopilot_config,
        drive_gateway,
    )

    ap_cfg = bench_autopilot_config(interval_s=0.3)
    # the widening direction is the subject here; park the narrowing
    # clock so a quiet stretch inside the short window can't retract the
    # headroom mid-measurement (production narrows over minutes)
    ap_cfg.admission.narrow_after_quiet_rounds = 10_000
    fleet = LocalFleet(
        n_replicas=1,
        max_batch_size=1,
        chaos_stall_prob=0.5,
        chaos_stall_s=0.4,
        max_queue_depth=32,
        gateway_max_inflight=4,
        gateway_interactive_headroom=0,
        seed=seed,
        autopilot_cfg=ap_cfg,
    )
    ring = tl_mod.get_flight_recorder()
    seq0 = max(
        (e.get("seq", 0) for e in ring.snapshot()["events"]), default=0
    )

    async def run() -> tuple[list[int], int]:
        gateway_url, admin_key = await fleet.astart()
        try:
            sheds = []
            for _ in range(2):
                before = fleet.gw_state.shed["interactive"]
                await drive_gateway(
                    gateway_url,
                    admin_key,
                    n_interactive=n_interactive,
                    n_rollout=n_rollout,
                    duration_s=window_s,
                    interactive_tokens=8,
                    rollout_tokens=128,
                    interactive_deadline_s=window_s * 3,
                    rollout_deadline_s=window_s * 3,
                )
                sheds.append(fleet.gw_state.shed["interactive"] - before)
            return sheds, fleet.gw_state.interactive_headroom
        finally:
            await fleet.astop()

    sheds, headroom = asyncio.run(run())
    if sheds[0] == 0:
        raise AssertionError(
            "interactive traffic never shed under headroom=0 — the "
            "scenario was not a 2x overload"
        )
    if headroom <= 0:
        raise AssertionError(
            "admission controller never widened the interactive headroom"
        )
    if sheds[1] >= sheds[0]:
        raise AssertionError(
            f"interactive shed count did not drop after the controller "
            f"widened headroom: {sheds[0]} -> {sheds[1]}"
        )
    evs = [
        e
        for e in ring.snapshot()["events"]
        if e.get("kind") == "autopilot_decision" and e.get("seq", 0) > seq0
    ]
    if not evs:
        raise AssertionError("no autopilot_decision events in flight ring")
    widen = [
        e
        for e in evs
        if (e.get("data") or {}).get("knob") == "gateway_interactive_headroom"
        and (e.get("data") or {}).get("reason") == "interactive_shed"
    ]
    if not widen:
        raise AssertionError(
            "no audited interactive_shed headroom decision in flight ring"
        )
    if not all(
        {"controller", "knob", "old", "new", "reason"}
        <= set(e.get("data") or {})
        for e in evs
    ):
        raise AssertionError("autopilot_decision events missing audit fields")
    return (
        f"{n_interactive}+{n_rollout} clients @~2x through a 4-slot "
        f"gateway: interactive sheds {sheds[0]} -> {sheds[1]} after the "
        f"controller widened headroom 0 -> {headroom}; "
        f"{len(evs)} audited decisions in the flight ring"
    )


def microbench_self_test() -> str:
    """Kernel-observatory smoke (docs/perf.md "Kernel observatory"):

    - the fast microbench registry runs end to end on CPU, every entry
      with a positive wall and — where the bench declares FLOPs — a
      non-null roofline fraction (the calibrated CPU peak fallback);
    - the compare gate is silent on a self-compare, flags a seeded 2x
      regression on EVERY bench, and treats new/missing entries as
      warnings, not failures;
    - a live tiny engine's per-step phase breakdown obeys the exact-sum
      identity (named phases + other_s == step wall) on every recorded
      step, and its steady-state roofline fraction is non-null."""
    import copy
    import threading

    import jax

    from areal_tpu.api.config import MeshConfig, ServerConfig
    from areal_tpu.api.io_struct import GenerationHyperparameters, ModelRequest
    from areal_tpu.inference.decode_engine import DecodeEngine
    from areal_tpu.models import qwen
    from areal_tpu.observability.kernel_probe import DECODE_PHASES
    from areal_tpu.tools import microbench as mb

    # 1. fast registry end to end
    names = mb.fast_names()
    res = mb.run_suite(names, iters=3, warmup=1)
    rooflines = 0
    for name in names:
        e = res["benches"][name]
        assert e["wall_s"] > 0, f"{name}: non-positive wall {e['wall_s']}"
        if e["flops"]:
            assert e["roofline_frac"] is not None, (
                f"{name}: declared FLOPs but null roofline (peak fallback "
                "broken?)"
            )
            rooflines += 1
    assert rooflines >= 3, f"only {rooflines} benches produced a roofline"

    # 2. compare gate semantics
    r = mb.compare(res, res)
    assert not r["regressions"] and not r["new"] and not r["missing"], (
        f"self-compare must be silent: {r}"
    )
    seeded = copy.deepcopy(res)
    for e in seeded["benches"].values():
        e["wall_s"] *= 2.0
    r2 = mb.compare(seeded, res)
    flagged = {x["bench"] for x in r2["regressions"]}
    assert flagged == set(names), (
        f"seeded 2x must flag every bench: {flagged} vs {set(names)}"
    )
    renamed = copy.deepcopy(res)
    renamed["benches"]["brand_new"] = renamed["benches"].pop(names[0])
    r3 = mb.compare(renamed, res)
    assert not r3["regressions"] and r3["new"] == ["brand_new"], (
        f"rename must warn, not fail: {r3}"
    )

    # 3. live-engine phase-sum identity + steady-state roofline
    tiny = tiny_model_config()
    params = qwen.init_params(jax.random.PRNGKey(0), tiny)
    cfg = ServerConfig(
        max_batch_size=4,
        max_seq_len=256,
        decode_steps_per_call=4,
        seed=1,
        mesh=MeshConfig(data=-1, fsdp=1, seq=1, model=1),
    )
    eng = DecodeEngine(cfg, params=params, model_cfg=tiny)
    eng.initialize()
    eng.start()
    try:
        done = threading.Event()
        got: list = []
        lock = threading.Lock()

        def cb(resp):
            with lock:
                got.append(resp)
                if len(got) == 4:
                    done.set()

        for i in range(4):
            eng.submit(
                ModelRequest(
                    input_ids=[3 + i, 7, 9, 11],
                    gconfig=GenerationHyperparameters(
                        max_new_tokens=12, greedy=True
                    ),
                ),
                cb,
            )
        assert done.wait(timeout=300.0), f"only {len(got)}/4 finished"
        recs = eng.kprobe.recent()
        assert recs, "no decode steps recorded by the kernel probe"
        worst = 0.0
        for rec in recs:
            bd = rec["breakdown"]
            named = sum(bd[f"{p}_s"] for p in DECODE_PHASES)
            worst = max(worst, abs(named + bd["other_s"] - bd["total_s"]))
        assert worst < 1e-9, f"phase-sum identity violated by {worst:.3e}s"
        ks = eng.kernel_stats()
        assert ks["roofline_fraction"] is not None, (
            "steady-state roofline must be non-null on CPU (calibrated "
            "peak fallback)"
        )
    finally:
        eng.stop()
    return (
        f"{len(names)} benches ({rooflines} rooflines), seeded 2x flagged "
        f"{len(flagged)}/{len(names)}, identity residual {worst:.1e}s over "
        f"{len(recs)} steps, steady roofline "
        f"{ks['roofline_fraction']:.4f}"
    )


def spec_decode_self_test() -> str:
    """Speculative decoding end to end (docs/serving.md "Speculative
    decoding"): a spec-enabled tiny engine over an acceptance-friendly
    repetitive workload.

    Asserts: (1) speculation genuinely ran — rounds > 0 and acceptance
    rate > 0 (prompt-lookup drafts of a periodic prompt must land);
    (2) zero leaked KV pages after settling (free + radix-held == pool
    total: rejected tails were rolled back through the refcounted pool);
    (3) request timelines carry the draft/verify stages and the kernel
    probe's per-step exact-sum identity holds with the two new phases
    in the taxonomy."""
    import threading
    import time

    import jax

    from areal_tpu.api.config import MeshConfig, ServerConfig, SpeculativeConfig
    from areal_tpu.api.io_struct import GenerationHyperparameters, ModelRequest
    from areal_tpu.inference.decode_engine import DecodeEngine
    from areal_tpu.models import qwen
    from areal_tpu.observability.kernel_probe import DECODE_PHASES

    tiny = tiny_model_config()
    params = qwen.init_params(jax.random.PRNGKey(0), tiny)
    cfg = ServerConfig(
        max_batch_size=2,
        max_seq_len=256,
        decode_steps_per_call=4,
        page_size=16,
        seed=0,
        mesh=MeshConfig(data=-1, fsdp=1, seq=1, model=1),
        speculative=SpeculativeConfig(enabled=True, drafter="tree"),
    )
    eng = DecodeEngine(cfg, params=params, model_cfg=tiny)
    eng.initialize()
    eng.start()
    try:
        done = threading.Event()
        got: list = []
        lock = threading.Lock()

        def cb(resp):
            with lock:
                got.append(resp)
                if len(got) == 3:
                    done.set()

        for i in range(3):
            eng.submit(
                ModelRequest(
                    # periodic prompts: prompt-lookup drafting proposes the
                    # continuation the model itself settles into
                    input_ids=[7 + i, 3, 9] * 8,
                    gconfig=GenerationHyperparameters(
                        max_new_tokens=32, greedy=True
                    ),
                ),
                cb,
            )
        assert done.wait(timeout=300.0), f"only {len(got)}/3 finished"
        rounds = eng.stats["spec_rounds"]
        drafted = eng.stats["spec_draft_tokens"]
        accepted = eng.stats["spec_accepted_tokens"]
        assert rounds > 0, "speculation never ran"
        assert drafted > 0 and accepted > 0, (
            f"acceptance rate must be > 0 on a repetitive prompt "
            f"(drafted {drafted}, accepted {accepted})"
        )
        # settle, then the allocator audit: every page free or radix-held
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            snap = eng.admission_snapshot()
            if snap["queue_depth"] == 0 and snap["active_slots"] == 0:
                break
            time.sleep(0.05)
        held = eng.prefix_cache_stats()["pages_held"] if eng._radix is not None else 0
        leaked = eng.pool.used - held
        assert leaked == 0, f"{leaked} leaked KV pages after settling"
        # timeline stage coverage: the spec rounds marked draft + verify
        staged = set()
        for rec in eng.timeline.recent():
            staged |= {ev["stage"] for ev in rec["events"]}
        for want in ("draft", "verify"):
            assert want in staged, (
                f"timeline missing the {want} stage (saw {sorted(staged)})"
            )
        # kernel-probe exact-sum identity over the widened phase taxonomy
        recs = eng.kprobe.recent()
        assert recs, "no decode steps recorded by the kernel probe"
        worst = 0.0
        spec_phase_s = 0.0
        for rec in recs:
            bd = rec["breakdown"]
            named = sum(bd[f"{p}_s"] for p in DECODE_PHASES)
            worst = max(worst, abs(named + bd["other_s"] - bd["total_s"]))
            spec_phase_s += bd["draft_s"] + bd["verify_s"]
        assert worst < 1e-9, f"phase-sum identity violated by {worst:.3e}s"
        assert spec_phase_s > 0, "draft/verify phases recorded no time"
    finally:
        eng.stop()
    return (
        f"acceptance {accepted}/{drafted} "
        f"({accepted / drafted:.0%}) over {rounds} rounds, 0 leaked pages, "
        f"draft+verify staged, identity residual {worst:.1e}s"
    )


def gateway_tier_self_test(
    n_replicas: int = 2,
    n_shards: int = 3,
    n_interactive: int = 9,
    n_rollout: int = 15,
    duration_s: float = 2.0,
    seed: int = 31,
) -> str:
    """Horizontally-sharded gateway tier end to end (docs/serving.md
    "Gateway tier"): 3 shards over a 2-replica fleet, sessions placed by
    the consistent-hash tier client, with seeded chaos arming a
    mid-run shard kill.

    Asserts: (1) the kill actually fired and the membership view
    converged to the survivors; (2) zero responseless requests — every
    session completed or ended on a real terminal status, and with no
    backpressure in this fleet that means completed == sent; (3) the
    survivors absorbed the re-hashed load: clients observed failovers,
    and the keyspace the victim owned was served by surviving shards."""
    import asyncio
    import time

    from areal_tpu.api.config import ChaosConfig
    from areal_tpu.robustness import FaultInjector
    from areal_tpu.tools.bench_gateway import (
        LocalFleet,
        _TierResolver,
        drive_gateway,
    )

    async def run() -> str:
        fleet = LocalFleet(
            n_replicas=n_replicas,
            n_gateways=n_shards,
            chaos_stall_prob=0.0,
            seed=seed,
        )
        await fleet.astart()
        try:
            assert fleet.tier is not None
            assert len(fleet.tier.addresses()) == n_shards
            resolver = _TierResolver(fleet.tier)
            # seeded chaos, restricted to ONE victim shard: the injector
            # fires each registered target at most once, so "kill one
            # shard mid-run" is a harness invariant, not a probability
            victim = sorted(fleet.tier.shards)[-1]
            injector = FaultInjector(
                ChaosConfig(
                    enabled=True,
                    seed=seed,
                    gateway_kill_prob=0.35,
                    path_prefix="/generate",
                )
            )
            injector.set_gateway_kill_targets(
                {victim: fleet.tier.kill_callables()[victim]}
            )
            fleet.client.install_fault_injector(injector)
            report = await drive_gateway(
                fleet.gateway_url,
                fleet.admin_key,
                n_interactive=n_interactive,
                n_rollout=n_rollout,
                duration_s=duration_s,
                interactive_deadline_s=30.0,
                rollout_deadline_s=30.0,
                interactive_tokens=8,
                rollout_tokens=16,
                turns=2,
                greedy=True,
                resolver=resolver,
            )
            tot = report["totals"]
            kills = injector.stats().get("gw_kill", 0)
            assert kills == 1, f"chaos never killed the shard ({kills=})"
            # zero responseless requests: every session reached a real
            # terminal (here: completion — this fleet has no admission
            # limit and generous deadlines, so shed/reaped would itself
            # be a tier failure)
            assert tot["errors"] == 0, f"responseless requests: {tot}"
            assert tot["completed"] == tot["sent"], (
                f"sessions lost mid-failover: {tot}"
            )
            # the survivors absorbed the re-hashed load: clients hit the
            # dead shard, failed over, and the victim's keyspace was
            # served by surviving shards
            assert resolver.failovers > 0, (
                "no client ever failed over — kill happened outside the "
                "measured run?"
            )
            survivors = {
                sid: tok
                for sid, tok in resolver.shard_tokens.items()
                if sid != victim
            }
            assert sum(survivors.values()) > 0, (
                f"survivors served nothing: {resolver.shard_tokens}"
            )
            # membership converges: the victim's record expires from the
            # name_resolve view (abandoned keepalive -> TTL), leaving
            # exactly the survivors serving
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                if len(fleet.tier.directory.view()) == n_shards - 1:
                    break
                await asyncio.sleep(0.2)
            view = fleet.tier.directory.view()
            assert len(view) == n_shards - 1, (
                f"membership never converged: {sorted(view)}"
            )
            assert victim not in view, f"dead shard still in view: {victim}"
            return (
                f"{tot['completed']}/{tot['sent']} sessions completed over "
                f"{n_shards} shards with shard {victim} killed mid-run: "
                f"0 responseless, {resolver.failovers} failovers, "
                f"survivor tokens {sorted(survivors.items())}, membership "
                f"converged to {len(view)} shards"
            )
        finally:
            await fleet.astop()

    return asyncio.run(run())


if __name__ == "__main__":
    raise SystemExit(main())
