"""Per-engine microbenchmarks (reference areal/tools/profile_engines.py /
profile_fsdp.py role): time train_batch / forward_batch / decode chunks on
the current backend for a synthetic model, print one JSON report.

Usage:
  python -m areal_tpu.tools.profile_engines --mode train --hidden 1536 \
      --layers 28 --seqs 6 --len 2048
  python -m areal_tpu.tools.profile_engines --mode decode --slots 128
"""

from __future__ import annotations

import argparse
import json
import time


def profile_train(args) -> dict:
    import numpy as np
    import jax.numpy as jnp

    from areal_tpu.api.config import (
        MeshConfig,
        MicroBatchSpec,
        OptimizerConfig,
        TrainEngineConfig,
    )
    from areal_tpu.api.io_struct import FinetuneSpec
    from areal_tpu.engine.train_engine import JaxTrainEngine
    from areal_tpu.models import qwen
    from areal_tpu.ops import functional as F
    from areal_tpu.utils.data import pad_sequences_to_tensors

    mc = qwen.ModelConfig(
        vocab_size=args.vocab,
        hidden_size=args.hidden,
        intermediate_size=args.hidden * 6 if args.inter is None else args.inter,
        num_layers=args.layers,
        num_heads=args.heads,
        num_kv_heads=args.kv_heads,
        head_dim=128,
        dtype="bfloat16",
    )
    cfg = TrainEngineConfig(
        init_from_scratch=True,
        dtype="bfloat16",
        param_dtype="bfloat16",
        mesh=MeshConfig(data=-1, fsdp=1, seq=1, model=1),
        optimizer=OptimizerConfig(lr=1e-5, lr_scheduler_type="constant"),
        mb_spec=MicroBatchSpec(max_tokens_per_mb=1_000_000),
        logprob_chunk_size=1024,
    )
    eng = JaxTrainEngine(cfg, model_config=mc)
    eng.initialize(FinetuneSpec(1, 1000, 8))
    rng = np.random.default_rng(0)
    trajs = []
    for _ in range(args.seqs):
        n = args.len
        trajs.append(
            {
                "input_ids": rng.integers(0, args.vocab, n).astype(np.int32),
                "loss_mask": np.ones(n, np.float32),
                "old_logprobs": rng.normal(-1.5, 0.1, n).astype(np.float32),
                "advantages": rng.normal(0, 1, n).astype(np.float32),
            }
        )
    batch = pad_sequences_to_tensors(trajs)
    n_tokens = int(np.asarray(batch["attention_mask"]).sum())

    def loss(outputs, b):
        lm = (b["label_valid"] & (b["loss_mask"] > 0)).astype(jnp.float32)
        l, _ = F.ppo_actor_loss_fn(
            logprobs=outputs["logprobs"],
            proximal_logprobs=b["old_logprobs"],
            old_logprobs=b["old_logprobs"],
            advantages=b["advantages"],
            loss_mask=lm,
        )
        return l, {}

    wf = lambda d: float((np.asarray(d["loss_mask"]) > 0).sum())  # noqa: E731
    t0 = time.monotonic()
    eng.train_batch(batch, loss, wf)
    compile_s = time.monotonic() - t0
    t0 = time.monotonic()
    for _ in range(args.steps):
        stats = eng.train_batch(batch, loss, wf)
    dt = (time.monotonic() - t0) / args.steps
    n_params = sum(x.size for x in __import__("jax").tree.leaves(eng.params))
    mfu = n_tokens * 6 * n_params / dt / 197e12
    return {
        "mode": "train",
        "tokens_per_step": n_tokens,
        "compile_s": round(compile_s, 1),
        "step_ms": round(dt * 1e3, 1),
        "tok_s": round(n_tokens / dt, 1),
        "mfu_v5e": round(mfu, 3),
        "loss": stats["loss"],
    }


def profile_decode(args) -> dict:
    import numpy as np
    import jax

    from areal_tpu.api.config import MeshConfig, ServerConfig
    from areal_tpu.api.io_struct import GenerationHyperparameters, ModelRequest
    from areal_tpu.inference.decode_engine import DecodeEngine
    from areal_tpu.models import qwen

    mc = qwen.ModelConfig(
        vocab_size=args.vocab,
        hidden_size=args.hidden,
        intermediate_size=args.hidden * 6 if args.inter is None else args.inter,
        num_layers=args.layers,
        num_heads=args.heads,
        num_kv_heads=args.kv_heads,
        head_dim=128,
        dtype="bfloat16",
    )
    cfg = ServerConfig(
        max_batch_size=args.slots,
        max_seq_len=args.ctx,
        decode_steps_per_call=32,
        mesh=MeshConfig(data=-1, fsdp=1, seq=1, model=1),
    )
    params = jax.jit(lambda k: qwen.init_params(k, mc))(jax.random.PRNGKey(0))
    eng = DecodeEngine(cfg, params=params, model_cfg=mc)
    eng.initialize()
    eng.start()
    rng = np.random.default_rng(0)
    import threading

    n_req = args.slots * 2
    done = threading.Event()
    results = []

    def cb(r):
        results.append(r)
        if len(results) == n_req:
            done.set()

    eng.generate_sync(
        ModelRequest(
            input_ids=rng.integers(0, 1000, 128).tolist(),
            gconfig=GenerationHyperparameters(max_new_tokens=16, greedy=True),
        ),
        timeout=600,
    )
    t0 = time.monotonic()
    for _ in range(n_req):
        eng.submit(
            ModelRequest(
                input_ids=rng.integers(0, 1000, 128).tolist(),
                gconfig=GenerationHyperparameters(
                    max_new_tokens=args.new_tokens, temperature=1.0
                ),
            ),
            cb,
        )
    done.wait(timeout=900)
    dt = time.monotonic() - t0
    toks = sum(len(r.output_tokens) for r in results)
    eng.stop()
    return {
        "mode": "decode",
        "slots": args.slots,
        "requests": len(results),
        "tok_s": round(toks / dt, 1),
        "stats": {k: int(v) for k, v in eng.stats.items()},
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--mode", choices=("train", "decode"), default="train")
    p.add_argument("--hidden", type=int, default=1536)
    p.add_argument("--inter", type=int, default=None)
    p.add_argument("--layers", type=int, default=28)
    p.add_argument("--heads", type=int, default=12)
    p.add_argument("--kv-heads", type=int, default=2)
    p.add_argument("--vocab", type=int, default=151936)
    p.add_argument("--seqs", type=int, default=6)
    p.add_argument("--len", type=int, default=2048)
    p.add_argument("--steps", type=int, default=3)
    p.add_argument("--slots", type=int, default=128)
    p.add_argument("--ctx", type=int, default=512)
    p.add_argument("--new-tokens", type=int, default=256)
    args = p.parse_args(argv)
    report = profile_train(args) if args.mode == "train" else profile_decode(args)
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
