"""Live terminal dashboard over the areal_tpu telemetry fleet.

Scrapes one or more ``/metrics`` endpoints (inference servers directly, or
a rollout controller's aggregated endpoint) and renders the async-RL
vitals: queue depths, staleness admission state, tokens/s, pause state,
and weight-update latency.

Usage:
    python -m areal_tpu.tools.obs_dashboard --targets host:port,host:port
    python -m areal_tpu.tools.obs_dashboard --targets ... --once
    python -m areal_tpu.tools.obs_dashboard --self-test   # CI smoke mode

``--self-test`` starts a local fake scrape target serving canned
exposition text, runs one aggregation + render round against it, asserts
the pipeline end-to-end (scrape -> parse -> merge -> render), and exits
0/1 — the tier-1 smoke test invokes exactly this.
"""

from __future__ import annotations

import argparse
import sys
import time

from areal_tpu.observability.aggregator import FleetAggregator, FleetSnapshot

# (metric, label filter, display name) rows for the vitals table
_ROWS = (
    ("areal_rollout_capacity", "staleness capacity"),
    ("areal_rollout_running", "rollouts running"),
    ("areal_rollout_accepted_total", "accepted"),
    ("areal_rollout_rejected_total", "rejected"),
    ("areal_executor_input_queue_depth", "input queue"),
    ("areal_executor_eval_queue_depth", "eval queue"),
    ("areal_executor_inflight_tasks", "in flight"),
    ("areal_server_queue_depth", "server queue"),
    ("areal_request_queue_depth", "lifecycle queue"),
    ("areal_decode_batch_occupancy", "batch occupancy"),
    ("areal_server_paused", "paused servers"),
    ("areal_weight_update_total", "weight updates"),
    ("areal_prefix_cache_pages_held", "prefix-cache pages"),
)


def _merged_value(snap: FleetSnapshot, name: str) -> float | None:
    """Sum a metric across all its label children in the merged view."""
    total = None
    for (n, _labels), v in snap.merged.items():
        if n == name:
            total = (total or 0.0) + v
    return total


def _merged_value_labeled(
    snap: FleetSnapshot, name: str, **want: str
) -> float | None:
    """Sum a metric over the label children matching ``want`` (e.g. the
    mode="sync" slice of areal_ckpt_save_seconds_sum)."""
    total = None
    for (n, labels), v in snap.merged.items():
        if n != name:
            continue
        ld = dict(labels)
        if all(ld.get(k) == val for k, val in want.items()):
            total = (total or 0.0) + v
    return total


def _shed_total(snap: FleetSnapshot) -> float | None:
    """Fleet-wide count of requests turned away with a 429: gateway load
    shedding (by priority class) + engine admission rejections (by reason)."""
    gw = _merged_value(snap, "areal_gateway_shed_total")
    adm = _merged_value(snap, "areal_admission_rejected_total")
    if gw is None and adm is None:
        return None
    return (gw or 0.0) + (adm or 0.0)


def _histogram_quantile(
    snap: FleetSnapshot, name: str, q: float
) -> float | None:
    """Approximate quantile from merged histogram buckets (classic
    Prometheus-style linear interpolation inside the winning bucket).
    Label children (e.g. ttft's priority classes) are summed — per-``le``
    cumulative counts stay cumulative under addition."""
    buckets: dict[float, float] = {}
    target_name = name + "_bucket"
    for (n, labels), v in snap.merged.items():
        if n != target_name:
            continue
        le = dict(labels).get("le")
        if le is None:
            continue
        lef = float("inf") if le == "+Inf" else float(le)
        buckets[lef] = buckets.get(lef, 0.0) + v
    total = buckets.get(float("inf"))
    if not total:
        return None
    target = q * total
    prev_le, prev_c = 0.0, 0.0
    for le in sorted(buckets):
        c = buckets[le]
        if c >= target:
            if le == float("inf") or c == prev_c:
                return prev_le if le == float("inf") else le
            return prev_le + (le - prev_le) * (target - prev_c) / (c - prev_c)
        prev_le, prev_c = le, c
    return prev_le


def _flight_total(snap: FleetSnapshot) -> float | None:
    """Flight-recorder events across all kinds (rate needs two frames)."""
    return _merged_value(snap, "areal_flight_events_total")


def _mean_per_target(snap: FleetSnapshot, name: str) -> float | None:
    """Mean of a gauge across live targets: fractions (bubble, MFU,
    headroom) are per-process ratios — SUMMING them across a fleet would
    report 200% utilization from two healthy trainers."""
    per = snap.per_target(name)
    if not per:
        return None
    return sum(per.values()) / len(per)


def _min_per_target(snap: FleetSnapshot, name: str) -> float | None:
    """Worst-replica view of a gauge (the HBM headroom that matters is the
    replica closest to OOM, not the fleet average)."""
    per = snap.per_target(name)
    if not per:
        return None
    return min(per.values())


# learning-health lag-bucket taxonomy (infra/staleness_manager.py)
from areal_tpu.infra.staleness_manager import LAG_BUCKET_LABELS as _LAG_BUCKETS

# decode-step phase taxonomy (observability/kernel_probe.py) + the
# identity remainder bucket
_DECODE_PHASES = (
    "admission",
    "radix_match",
    "prefill",
    "draft",
    "dispatch",
    "device_wait",
    "verify",
    "bookkeeping",
    "other",
)

# trainer observatory phase taxonomy (observability/step_timeline.py)
_TRAIN_PHASES = (
    "rollout_wait",
    "host_prep",
    "forward_backward",
    "optimizer",
    "weight_publish",
    "ckpt_eval",
    "other",
)


def _labeled_values(
    snap: FleetSnapshot, name: str, label: str
) -> dict[str, list[float]]:
    """{label value: [per-target raw values]} for one labeled family —
    the un-summed view for gauges whose fleet semantics are not additive
    (autopilot setpoints, last-action ages)."""
    out: dict[str, list[float]] = {}
    for t in snap.targets:
        if not t.up:
            continue
        for n, labels, v in t.samples:
            if n == name:
                out.setdefault(dict(labels).get(label, "?"), []).append(v)
    return out


def _fmt(v: float | None) -> str:
    if v is None:
        return "-"
    if float(v).is_integer():
        return str(int(v))
    return f"{v:.2f}"


def render_frame(
    snap: FleetSnapshot, prev: FleetSnapshot | None = None
) -> str:
    """One dashboard frame as plain text (also the --once/--self-test
    output, so it stays pipe- and CI-friendly)."""
    lines = []
    up, total = snap.n_up, len(snap.targets)
    lines.append(
        f"areal_tpu fleet  |  targets {up}/{total} up  |  "
        + time.strftime("%H:%M:%S", time.localtime(snap.scraped_at))
    )
    lines.append("-" * 64)
    # tokens/s needs two frames: rate = d(generated)/dt
    toks = _merged_value(snap, "areal_decode_generated_tokens_total")
    if prev is not None and toks is not None:
        prev_toks = _merged_value(
            prev, "areal_decode_generated_tokens_total"
        )
        dt = snap.scraped_at - prev.scraped_at
        if prev_toks is not None and dt > 0:
            lines.append(f"{'tokens/s':<24} {(toks - prev_toks) / dt:>12.1f}")
    elif toks is not None:
        lines.append(f"{'tokens (total)':<24} {_fmt(toks):>12}")
    for name, label in _ROWS:
        v = _merged_value(snap, name)
        if v is not None:
            lines.append(f"{label:<24} {_fmt(v):>12}")
    # fleet-level prefix reuse: tokens served from radix-cached KV over all
    # prompt tokens admitted (cached + actually prefilled)
    hit_tok = _merged_value(snap, "areal_prefix_cache_hit_tokens_total")
    pf_tok = _merged_value(snap, "areal_decode_prefill_tokens_total")
    if hit_tok is not None and pf_tok is not None and (hit_tok + pf_tok) > 0:
        lines.append(
            f"{'prefix hit rate':<24} {hit_tok / (hit_tok + pf_tok):>11.1%}"
        )
    # routing brain (docs/serving.md "Cache-aware routing"): decision
    # totals by reason plus the predicted-vs-actual prefix-hit audit —
    # divergence means the shadow index drifted from the fleet's caches
    decisions = _merged_value(snap, "areal_router_decisions_total")
    if decisions is not None:
        lines.append(f"{'router decisions':<24} {_fmt(decisions):>12}")
        reasons = {}
        for (n, labels), v in snap.merged.items():
            if n == "areal_router_decisions_total":
                key = dict(labels).get("reason", "?")
                reasons[key] = reasons.get(key, 0.0) + v
        top = sorted(reasons.items(), key=lambda kv: -kv[1])[:4]
        for reason, v in top:
            lines.append(f"{'  ' + reason:<24} {_fmt(v):>12}")
        pred = _merged_value(snap, "areal_router_predicted_hit_total")
        act = _merged_value(snap, "areal_router_actual_hit_total")
        if pred is not None or act is not None:
            lines.append(
                f"{'router hit pred/actual':<24} "
                f"{_fmt(pred or 0):>6} / {_fmt(act or 0)}"
            )
    # goodput autopilot (docs/autopilot.md): current setpoints, decision
    # totals by reason, and each controller's last-action age — the
    # at-a-glance answer to "what is the control plane doing right now".
    # Setpoints and ages are per-control-plane FACTS, not additive: they
    # bypass the fleet merge-sum (two scrapes of one plane must not
    # double a setpoint; a mixed acted/never fleet must not average the
    # -1 sentinel into a bogus age).
    ap_decisions = _merged_value(snap, "areal_autopilot_decisions_total")
    if ap_decisions is not None:
        lines.append("-" * 64)
        lines.append(f"{'autopilot decisions':<24} {_fmt(ap_decisions):>12}")
        reasons: dict[str, float] = {}
        for (n, labels), v in snap.merged.items():
            if n == "areal_autopilot_decisions_total":
                key = dict(labels).get("reason", "?")
                reasons[key] = reasons.get(key, 0.0) + v
        for reason, v in sorted(reasons.items(), key=lambda kv: -kv[1])[:4]:
            lines.append(f"{'  ' + reason:<24} {_fmt(v):>12}")
        for knob, vs in sorted(
            _labeled_values(snap, "areal_autopilot_setpoint", "knob").items()
        ):
            lines.append(f"{'  set ' + knob:<28} {_fmt(max(vs)):>8}")
        for ctrl, vs in sorted(
            _labeled_values(
                snap, "areal_autopilot_last_action_age_seconds", "controller"
            ).items()
        ):
            nonneg = [v for v in vs if v >= 0]
            age = f"{min(nonneg):.0f}s ago" if nonneg else "never"
            lines.append(f"{'  ' + ctrl + ' acted':<24} {age:>12}")
    # gateway tier (docs/serving.md "Gateway tier"): ring fan-out plus the
    # shard-death story — degraded membership refreshes, affinity repairs
    # on survivors, misroutes, and per-shard session balance. The shard
    # count and per-shard session gauges are per-membership-view FACTS
    # (co-located shards share one registry, so every scrape of the tier
    # process reports the whole tier): take the max, never the merge-sum.
    shard_counts = [
        v
        for t in snap.targets
        if t.up
        for n, _labels, v in t.samples
        if n == "areal_gateway_shard_count"
    ]
    if shard_counts:
        lines.append("-" * 64)
        lines.append(f"{'gateway shards':<24} {_fmt(max(shard_counts)):>12}")
        for metric, label in (
            ("areal_gateway_shard_membership_stale_total", "  stale membership"),
            ("areal_gateway_shard_route_recoveries_total", "  route recoveries"),
            ("areal_gateway_shard_misroute_total", "  misroutes"),
            ("areal_gateway_shard_drain_total", "  drain transitions"),
        ):
            v = _merged_value(snap, metric)
            if v is not None:
                lines.append(f"{label:<24} {_fmt(v):>12}")
        for shard, vs in sorted(
            _labeled_values(
                snap, "areal_gateway_shard_sessions", "shard"
            ).items()
        ):
            lines.append(f"{'  sessions ' + shard:<24} {_fmt(max(vs)):>12}")
    # overload view (docs/request_lifecycle.md): everything turned away with
    # a 429 — gateway load shedding + engine admission rejections — as a
    # fleet total, and as a rate once two frames exist
    shed = _shed_total(snap)
    if shed is not None:
        lines.append(f"{'shed/rejected (429)':<24} {_fmt(shed):>12}")
        if prev is not None:
            prev_shed = _shed_total(prev)
            dt = snap.scraped_at - prev.scraped_at
            if prev_shed is not None and dt > 0:
                lines.append(
                    f"{'shed rate (429/s)':<24} {(shed - prev_shed) / dt:>12.1f}"
                )
    # request-timeline stage view (observability/timeline.py): TTFT/TPOT
    # tails from the catalogued stage histograms, fence-stall cost, and the
    # flight-recorder event cadence
    for metric, label in (
        ("areal_request_ttft_seconds", "ttft"),
        ("areal_request_tpot_seconds", "tpot"),
    ):
        p50 = _histogram_quantile(snap, metric, 0.50)
        p99 = _histogram_quantile(snap, metric, 0.99)
        if p50 is not None and p99 is not None:
            lines.append(
                f"{label + ' p50/p99 (s)':<24} {p50:>6.3f} / {p99:.3f}"
            )
    fence_sum = _merged_value(snap, "areal_request_fence_stall_seconds_sum")
    fence_cnt = _merged_value(snap, "areal_request_fence_stall_seconds_count")
    if fence_sum is not None and fence_cnt:
        lines.append(
            f"{'fence stall (mean s)':<24} {fence_sum / fence_cnt:>12.3f}"
        )
    flight = _flight_total(snap)
    if flight is not None:
        lines.append(f"{'flight events':<24} {_fmt(flight):>12}")
        if prev is not None:
            prev_flight = _flight_total(prev)
            dt = snap.scraped_at - prev.scraped_at
            if prev_flight is not None and dt > 0:
                lines.append(
                    f"{'flight events/s':<24} {(flight - prev_flight) / dt:>12.1f}"
                )
    pause_sum = _merged_value(snap, "areal_weight_update_pause_seconds_sum")
    pause_cnt = _merged_value(snap, "areal_weight_update_pause_seconds_count")
    if pause_sum is not None and pause_cnt:
        lines.append(
            f"{'update pause (mean s)':<24} {pause_sum / pause_cnt:>12.3f}"
        )
    # preemption tolerance (docs/fault_tolerance.md): drains survived,
    # drain cost, step-loop checkpoint pause by mode, and how much rollout
    # work the trajectory journal saved from re-generation
    preempts = _merged_value(snap, "areal_preemption_total")
    if preempts is not None:
        lines.append(f"{'preemptions':<24} {_fmt(preempts):>12}")
    drain_sum = _merged_value(snap, "areal_drain_seconds_sum")
    drain_cnt = _merged_value(snap, "areal_drain_seconds_count")
    if drain_sum is not None and drain_cnt:
        lines.append(
            f"{'drain (mean s)':<24} {drain_sum / drain_cnt:>12.2f}"
        )
    for mode in ("sync", "async"):
        s = _merged_value_labeled(
            snap, "areal_ckpt_save_seconds_sum", mode=mode
        )
        c = _merged_value_labeled(
            snap, "areal_ckpt_save_seconds_count", mode=mode
        )
        if s is not None and c:
            lines.append(
                f"{'ckpt pause ' + mode + ' (s)':<24} {s / c:>12.3f}"
            )
    replayed = _merged_value(snap, "areal_journal_replayed_total")
    dropped = _merged_value(snap, "areal_journal_dropped_stale_total")
    if replayed is not None or dropped is not None:
        lines.append(
            f"{'journal replay/stale':<24} "
            f"{_fmt(replayed or 0):>6} / {_fmt(dropped or 0)}"
        )
    # kernel observatory (docs/perf.md "Kernel observatory"): decode-step
    # phase means with the dominant phase highlighted, plus the fleet's
    # achieved-roofline fraction (mean across targets — a per-engine
    # fact like MFU, never fleet-summed)
    dphase_rows = []
    for ph in _DECODE_PHASES:
        s = _merged_value_labeled(
            snap, "areal_decode_phase_seconds_sum", phase=ph
        )
        c = _merged_value_labeled(
            snap, "areal_decode_phase_seconds_count", phase=ph
        )
        if s is not None and c:
            dphase_rows.append((ph, s / c))
    if dphase_rows:
        lines.append("-" * 64)
        lines.append("decode step phases (mean s)")
        dominant = max(dphase_rows, key=lambda kv: kv[1])[0]
        for ph, v in dphase_rows:
            label = "  " + ph + (" (dominant)" if ph == dominant else "")
            lines.append(f"{label:<24} {v:>12.6f}")
    roofline = _mean_per_target(snap, "areal_decode_roofline_fraction")
    if roofline is not None:
        lines.append(f"{'decode roofline frac':<24} {roofline:>11.1%}")
    # speculative decoding (docs/serving.md "Speculative decoding"):
    # acceptance economics — drafted vs accepted tokens, the per-round
    # accepted-length mean, and allocator-level rollback churn
    spec_rounds = _merged_value(snap, "areal_spec_rounds_total")
    if spec_rounds is not None:
        lines.append("-" * 64)
        lines.append(f"{'spec rounds':<24} {_fmt(spec_rounds):>12}")
        drafted = _merged_value(snap, "areal_spec_draft_tokens_total")
        accepted = _merged_value(snap, "areal_spec_accepted_tokens_total")
        if drafted is not None:
            lines.append(f"{'spec drafted tokens':<24} {_fmt(drafted):>12}")
            for src, vs in sorted(
                _labeled_values(
                    snap, "areal_spec_draft_tokens_total", "source"
                ).items()
            ):
                lines.append(f"{'  draft ' + src:<24} {_fmt(sum(vs)):>12}")
        if accepted is not None:
            lines.append(f"{'spec accepted tokens':<24} {_fmt(accepted):>12}")
        if drafted and accepted is not None:
            lines.append(
                f"{'spec acceptance rate':<24} {accepted / drafted:>11.1%}"
            )
        al_sum = _merged_value(snap, "areal_spec_accepted_length_sum")
        al_cnt = _merged_value(snap, "areal_spec_accepted_length_count")
        if al_sum is not None and al_cnt:
            lines.append(
                f"{'spec accepted len mean':<24} {al_sum / al_cnt:>12.2f}"
            )
        rb = _merged_value(snap, "areal_spec_rollback_pages_total")
        if rb is not None:
            lines.append(f"{'spec rollback pages':<24} {_fmt(rb):>12}")
    # trainer observatory (docs/observability.md "Trainer observatory"):
    # step-phase means with the async bubble highlighted, utilization,
    # worst-replica HBM headroom, and the recompile-storm counters
    phase_rows = []
    for ph in _TRAIN_PHASES:
        s = _merged_value_labeled(
            snap, "areal_train_phase_seconds_sum", phase=ph
        )
        c = _merged_value_labeled(
            snap, "areal_train_phase_seconds_count", phase=ph
        )
        if s is not None and c:
            phase_rows.append((ph, s / c))
    if phase_rows:
        lines.append("-" * 64)
        lines.append("trainer step phases (mean s)")
        for ph, v in phase_rows:
            label = "  " + ph + (" (bubble)" if ph == "rollout_wait" else "")
            lines.append(f"{label:<24} {v:>12.3f}")
    bub = _mean_per_target(snap, "areal_train_bubble_fraction")
    if bub is not None:
        lines.append(f"{'bubble fraction':<24} {bub:>11.1%}")
    mfu = _mean_per_target(snap, "areal_train_mfu")
    if mfu is not None:
        lines.append(f"{'mfu':<24} {mfu:>11.1%}")
    tok_chip = _mean_per_target(snap, "areal_train_tokens_per_sec_per_chip")
    if tok_chip is not None:
        lines.append(f"{'train tok/s/chip':<24} {tok_chip:>12.1f}")
    head = _min_per_target(snap, "areal_hbm_headroom_fraction")
    if head is not None:
        lines.append(f"{'hbm headroom (worst)':<24} {head:>11.1%}")
    compiles = _merged_value(snap, "areal_xla_compiles_total")
    if compiles is not None:
        lines.append(f"{'xla compiles':<24} {_fmt(compiles):>12}")
        cs = _merged_value(snap, "areal_xla_compile_seconds_sum")
        if cs is not None:
            lines.append(f"{'xla compile time (s)':<24} {cs:>12.1f}")
    # learning-health observatory (docs/observability.md): decoupled-PPO
    # loss diagnostics by version-lag bucket — clip fraction, behave |KL|,
    # cap-hit tail mass, token share — plus the lineage join counters.
    # Per-bucket gauges are per-trainer facts (mean across targets, like
    # bubble/MFU), never fleet-summed.
    share_by = _labeled_values(snap, "areal_train_lag_token_share", "lag_bucket")
    if share_by:
        clip_by = _labeled_values(snap, "areal_train_lag_clip_ratio", "lag_bucket")
        kl_by = _labeled_values(snap, "areal_train_lag_behave_kl", "lag_bucket")
        cap_by = _labeled_values(
            snap, "areal_train_lag_cap_hit_share", "lag_bucket"
        )

        def _bucket_mean(d: dict[str, list[float]], label: str) -> float:
            vs = d.get(label)
            return sum(vs) / len(vs) if vs else 0.0

        lines.append("-" * 64)
        lines.append("learning health by lag bucket (clip/|KL|/cap-hit/tok)")
        for label in _LAG_BUCKETS:
            if label not in share_by:
                continue
            lines.append(
                f"{'  lag ' + label:<10}"
                f" clip {_bucket_mean(clip_by, label):>6.1%}"
                f"  |KL| {_bucket_mean(kl_by, label):>8.4f}"
                f"  cap {_bucket_mean(cap_by, label):>6.1%}"
                f"  tok {_bucket_mean(share_by, label):>6.1%}"
            )
        regd = _merged_value(snap, "areal_lineage_records_total")
        joined = _merged_value(snap, "areal_lineage_joined_total")
        if regd is not None:
            lines.append(
                f"{'lineage joined/records':<24} "
                f"{_fmt(joined or 0):>6} / {_fmt(regd)}"
            )
    # straggler view: per-target token counters expose a lagging server
    # that the fleet-merged sums hide
    per = snap.per_target("areal_decode_generated_tokens_total")
    if len(per) > 1:
        lines.append("-" * 64)
        for target, v in sorted(per.items(), key=lambda kv: kv[1]):
            lines.append(f"  {target:<22} {_fmt(v):>12} tok")
    down = [t.target for t in snap.targets if not t.up]
    if down:
        lines.append("-" * 64)
        for t in down:
            lines.append(f"DOWN  {t}")
    return "\n".join(lines)


def run_dashboard(
    targets: list[str],
    refresh: float = 2.0,
    once: bool = False,
    timeout: float = 2.0,
) -> int:
    agg = FleetAggregator(targets, timeout=timeout)
    prev = None
    while True:
        snap = agg.scrape_once()
        frame = render_frame(snap, prev)
        if once:
            print(frame)
            return 0 if snap.n_up == len(targets) else 1
        # clear + home, then the frame (plain ANSI, no curses dependency)
        sys.stdout.write("\x1b[2J\x1b[H" + frame + "\n")
        sys.stdout.flush()
        prev = snap
        time.sleep(refresh)


# ---------------------------------------------------------------------------
# --self-test: CI smoke over a fake scrape target
# ---------------------------------------------------------------------------

_FAKE_EXPOSITION = """\
# HELP areal_rollout_capacity Remaining rollout admission capacity.
# TYPE areal_rollout_capacity gauge
areal_rollout_capacity 7
# HELP areal_executor_input_queue_depth Queued train rollout tasks.
# TYPE areal_executor_input_queue_depth gauge
areal_executor_input_queue_depth 3
# HELP areal_decode_generated_tokens_total Tokens emitted by the decode loop.
# TYPE areal_decode_generated_tokens_total counter
areal_decode_generated_tokens_total 1234
# HELP areal_server_paused 1 while generation is paused.
# TYPE areal_server_paused gauge
areal_server_paused 0
# HELP areal_prefix_cache_hit_tokens_total Tokens served from cached KV.
# TYPE areal_prefix_cache_hit_tokens_total counter
areal_prefix_cache_hit_tokens_total 800
# HELP areal_decode_prefill_tokens_total Prompt tokens prefilled.
# TYPE areal_decode_prefill_tokens_total counter
areal_decode_prefill_tokens_total 200
# HELP areal_request_queue_depth Engine admission queue + backlog depth.
# TYPE areal_request_queue_depth gauge
areal_request_queue_depth 2
# HELP areal_gateway_shed_total Requests load-shed at the gateway.
# TYPE areal_gateway_shed_total counter
areal_gateway_shed_total{priority="rollout"} 5
areal_gateway_shed_total{priority="interactive"} 1
# HELP areal_router_decisions_total Replica-selection decisions by reason.
# TYPE areal_router_decisions_total counter
areal_router_decisions_total{reason="prefix_overlap"} 6
areal_router_decisions_total{reason="least_loaded"} 3
areal_router_decisions_total{reason="stale_snapshots"} 1
# HELP areal_router_predicted_hit_total Decisions predicting a warm prefix.
# TYPE areal_router_predicted_hit_total counter
areal_router_predicted_hit_total 6
# HELP areal_router_actual_hit_total Routed requests with a real radix hit.
# TYPE areal_router_actual_hit_total counter
areal_router_actual_hit_total 5
# HELP areal_admission_rejected_total Requests rejected at engine admission.
# TYPE areal_admission_rejected_total counter
areal_admission_rejected_total{reason="queue_depth"} 4
# HELP areal_gateway_shard_count Live gateway shards in the membership view.
# TYPE areal_gateway_shard_count gauge
areal_gateway_shard_count 3
# HELP areal_gateway_shard_membership_stale_total Failed membership refreshes served on the last-known view.
# TYPE areal_gateway_shard_membership_stale_total counter
areal_gateway_shard_membership_stale_total 2
# HELP areal_gateway_shard_route_recoveries_total Sessions adopted by a surviving shard.
# TYPE areal_gateway_shard_route_recoveries_total counter
areal_gateway_shard_route_recoveries_total 4
# HELP areal_gateway_shard_misroute_total Requests landing on an unexpected shard.
# TYPE areal_gateway_shard_misroute_total counter
areal_gateway_shard_misroute_total 1
# HELP areal_gateway_shard_sessions Active session routes per gateway shard.
# TYPE areal_gateway_shard_sessions gauge
areal_gateway_shard_sessions{shard="gw0"} 5
areal_gateway_shard_sessions{shard="gw1"} 3
# HELP areal_autopilot_decisions_total Autopilot setpoint changes applied.
# TYPE areal_autopilot_decisions_total counter
areal_autopilot_decisions_total{controller="admission",reason="queue_wait_high"} 3
areal_autopilot_decisions_total{controller="fleet",reason="sustained_idle"} 1
# HELP areal_autopilot_setpoint Current autopilot-managed setpoint by knob.
# TYPE areal_autopilot_setpoint gauge
areal_autopilot_setpoint{knob="max_queue_depth"} 16
# HELP areal_autopilot_last_action_age_seconds Seconds since each controller acted.
# TYPE areal_autopilot_last_action_age_seconds gauge
areal_autopilot_last_action_age_seconds{controller="admission"} 12
areal_autopilot_last_action_age_seconds{controller="cache"} -1
# HELP areal_weight_update_pause_seconds Availability gap per update.
# TYPE areal_weight_update_pause_seconds histogram
areal_weight_update_pause_seconds_bucket{le="1"} 2
areal_weight_update_pause_seconds_bucket{le="+Inf"} 2
areal_weight_update_pause_seconds_sum 1.5
areal_weight_update_pause_seconds_count 2
# HELP areal_request_ttft_seconds Engine-side time to first token.
# TYPE areal_request_ttft_seconds histogram
areal_request_ttft_seconds_bucket{priority="interactive",le="0.05"} 8
areal_request_ttft_seconds_bucket{priority="interactive",le="0.1"} 10
areal_request_ttft_seconds_bucket{priority="interactive",le="+Inf"} 10
areal_request_ttft_seconds_sum{priority="interactive"} 0.5
areal_request_ttft_seconds_count{priority="interactive"} 10
# HELP areal_request_tpot_seconds Time per output token after the first.
# TYPE areal_request_tpot_seconds histogram
areal_request_tpot_seconds_bucket{le="0.005"} 90
areal_request_tpot_seconds_bucket{le="0.01"} 100
areal_request_tpot_seconds_bucket{le="+Inf"} 100
areal_request_tpot_seconds_sum 0.4
areal_request_tpot_seconds_count 100
# HELP areal_request_fence_stall_seconds Fence stall per request.
# TYPE areal_request_fence_stall_seconds histogram
areal_request_fence_stall_seconds_bucket{le="0.1"} 4
areal_request_fence_stall_seconds_bucket{le="+Inf"} 4
areal_request_fence_stall_seconds_sum 0.2
areal_request_fence_stall_seconds_count 4
# HELP areal_flight_events_total Flight-recorder events by kind.
# TYPE areal_flight_events_total counter
areal_flight_events_total{kind="admission_reject"} 3
areal_flight_events_total{kind="weight_commit"} 2
# HELP areal_preemption_total Preemption signals honored, by role.
# TYPE areal_preemption_total counter
areal_preemption_total{role="trainer"} 1
areal_preemption_total{role="inference_server"} 2
# HELP areal_drain_seconds Graceful-drain duration.
# TYPE areal_drain_seconds histogram
areal_drain_seconds_bucket{le="5"} 3
areal_drain_seconds_bucket{le="+Inf"} 3
areal_drain_seconds_sum 6.0
areal_drain_seconds_count 3
# HELP areal_ckpt_save_seconds Step-loop pause per checkpoint save, by mode.
# TYPE areal_ckpt_save_seconds histogram
areal_ckpt_save_seconds_bucket{mode="sync",le="+Inf"} 2
areal_ckpt_save_seconds_sum{mode="sync"} 5.0
areal_ckpt_save_seconds_count{mode="sync"} 2
areal_ckpt_save_seconds_bucket{mode="async",le="+Inf"} 4
areal_ckpt_save_seconds_sum{mode="async"} 0.4
areal_ckpt_save_seconds_count{mode="async"} 4
# HELP areal_journal_replayed_total Journaled trajectories replayed on recovery.
# TYPE areal_journal_replayed_total counter
areal_journal_replayed_total 7
# HELP areal_journal_dropped_stale_total Journaled trajectories dropped over-stale.
# TYPE areal_journal_dropped_stale_total counter
areal_journal_dropped_stale_total 1
# HELP areal_decode_phase_seconds Wall-clock seconds per decode-step phase.
# TYPE areal_decode_phase_seconds histogram
areal_decode_phase_seconds_bucket{phase="dispatch",le="+Inf"} 10
areal_decode_phase_seconds_sum{phase="dispatch"} 0.5
areal_decode_phase_seconds_count{phase="dispatch"} 10
areal_decode_phase_seconds_bucket{phase="device_wait",le="+Inf"} 10
areal_decode_phase_seconds_sum{phase="device_wait"} 0.2
areal_decode_phase_seconds_count{phase="device_wait"} 10
# HELP areal_decode_roofline_fraction Achieved fraction of the roofline ceiling.
# TYPE areal_decode_roofline_fraction gauge
areal_decode_roofline_fraction 0.42
# HELP areal_spec_rounds_total Speculative draft/verify rounds executed.
# TYPE areal_spec_rounds_total counter
areal_spec_rounds_total 50
# HELP areal_spec_draft_tokens_total Draft tokens proposed, by source.
# TYPE areal_spec_draft_tokens_total counter
areal_spec_draft_tokens_total{source="ngram"} 150
areal_spec_draft_tokens_total{source="radix"} 50
# HELP areal_spec_accepted_tokens_total Draft tokens accepted by the verifier.
# TYPE areal_spec_accepted_tokens_total counter
areal_spec_accepted_tokens_total 120
# HELP areal_spec_accepted_length Accepted draft-prefix length per slot-round.
# TYPE areal_spec_accepted_length histogram
areal_spec_accepted_length_bucket{le="+Inf"} 60
areal_spec_accepted_length_sum 120
areal_spec_accepted_length_count 60
# HELP areal_spec_rollback_pages_total KV pages rolled back after rejection.
# TYPE areal_spec_rollback_pages_total counter
areal_spec_rollback_pages_total 9
# HELP areal_train_phase_seconds Wall-clock seconds per training-step phase.
# TYPE areal_train_phase_seconds histogram
areal_train_phase_seconds_bucket{phase="rollout_wait",le="+Inf"} 4
areal_train_phase_seconds_sum{phase="rollout_wait"} 6.0
areal_train_phase_seconds_count{phase="rollout_wait"} 4
areal_train_phase_seconds_bucket{phase="forward_backward",le="+Inf"} 4
areal_train_phase_seconds_sum{phase="forward_backward"} 2.0
areal_train_phase_seconds_count{phase="forward_backward"} 4
# HELP areal_train_bubble_fraction rollout_wait / step wall time.
# TYPE areal_train_bubble_fraction gauge
areal_train_bubble_fraction 0.6
# HELP areal_train_mfu Model FLOPs utilization over the compute window.
# TYPE areal_train_mfu gauge
areal_train_mfu 0.35
# HELP areal_train_tokens_per_sec_per_chip Trained tokens/s per chip.
# TYPE areal_train_tokens_per_sec_per_chip gauge
areal_train_tokens_per_sec_per_chip 5200
# HELP areal_hbm_headroom_fraction Free fraction of device memory.
# TYPE areal_hbm_headroom_fraction gauge
areal_hbm_headroom_fraction 0.25
# HELP areal_xla_compiles_total XLA backend compilations.
# TYPE areal_xla_compiles_total counter
areal_xla_compiles_total 12
# HELP areal_xla_compile_seconds Per-compilation backend compile time.
# TYPE areal_xla_compile_seconds histogram
areal_xla_compile_seconds_bucket{le="+Inf"} 12
areal_xla_compile_seconds_sum 30.0
areal_xla_compile_seconds_count 12
# HELP areal_train_lag_token_share Bucket share of last update's tokens.
# TYPE areal_train_lag_token_share gauge
areal_train_lag_token_share{lag_bucket="0"} 0.5
areal_train_lag_token_share{lag_bucket="4+"} 0.25
# HELP areal_train_lag_clip_ratio Clip fraction by version-lag bucket.
# TYPE areal_train_lag_clip_ratio gauge
areal_train_lag_clip_ratio{lag_bucket="0"} 0.05
areal_train_lag_clip_ratio{lag_bucket="4+"} 0.85
# HELP areal_train_lag_behave_kl Mean behave |KL| by version-lag bucket.
# TYPE areal_train_lag_behave_kl gauge
areal_train_lag_behave_kl{lag_bucket="0"} 0.01
areal_train_lag_behave_kl{lag_bucket="4+"} 0.62
# HELP areal_train_lag_cap_hit_share Cap-hit tail mass by lag bucket.
# TYPE areal_train_lag_cap_hit_share gauge
areal_train_lag_cap_hit_share{lag_bucket="0"} 0.0
areal_train_lag_cap_hit_share{lag_bucket="4+"} 0.2
# HELP areal_lineage_records_total Trajectory lineage records registered.
# TYPE areal_lineage_records_total counter
areal_lineage_records_total 9
# HELP areal_lineage_joined_total Lineage records joined to step stats.
# TYPE areal_lineage_joined_total counter
areal_lineage_joined_total 6
"""


def self_test() -> int:
    """End-to-end smoke: fake target -> scrape -> merge -> render."""
    import threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 — http.server API
            body = _FAKE_EXPOSITION.encode()
            self.send_response(200 if self.path == "/metrics" else 404)
            self.send_header("Content-Type", "text/plain; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            if self.path == "/metrics":
                self.wfile.write(body)

        def log_message(self, *a):
            pass

    srv = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    target = f"127.0.0.1:{srv.server_address[1]}"
    try:
        # two live targets sharing one backend: merge must sum them, and a
        # third dead target must not stall or fail the round
        agg = FleetAggregator(
            [target, target, "127.0.0.1:1"], timeout=2.0, retries=0
        )
        t0 = time.monotonic()
        snap = agg.scrape_once()
        elapsed = time.monotonic() - t0
        frame = render_frame(snap)
        checks = [
            (snap.n_up == 2, f"expected 2 targets up, got {snap.n_up}"),
            (
                _merged_value(snap, "areal_rollout_capacity") == 14,
                "gauge merge: capacity should sum to 14",
            ),
            (
                _merged_value(snap, "areal_decode_generated_tokens_total")
                == 2468,
                "counter merge: tokens should sum to 2468",
            ),
            (
                elapsed < 10.0,
                f"dead target stalled the round ({elapsed:.1f}s)",
            ),
            ("staleness capacity" in frame, "frame missing capacity row"),
            (
                "prefix hit rate" in frame and "80.0%" in frame,
                "frame missing prefix hit-rate row (800/(800+200) per "
                "target merges to the same 80% ratio)",
            ),
            ("update pause (mean s)" in frame, "frame missing pause row"),
            (
                "decode step phases (mean s)" in frame,
                "frame missing decode phase panel",
            ),
            (
                "dispatch (dominant)" in frame,
                "dispatch (0.05 mean) should be highlighted as the "
                "dominant decode phase over device_wait (0.02)",
            ),
            (
                "decode roofline frac" in frame and "42.0%" in frame,
                "frame missing fleet roofline row (0.42 per target means "
                "to 42.0%)",
            ),
            (
                "ttft p50/p99 (s)" in frame,
                "frame missing timeline ttft quantile row",
            ),
            (
                "tpot p50/p99 (s)" in frame,
                "frame missing timeline tpot quantile row",
            ),
            (
                abs(
                    (
                        _histogram_quantile(
                            snap, "areal_request_ttft_seconds", 0.5
                        )
                        or 0.0
                    )
                    - 0.03125
                )
                < 1e-9,
                "ttft p50 should interpolate to 0.03125 (target 10 of 16 "
                "in the 0.05 bucket)",
            ),
            (
                "fence stall (mean s)" in frame and "0.050" in frame,
                "frame missing fence-stall row (0.2/4 = 0.050)",
            ),
            (
                "flight events" in frame
                and _flight_total(snap) == 10,
                "flight events should sum kinds across targets (2x(3+2))",
            ),
            (
                "lifecycle queue" in frame,
                "frame missing lifecycle queue-depth row",
            ),
            (
                "router decisions" in frame
                and _merged_value(snap, "areal_router_decisions_total")
                == 20,
                "router decisions should sum reasons across targets "
                "(2x(6+3+1))",
            ),
            (
                "prefix_overlap" in frame,
                "frame missing top decision-reason rows",
            ),
            (
                "router hit pred/actual" in frame and "12 / 10" in frame,
                "frame missing predicted-vs-actual router hit row "
                "(2x6 / 2x5)",
            ),
            (
                _shed_total(snap) == 20,
                "shed total: gateway (5+1) + admission (4) per target "
                "should merge to 20",
            ),
            (
                "autopilot decisions" in frame
                and _merged_value(snap, "areal_autopilot_decisions_total")
                == 8,
                "autopilot decisions should sum controller/reason children "
                "across targets (2x(3+1))",
            ),
            (
                "queue_wait_high" in frame,
                "frame missing autopilot decision-reason rows",
            ),
            (
                "set max_queue_depth" in frame and "16" in frame,
                "frame missing autopilot setpoint row (a per-plane fact: "
                "16, never the 32 a fleet merge-sum would claim)",
            ),
            (
                "admission acted" in frame and "12s ago" in frame,
                "frame missing per-controller last-action age row (12s "
                "per target must stay 12s, not merge-sum to 24)",
            ),
            (
                "cache acted" in frame and "never" in frame,
                "a controller that never acted must read 'never', not a "
                "negative age",
            ),
            (
                "shed/rejected (429)" in frame and "20" in frame,
                "frame missing shed/rejected row",
            ),
            (
                "gateway shards" in frame and "3" in frame,
                "frame missing gateway-tier panel (shard count is a "
                "membership FACT: 3 per scrape must stay 3, never the 6 "
                "a fleet merge-sum would claim)",
            ),
            (
                "route recoveries" in frame
                and _merged_value(
                    snap, "areal_gateway_shard_route_recoveries_total"
                )
                == 8,
                "frame missing affinity-repair row (counters are "
                "additive: 2x4 = 8)",
            ),
            (
                "stale membership" in frame,
                "frame missing degraded-discovery row",
            ),
            (
                "sessions gw0" in frame and "sessions gw1" in frame,
                "frame missing per-shard session balance rows (gauge "
                "children keyed by shard, max across scrapes)",
            ),
            (
                "preemptions" in frame
                and _merged_value(snap, "areal_preemption_total") == 6,
                "preemption total should sum roles across targets (2x(1+2))",
            ),
            (
                "drain (mean s)" in frame and "2.00" in frame,
                "frame missing drain row (6.0/3 = 2.00 mean)",
            ),
            (
                "ckpt pause sync (s)" in frame
                and "ckpt pause async (s)" in frame
                and "2.500" in frame
                and "0.100" in frame,
                "frame missing per-mode ckpt pause rows (sync 5.0/2, "
                "async 0.4/4)",
            ),
            (
                "journal replay/stale" in frame and "14 / 2" in frame,
                "frame missing journal replay row (2x7 / 2x1)",
            ),
            (
                "trainer step phases (mean s)" in frame
                and "rollout_wait (bubble)" in frame
                and "1.500" in frame,
                "frame missing trainer phase rows (rollout_wait mean "
                "6.0/4 = 1.500, merged across targets)",
            ),
            (
                "bubble fraction" in frame and "60.0%" in frame,
                "frame missing bubble-fraction row (per-target MEAN of "
                "0.6, not the 1.2 a fleet sum would claim)",
            ),
            (
                "mfu" in frame and "35.0%" in frame,
                "frame missing mfu row (per-target mean of 0.35)",
            ),
            (
                "train tok/s/chip" in frame and "5200.0" in frame,
                "frame missing train tok/s/chip row",
            ),
            (
                "hbm headroom (worst)" in frame and "25.0%" in frame,
                "frame missing hbm-headroom row (worst replica, 0.25)",
            ),
            (
                "xla compiles" in frame
                and _merged_value(snap, "areal_xla_compiles_total") == 24,
                "frame missing compile-count row (12 per target sums to 24)",
            ),
            (
                "xla compile time (s)" in frame and "60.0" in frame,
                "frame missing compile-time row (30.0s per target sums "
                "to 60.0)",
            ),
            (
                "spec rounds" in frame
                and _merged_value(snap, "areal_spec_rounds_total") == 100,
                "frame missing speculation panel (50 rounds per target "
                "sums to 100)",
            ),
            (
                "draft ngram" in frame and "draft radix" in frame,
                "frame missing per-source draft-token rows",
            ),
            (
                "spec acceptance rate" in frame and "60.0%" in frame,
                "frame missing acceptance-rate row (120 accepted / 200 "
                "drafted = 60.0%, ratio survives the fleet merge)",
            ),
            (
                "spec accepted len mean" in frame and "2.00" in frame,
                "frame missing accepted-length row (120/60 = 2.00)",
            ),
            (
                "spec rollback pages" in frame
                and _merged_value(snap, "areal_spec_rollback_pages_total")
                == 18,
                "frame missing rollback-pages row (counters sum: 2x9)",
            ),
            (
                "learning health by lag bucket" in frame
                and "lag 4+" in frame
                and "0.6200" in frame,
                "frame missing learning-health panel (per-target mean "
                "behave |KL| 0.62 in the 4+ bucket)",
            ),
            (
                "lineage joined/records" in frame and "12 / 18" in frame,
                "frame missing lineage join row (counters sum: 2x6 / 2x9)",
            ),
            ("DOWN  127.0.0.1:1" in frame, "frame missing down-target row"),
        ]
        failed = [msg for ok, msg in checks if not ok]
        print(frame)
        print("-" * 64)
        for ok, msg in checks:
            print(f"{'PASS' if ok else 'FAIL'}  {msg}")
        if failed:
            return 1
        print("self-test OK")
        return 0
    finally:
        srv.shutdown()
        srv.server_close()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument(
        "--targets",
        default="",
        help="comma-separated host:port /metrics endpoints",
    )
    p.add_argument(
        "--refresh", type=float, default=2.0, help="redraw period (s)"
    )
    p.add_argument(
        "--timeout", type=float, default=2.0, help="per-target scrape timeout"
    )
    p.add_argument(
        "--once", action="store_true", help="render one frame and exit"
    )
    p.add_argument(
        "--self-test",
        action="store_true",
        help="run against a built-in fake target (CI smoke)",
    )
    args = p.parse_args(argv)
    if args.self_test:
        return self_test()
    targets = [t for t in args.targets.split(",") if t]
    if not targets:
        p.error("--targets required (or --self-test)")
    return run_dashboard(
        targets, refresh=args.refresh, once=args.once, timeout=args.timeout
    )


if __name__ == "__main__":
    raise SystemExit(main())
