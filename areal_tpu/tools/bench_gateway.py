"""Many-client open-loop gateway goodput benchmark.

The standing scoreboard for ROADMAP item 3 (disaggregated, cache-aware
serving fleet): drive the OpenAI-compatible gateway with mixed
interactive/rollout priority traffic on per-request deadlines, at an
OPEN-LOOP arrival schedule (clients arrive on a clock, not when the
previous one finishes — so overload shows up as queueing/shedding, not as
a slower client), and report per class:

- p50/p99 TTFT (from the ``areal_timing`` extension the proxy stamps onto
  completions — the engine-side request-timeline breakdown)
- p50/p99 end-to-end latency
- goodput: tokens completed WITHIN deadline per second
- shed/429, deadline-reap, and error counts

as a JSON artifact (``--output``), so router changes (prefix-locality
routing, prefill/decode disaggregation) have a fixed number to move.

Usage:
    # self-contained local fleet (tiny model, CPU-safe) under chaos stalls:
    python -m areal_tpu.tools.bench_gateway --local --replicas 2 \
        --interactive 8 --rollout 8 --duration 20 -o report.json
    # against an existing gateway:
    python -m areal_tpu.tools.bench_gateway --gateway http://host:port \
        --admin-key KEY --interactive 64 --rollout 64 --duration 60
    # routing A/B (ROADMAP item 3): round_robin vs cache_aware on an
    # 80%-shared-prefix multi-turn-style workload, one report:
    python -m areal_tpu.tools.bench_gateway --ab --replicas 3 \
        --workload shared_prefix --duration 15 -o ab.json
    # gateway tier (ROADMAP item 8): 3 consistent-hash shards, one
    # hard-killed 2s into the measured window:
    python -m areal_tpu.tools.bench_gateway --local --gateways 3 \
        --kill-shard-at 2 -o tier.json
    # the tier acceptance A/B (1 vs 3 shards + kill twin, one report):
    python -m areal_tpu.tools.bench_gateway --tier-ab -o tier_ab.json
"""

from __future__ import annotations

import argparse
import asyncio
import json
import math
import time
from dataclasses import dataclass, field
from typing import Any

# the self-contained local fleet serves the toy char tokenizer — the bench
# measures serving latency, not tokenization; real deployments pass
# --gateway at a fleet whose proxies run the production tokenizer
from areal_tpu.api import wire
from areal_tpu.infra.rpc.echo_engine import CharTokenizer  # noqa: F401
from areal_tpu.utils import logging as alog

logger = alog.getLogger("bench_gateway")

PRIORITIES = ("interactive", "rollout")

# time-varying open-loop arrival profiles: (fraction_of_duration,
# relative_rate) segments. "step" doubles down mid-run, "diurnal" ramps
# up and back (the traffic shape the fleet autoscaler tracks), "burst"
# is a calm fleet hit by a 6x spike — the shape a static admission
# config must lose on somewhere (shed the calm or drown in the spike).
LOAD_PROFILES: dict[str, list[tuple[float, float]]] = {
    "step": [(0.5, 1.0), (0.5, 3.0)],
    # a real night: the trough runs at ~5% of the peak rate, so a
    # load-following fleet has genuine idle capacity to return
    "diurnal": [(0.3, 0.25), (0.25, 2.0), (0.25, 5.0), (0.2, 1.0)],
    "burst": [(0.4, 1.0), (0.2, 6.0), (0.4, 1.0)],
}


def profile_arrivals(
    n: int, duration_s: float, segments: list[tuple[float, float]]
) -> list[float]:
    """Client arrival offsets in [0, duration_s) following the piecewise-
    constant relative rate (inverse CDF of the integrated rate, midpoint
    rule — n clients land exactly where the profile says the traffic
    is). A uniform profile reproduces the legacy even spread."""
    total = sum(f * w for f, w in segments) or 1.0
    out: list[float] = []
    for i in range(n):
        u = (i + 0.5) / max(1, n) * total
        t, start, cum = 1.0, 0.0, 0.0
        for f, w in segments:
            seg = f * w
            if seg > 0 and cum + seg >= u:
                t = start + (u - cum) / w
                break
            start += f
            cum += seg
        out.append(min(duration_s, t * duration_s))
    return out


def resolve_load_profile(
    profile: str | list | None,
) -> list[tuple[float, float]] | None:
    if profile is None:
        return None
    if isinstance(profile, str):
        if profile in ("", "uniform"):
            return None
        return LOAD_PROFILES[profile]
    return [(float(f), float(w)) for f, w in profile]


def make_shared_prefix_prompts(
    n: int,
    shared_frac: float = 0.8,
    total_chars: int = 400,
    seed: int = 11,
) -> list[str]:
    """The router scoreboard's workload: ``n`` prompts sharing the first
    ``shared_frac`` of their characters (the CharTokenizer maps one char
    to one token, so this IS an 80%-shared token prefix) with unique
    suffixes — the multi-turn-agent shape where prefix-locality routing
    pays: replicas that already hold the shared prefix's KV pages prefill
    only the suffix."""
    import random as _random
    import string

    rng = _random.Random(seed)
    alphabet = string.ascii_lowercase + " "
    shared_len = max(0, min(total_chars, int(total_chars * shared_frac)))
    shared = "".join(rng.choice(alphabet) for _ in range(shared_len))
    out = []
    for _ in range(n):
        sfx = "".join(
            rng.choice(alphabet) for _ in range(total_chars - shared_len)
        )
        out.append(shared + sfx)
    return out


def _percentile(values: list[float], q: float) -> float | None:
    if not values:
        return None
    xs = sorted(values)
    idx = min(len(xs) - 1, max(0, math.ceil(q * len(xs)) - 1))
    return xs[idx]


@dataclass
class _ClassStats:
    sent: int = 0
    completed: int = 0
    # shed_429 counts 429 RESPONSES (a retrying client can collect several
    # before admission and shed_429 may exceed sent); shed_requests counts
    # requests that were shed at least once — the router-comparison ratio
    shed_429: int = 0
    shed_requests: int = 0
    deadline_reaped: int = 0
    errors: int = 0
    ttft_s: list[float] = field(default_factory=list)
    e2e_s: list[float] = field(default_factory=list)
    tokens: int = 0
    tokens_within_deadline: int = 0

    def report(self, duration_s: float) -> dict[str, Any]:
        return {
            "sent": self.sent,
            "completed": self.completed,
            "shed_429": self.shed_429,
            "shed_requests": self.shed_requests,
            "deadline_reaped": self.deadline_reaped,
            "errors": self.errors,
            "ttft_p50_s": _percentile(self.ttft_s, 0.50),
            "ttft_p99_s": _percentile(self.ttft_s, 0.99),
            "e2e_p50_s": _percentile(self.e2e_s, 0.50),
            "e2e_p99_s": _percentile(self.e2e_s, 0.99),
            "tokens": self.tokens,
            "tokens_within_deadline": self.tokens_within_deadline,
            "goodput_tok_s": (
                self.tokens_within_deadline / duration_s if duration_s > 0 else 0.0
            ),
        }


class _TierResolver:
    """Session-key -> gateway-shard placement for the tier bench.

    Wraps :class:`~areal_tpu.openai.proxy.tier.TierClient` (the ring +
    circuit machinery every tier client threads through) and keeps the
    per-shard goodput scoreboard: each client attributes its
    within-deadline tokens to the shard that served them (the
    ``x-areal-gateway-shard`` response header), so the artifact shows
    load re-hashing onto survivors after a kill."""

    def __init__(self, tier):
        self.tier = tier
        self._client = tier.client()
        self.shard_tokens: dict[str, int] = {}
        self.failovers = 0

    def pick(self, session_key: str, exclude: tuple[str, ...] = ()):
        return self._client.pick(session_key, exclude)

    def note_failure(self, addr: str) -> None:
        self.failovers += 1
        self._client.note_failure(addr)

    def note_success(self, addr: str) -> None:
        self._client.note_success(addr)

    def note_tokens(self, shard_id: str, n: int) -> None:
        if shard_id:
            self.shard_tokens[shard_id] = self.shard_tokens.get(shard_id, 0) + n

    def report(self, duration_s: float) -> dict[str, Any]:
        return {
            "per_shard_goodput_tok_s": {
                sid: (tok / duration_s if duration_s > 0 else 0.0)
                for sid, tok in sorted(self.shard_tokens.items())
            },
            "failovers": self.failovers,
        }


async def _one_client(
    http,
    gateway_url: str,
    admin_key: str,
    priority: str,
    deadline_s: float,
    max_completion_tokens: int,
    prompt: str,
    stats: _ClassStats,
    turns: int = 1,
    greedy: bool = False,
    resolver: _TierResolver | None = None,
    client_id: int = 0,
) -> None:
    """One open-loop client: session -> ``turns`` sequential prioritized
    chat completions -> end session, honoring 429 Retry-After inside the
    deadline budget. With ``turns > 1`` this is a multi-turn episode: each
    turn appends the assistant's reply plus a follow-up message, so turn
    t's prompt extends turn t-1's — the conversation-history locality
    that prefix-aware routing exploits (and round-robin re-prefills on a
    cold replica ~(N-1)/N of the time).
    With a ``resolver`` (the tier bench) the session hashes to ONE gateway
    shard for its whole lifetime; a connection-refused shard (killed
    mid-run) is reported into the circuit machinery and the request
    re-hashes to the ring successor, where route adoption resumes the
    session — the request must never end responseless.
    The session ends on EVERY exit path: an abandoned session burns one of
    the proxy's capacity units forever, and a bench that leaks capacity
    under sustained overload corrupts its own scoreboard (start_session
    eventually 429s and every later client counts as an error)."""
    import aiohttp

    stats.sent += 1
    t0 = time.monotonic()
    budget_end = t0 + deadline_s
    key = None
    session_key = f"bench-{priority}-{client_id}"
    pick = resolver.pick(session_key) if resolver is not None else None
    shard_tokens: dict[str, int] = {}

    async def post(path: str, body: dict, headers: dict):
        """POST returning (status, headers, json-or-None). Without a
        resolver this is a single attempt against ``gateway_url`` — the
        pre-tier behavior, byte for byte. With one, a refused connection
        re-picks past the dead shard and retries (bounded)."""
        nonlocal pick
        tried: list[str] = []
        for _ in range(4):
            if pick is not None:
                base = pick.url
                headers = dict(headers)
                headers[wire.GATEWAY_EXPECT_SHARD_HEADER] = pick.shard_id
            else:
                base = gateway_url
            try:
                async with http.post(
                    f"{base}{path}", json=body, headers=headers
                ) as r:
                    payload = (
                        await r.json(content_type=None)
                        if r.status == 200
                        else None
                    )
                    if pick is not None:
                        resolver.note_success(pick.addr)
                    return r.status, r.headers, payload
            except (aiohttp.ClientConnectionError, OSError):
                if pick is None:
                    raise
                resolver.note_failure(pick.addr)
                tried.append(pick.addr)
                pick = resolver.pick(session_key, tuple(tried))
                if pick is None:
                    break
        raise ConnectionError("no reachable gateway shard")

    try:
        status, _hd, sess = await post(
            "/rl/start_session",
            {"task_id": f"bench-{priority}"},
            {"Authorization": f"Bearer {admin_key}"},
        )
        if status != 200:
            stats.errors += 1
            return
        key = sess["api_key"]
        headers = {
            "Authorization": f"Bearer {key}",
            wire.PRIORITY_HEADER: priority,
            wire.DEADLINE_HEADER: f"{time.time() + (budget_end - time.monotonic()):.6f}",
        }
        messages = [{"role": "user", "content": prompt}]
        was_shed = False
        session_tokens = 0
        reaped = False
        for turn in range(max(1, turns)):
            body = {
                "messages": messages,
                "max_completion_tokens": max_completion_tokens,
                "model": "bench",
            }
            if greedy:
                # deterministic decode lengths: an A/B comparing CONTROL
                # policies must not let sampling-dependent EOS timing
                # masquerade as a goodput difference between arms
                body["temperature"] = 0
            comp = None
            served_by = ""
            while True:
                status, hd, comp = await post(
                    "/v1/chat/completions", body, headers
                )
                if status == 429:
                    stats.shed_429 += 1
                    if not was_shed:
                        was_shed = True
                        stats.shed_requests += 1
                    # floor: a foreign gateway's "Retry-After: 0" must
                    # not hot-spin the bench into amplifying the
                    # overload; the RFC 7231 HTTP-date form falls back
                    # to the default rather than misclassifying the
                    # shed as an error
                    try:
                        ra = float(hd.get("Retry-After", "0.5") or 0.5)
                    except ValueError:
                        ra = 0.5
                    ra = max(0.05, ra)
                    if time.monotonic() + ra >= budget_end:
                        return  # budget exhausted while shed
                    await asyncio.sleep(ra)
                    continue
                if status != 200:
                    stats.errors += 1
                    return
                served_by = hd.get(wire.GATEWAY_SHARD_HEADER, "")
                break
            timing = comp.get("areal_timing") or {}
            usage = comp.get("usage") or {}
            n_tok = int(usage.get("completion_tokens") or 0)
            session_tokens += n_tok
            stats.tokens += n_tok
            if resolver is not None and served_by:
                shard_tokens[served_by] = (
                    shard_tokens.get(served_by, 0) + n_tok
                )
            if n_tok > 0 and timing.get("ttft_s"):
                # EVERY turn's TTFT enters the distribution — turns 2+
                # are exactly where prefix routing shows up (warm
                # suffix-only prefill vs a cold re-prefill of the whole
                # history). Zero-token completions (queued-expiry reaps)
                # never emitted a first token: their fallback ttft is the
                # full wall latency and would saturate p99 at the
                # deadline — counted by deadline_reaped, not the TTFT dist
                stats.ttft_s.append(float(timing["ttft_s"]))
            if (
                timing.get("truncated_by") == "deadline"
                or timing.get("stop_reason") == "deadline"
            ):
                reaped = True
                break
            messages = messages + [
                {
                    "role": "assistant",
                    "content": comp["choices"][0]["message"]["content"] or "",
                },
                {"role": "user", "content": f"go deeper on part {turn + 2}"},
            ]
        e2e = time.monotonic() - t0
        stats.completed += 1
        stats.e2e_s.append(e2e)
        if reaped:
            stats.deadline_reaped += 1
        elif e2e <= deadline_s:
            stats.tokens_within_deadline += session_tokens
            if resolver is not None:
                # per-shard goodput uses the same within-deadline rule as
                # the class totals, attributed to the serving shard
                for sid, tok in shard_tokens.items():
                    resolver.note_tokens(sid, tok)
    except Exception as e:  # noqa: BLE001 — one client's failure is a data
        # point (errors count), not a bench abort
        logger.debug(f"bench client failed: {e!r}")
        stats.errors += 1
    finally:
        if key is not None:
            try:
                await post(
                    "/rl/end_session",
                    {},
                    {"Authorization": f"Bearer {key}"},
                )
            except Exception as e:  # noqa: BLE001 — best-effort release
                logger.debug(f"end_session failed: {e!r}")


async def drive_gateway(
    gateway_url: str,
    admin_key: str,
    n_interactive: int,
    n_rollout: int,
    duration_s: float,
    interactive_deadline_s: float = 20.0,
    rollout_deadline_s: float = 30.0,
    interactive_tokens: int = 16,
    rollout_tokens: int = 128,
    interactive_prompts: list[str] | None = None,
    rollout_prompts: list[str] | None = None,
    turns: int = 1,
    rounds: int = 1,
    load_profile: str | list | None = None,
    greedy: bool = False,
    resolver: _TierResolver | None = None,
) -> dict[str, Any]:
    """Open-loop drive: each class's clients start on a fixed arrival
    schedule spread over ``duration_s``. ``*_prompts`` override the default
    single prompt per class (client i takes prompts[i % len]) — the
    shared-prefix router workload rides through here; ``turns`` makes each
    client a multi-turn episode. ``rounds`` repeats the whole schedule
    back-to-back into ONE aggregated report (the A/B uses it to average
    out scheduling transients). ``load_profile`` (a LOAD_PROFILES name or
    explicit (time_fraction, relative_rate) segments) makes the arrival
    rate time-varying — the overload-study / autopilot-acceptance shape;
    None keeps the legacy even spread. A ``resolver`` (gateway tier mode)
    hashes each session to a shard and survives shard death; without one
    every request hits ``gateway_url``. Returns the report dict."""
    import aiohttp

    stats = {p: _ClassStats() for p in PRIORITIES}
    segments = resolve_load_profile(load_profile)
    t_start = time.monotonic()

    async def schedule(priority, n, deadline_s, max_tokens, prompts, t0, rnd):
        offsets = (
            profile_arrivals(n, duration_s, segments)
            if segments is not None
            else [i * duration_s / max(1, n) for i in range(n)]
        )
        async with aiohttp.ClientSession() as http:
            tasks = []
            for i in range(n):
                target = t0 + offsets[i]
                delay = max(0.0, target - time.monotonic())
                if delay:
                    await asyncio.sleep(delay)
                tasks.append(
                    asyncio.ensure_future(
                        _one_client(
                            http,
                            gateway_url,
                            admin_key,
                            priority,
                            deadline_s,
                            max_tokens,
                            # rounds walk forward through the prompt list so
                            # a replayed schedule still sees fresh suffixes
                            prompts[(rnd * n + i) % len(prompts)],
                            stats[priority],
                            turns=turns,
                            greedy=greedy,
                            resolver=resolver,
                            client_id=rnd * n + i,
                        )
                    )
                )
            await asyncio.gather(*tasks)

    for rnd in range(max(1, rounds)):
        t0 = time.monotonic()
        await asyncio.gather(
            schedule(
                "interactive",
                n_interactive,
                interactive_deadline_s,
                interactive_tokens,
                interactive_prompts or ["ping?"],
                t0,
                rnd,
            ),
            schedule(
                "rollout",
                n_rollout,
                rollout_deadline_s,
                rollout_tokens,
                rollout_prompts or ["solve this problem step by step please"],
                t0,
                rnd,
            ),
        )
    wall = time.monotonic() - t_start
    report = {
        "bench": "gateway_goodput",
        "gateway": gateway_url,
        "duration_s": round(wall, 3),
        "classes": {p: stats[p].report(wall) for p in PRIORITIES},
    }
    if segments is not None:
        # the piecewise schedule rides the artifact so a report is
        # self-describing (which seconds were the spike)
        report["load_profile"] = {
            "name": load_profile if isinstance(load_profile, str) else "custom",
            "segments": [[f, w] for f, w in segments],
        }
    tot = _ClassStats()
    for s in stats.values():
        tot.sent += s.sent
        tot.completed += s.completed
        tot.shed_429 += s.shed_429
        tot.shed_requests += s.shed_requests
        tot.deadline_reaped += s.deadline_reaped
        tot.errors += s.errors
        tot.ttft_s += s.ttft_s
        tot.e2e_s += s.e2e_s
        tot.tokens += s.tokens
        tot.tokens_within_deadline += s.tokens_within_deadline
    report["totals"] = tot.report(wall)
    return report


# ---------------------------------------------------------------------------
# self-contained local fleet (tiny model; CPU-safe) under chaos stalls
# ---------------------------------------------------------------------------


class LocalFleet:
    """N engine replicas + rollout client + OpenAI proxy + gateway, all
    in-process — the 2-replica-under-chaos configuration the ISSUE's
    acceptance scenario names. ``start`` returns (gateway_url, admin_key)."""

    def __init__(
        self,
        n_replicas: int = 2,
        max_batch_size: int = 4,
        chaos_stall_prob: float = 0.3,
        chaos_stall_s: float = 0.1,
        max_queue_depth: int = 32,
        retry_after_s: float = 0.1,
        gateway_max_inflight: int = 0,
        gateway_interactive_headroom: int = 0,
        seed: int = 7,
        route_policy: str = "round_robin",
        max_seq_len: int = 512,
        routing_kw: dict | None = None,
        model: str = "tiny",
        autopilot_cfg: Any = None,
        n_gateways: int = 1,
    ):
        self.n_replicas = n_replicas
        self.n_gateways = n_gateways
        self.tier = None
        self.max_batch_size = max_batch_size
        self.chaos_stall_prob = chaos_stall_prob
        self.chaos_stall_s = chaos_stall_s
        self.max_queue_depth = max_queue_depth
        self.retry_after_s = retry_after_s
        self.gateway_max_inflight = gateway_max_inflight
        self.gateway_interactive_headroom = gateway_interactive_headroom
        self.seed = seed
        self.route_policy = route_policy
        self.max_seq_len = max_seq_len
        self.routing_kw = dict(routing_kw or {})
        self.model = model
        self.autopilot_cfg = autopilot_cfg
        self.autopilot = None
        self.gw_state = None
        self.servers: list[Any] = []
        self.client = None
        self._proxy_runner = None
        self._gateway_runner = None
        self.admin_key = "bench-admin"
        self.gateway_url = ""
        self.proxy_url = ""
        self._act_stop: Any = None
        self._act_samples: list[int] = []

    async def astart(self) -> tuple[str, str]:
        import jax
        from aiohttp import web

        from areal_tpu.api.config import (
            ChaosConfig,
            InferenceEngineConfig,
            MeshConfig,
            RequestLifecycleConfig,
            RoutingConfig,
            ServerConfig,
        )
        from areal_tpu.inference.client import RemoteJaxEngine
        from areal_tpu.inference.decode_engine import DecodeEngine
        from areal_tpu.inference.server import ServerThread
        from areal_tpu.models import qwen
        from areal_tpu.openai.proxy.gateway import (
            GatewayState,
            create_gateway_app,
        )
        from areal_tpu.openai.proxy.rollout_server import (
            ProxyState,
            create_proxy_app,
        )
        from areal_tpu.robustness import FaultInjector
        from areal_tpu.utils.network import find_free_port

        from areal_tpu.tools.validate_installation import tiny_model_config

        if self.model == "small":
            # prefill-costly bench model (the routing A/B): on the toy
            # 32-dim model a 700-token prefill costs single-digit ms, so
            # there is nothing for prefix routing to save — this one makes
            # prompt prefill the dominant per-request cost, like real
            # serving, while still CPU-feasible
            tiny = qwen.ModelConfig(
                vocab_size=128,
                hidden_size=128,
                intermediate_size=512,
                num_layers=4,
                num_heads=4,
                num_kv_heads=2,
                dtype="float32",
                tie_word_embeddings=True,
                rope_theta=10000.0,
            )
        else:
            tiny = tiny_model_config()
        params = qwen.init_params(jax.random.PRNGKey(0), tiny)
        for i in range(self.n_replicas):
            cfg = ServerConfig(
                max_batch_size=self.max_batch_size,
                max_seq_len=self.max_seq_len,
                decode_steps_per_call=4,
                # a real (shared-pool) page budget instead of the dense-
                # equivalent default: the radix cache may hold up to half
                # of it, so cross-request prefix reuse isn't evicted by a
                # handful of concurrent sessions (the router workload's
                # whole premise). The bigger bench model carries a bigger
                # per-page cost, so its budget scales to keep a few dozen
                # session prefixes resident.
                kv_hbm_gb=0.1 if self.model == "small" else 0.005,
                seed=self.seed + i,
                mesh=MeshConfig(data=-1, fsdp=1, seq=1, model=1),
                lifecycle=RequestLifecycleConfig(
                    max_queue_depth=self.max_queue_depth,
                    retry_after_s=self.retry_after_s,
                    watchdog_s=60.0,
                ),
            )
            eng = DecodeEngine(cfg, params=params, model_cfg=tiny)
            eng.initialize()
            st = ServerThread(cfg, eng)
            st.start()
            self.servers.append(st)
        self.client = RemoteJaxEngine(
            InferenceEngineConfig(
                max_concurrent_rollouts=64,
                consumer_batch_size=8,
                max_head_offpolicyness=1000,
                request_timeout=120,
                request_retries=3,
                routing_policy=self.route_policy,
                # short bench: snapshots must refresh well inside the run
                routing=RoutingConfig(
                    poll_interval_s=0.5, **self.routing_kw
                ),
            ),
            addresses=[s.address for s in self.servers],
        )
        self.client.initialize()
        if self.chaos_stall_prob > 0:
            self.client.install_fault_injector(
                FaultInjector(
                    ChaosConfig(
                        enabled=True,
                        seed=self.seed,
                        stall_prob=self.chaos_stall_prob,
                        stall_s=self.chaos_stall_s,
                        path_prefix="/generate",
                    )
                )
            )
        proxy_state = ProxyState(
            self.client,
            CharTokenizer(),
            admin_api_key=self.admin_key,
            capacity=4096,
        )
        self._proxy_runner = web.AppRunner(create_proxy_app(proxy_state))
        await self._proxy_runner.setup()
        pport = find_free_port()
        await web.TCPSite(self._proxy_runner, "127.0.0.1", pport).start()
        self.proxy_url = f"http://127.0.0.1:{pport}"
        if self.n_gateways > 1:
            # the horizontally-sharded tier: N gateway shards over this
            # one proxy, membership in a PRIVATE memory repo (concurrent
            # benches must not cross-pollinate the process-wide default)
            from areal_tpu.api.config import GatewayTierConfig
            from areal_tpu.openai.proxy.tier import GatewayTier
            from areal_tpu.utils import name_resolve

            self.tier = GatewayTier(
                [self.proxy_url],
                self.admin_key,
                cfg=GatewayTierConfig(
                    enabled=True,
                    n_shards=self.n_gateways,
                    membership_ttl_s=2.0,
                    membership_poll_s=0.25,
                ),
                max_inflight=self.gateway_max_inflight,
                interactive_headroom=self.gateway_interactive_headroom,
                retry_after_s=0.2,
                repo=name_resolve.MemoryNameResolveRepo(),
            )
            await self.tier.astart()
            # the plain-URL consumers (greedy probes) pin shard 0
            self.gateway_url = f"http://{self.tier.addresses()[0]}"
            self.gw_state = next(iter(self.tier.shards.values())).state
        else:
            gw_state = GatewayState(
                [self.proxy_url],
                admin_api_key=self.admin_key,
                max_inflight=self.gateway_max_inflight,
                interactive_headroom=self.gateway_interactive_headroom,
                retry_after_s=0.2,
            )
            self._gateway_runner = web.AppRunner(create_gateway_app(gw_state))
            await self._gateway_runner.setup()
            gport = find_free_port()
            await web.TCPSite(self._gateway_runner, "127.0.0.1", gport).start()
            self.gateway_url = f"http://127.0.0.1:{gport}"
            self.gw_state = gw_state
        if self.autopilot_cfg is not None and self.autopilot_cfg.enabled:
            # the goodput autopilot over this fleet: knob pushes over HTTP
            # like production, the gateway headroom via the in-process
            # hook (the gateway lives in the controller process there too)
            from areal_tpu.autopilot import Autopilot

            self.autopilot = Autopilot(
                self.autopilot_cfg,
                lambda: [s.address for s in self.servers],
                gateway=self.gw_state,
                gateway_tier=self.tier,
            )
            self.autopilot.seed_setpoints(
                max_queue_depth=self.max_queue_depth,
                gateway_interactive_headroom=self.gateway_interactive_headroom,
            )
            self.autopilot.start()
        return self.gateway_url, self.admin_key

    async def astop(self) -> None:
        from areal_tpu.inference.client import close_loop_sessions

        if self.autopilot is not None:
            self.autopilot.stop()
        if self.tier is not None:
            await self.tier.astop()
        if self._gateway_runner is not None:
            await self._gateway_runner.cleanup()
        if self._proxy_runner is not None:
            await self._proxy_runner.cleanup()
        if self.client is not None:
            self.client.destroy()
        # the proxy drove agenerate on THIS loop: close its cached session
        # (destroy only reaches the client's executor-loop cache)
        await close_loop_sessions()
        for st in self.servers:
            st.stop()

    # -- fleet-activity accounting (the autoscaler scoreboard) -------------
    def start_activity_sampler(self, period_s: float = 0.25) -> None:
        """Sample the count of non-draining replicas on a wall clock so
        the report can price goodput per replica-second — the number the
        fleet controller must move (drained capacity is returned
        capacity)."""
        import threading

        stop = threading.Event()
        self._act_stop = stop
        self._act_samples = []

        def run():
            while not stop.wait(period_s):
                self._act_samples.append(
                    sum(1 for st in self.servers if not st.engine.is_draining)
                )

        threading.Thread(target=run, daemon=True).start()

    def stop_activity_sampler(self) -> float | None:
        if self._act_stop is not None:
            self._act_stop.set()
            self._act_stop = None
        if not self._act_samples:
            return None
        return sum(self._act_samples) / len(self._act_samples)

    def mark_baseline(self) -> None:
        """Snapshot the cumulative engine counters so ``engine_stats``
        reports deltas from here — the A/B measures its timed window, not
        the warm-up traffic before it."""
        self._baseline = {
            st.address: {
                k: st.engine.stats[k]
                for k in (
                    "generated_tokens",
                    "prefix_cache_hits",
                    "prefix_hit_tokens",
                    "prefill_tokens",
                )
            }
            for st in self.servers
        }

    def engine_stats(self) -> dict[str, Any]:
        """Fleet-level engine counters folded into the report (deadline
        reaps, timeline health, and the prefix-reuse numbers the routing
        A/B compares come from the engines themselves). Counters are
        deltas from ``mark_baseline`` when one was taken."""
        base = getattr(self, "_baseline", {})
        out: dict[str, Any] = {"replicas": []}
        hit_tokens = prefill_tokens = 0
        for st in self.servers:
            eng = st.engine
            b = base.get(st.address, {})

            def d(key: str) -> int:
                return eng.stats[key] - b.get(key, 0)

            hit_tokens += d("prefix_hit_tokens")
            prefill_tokens += d("prefill_tokens")
            out["replicas"].append(
                {
                    "address": st.address,
                    "generated_tokens": d("generated_tokens"),
                    "deadline_exceeded": eng.stats["deadline_exceeded"],
                    "prefix_cache_hits": d("prefix_cache_hits"),
                    "prefix_hit_tokens": d("prefix_hit_tokens"),
                    "prefill_tokens": d("prefill_tokens"),
                    "timelines": eng.timeline.stats(),
                }
            )
        # suffix-only prefill economics: warm tokens over all prompt
        # tokens admitted (cached + actually prefilled) — the number the
        # cache-aware arm must raise
        out["prefix_hit_tokens"] = hit_tokens
        out["prefill_tokens"] = prefill_tokens
        out["prefix_hit_rate"] = (
            hit_tokens / (hit_tokens + prefill_tokens)
            if (hit_tokens + prefill_tokens) > 0
            else None
        )
        return out


async def _greedy_probes(
    gateway_url: str,
    admin_key: str,
    prompts: list[str],
    max_tokens: int = 8,
) -> list[str]:
    """Sequential greedy (temperature=0) completions through the gateway.

    Dual duty in the A/B: the returned texts are the byte-identity
    evidence (routing is placement-only — greedy output must not depend
    on the policy), and running them BEFORE the timed drive warms both
    arms' compile caches (incl. the suffix-only prefill variant) so the
    measured window compares steady-state serving, not XLA compiles."""
    import aiohttp

    texts: list[str] = []
    async with aiohttp.ClientSession() as http:
        for i, prompt in enumerate(prompts):
            admin = {"Authorization": f"Bearer {admin_key}"}
            async with http.post(
                f"{gateway_url}/rl/start_session",
                json={"task_id": f"probe-{i}"},
                headers=admin,
            ) as r:
                sess = await r.json(content_type=None)
            key = sess["api_key"]
            headers = {"Authorization": f"Bearer {key}"}
            try:
                async with http.post(
                    f"{gateway_url}/v1/chat/completions",
                    json={
                        "messages": [{"role": "user", "content": prompt}],
                        "max_completion_tokens": max_tokens,
                        "temperature": 0,
                        "model": "bench",
                    },
                    headers=headers,
                ) as r:
                    # a failed probe is evidence, not an abort: a marker
                    # text keeps the byte-identity comparison meaningful
                    # (both arms see the same fleet, so a persistent error
                    # reproduces; a transient one shows as a mismatch)
                    if r.status != 200:
                        texts.append(f"<probe-error:{r.status}>")
                        continue
                    comp = await r.json(content_type=None)
                choices = comp.get("choices") or []
                msg = (choices[0].get("message") or {}) if choices else {}
                texts.append(
                    msg.get("content") or ("" if choices else "<probe-malformed>")
                )
            finally:
                async with http.post(
                    f"{gateway_url}/rl/end_session",
                    json={},
                    headers=headers,
                ):
                    pass
    return texts


def _workload_prompts(
    workload: str,
    n_interactive: int,
    n_rollout: int,
    shared_frac: float,
    prompt_chars: int,
    generation: int = 0,
    generations: int = 1,
) -> tuple[list[str] | None, list[str] | None]:
    if workload != "shared_prefix":
        return None, None
    # one shared family across BOTH classes (the agent-fleet shape: many
    # concurrent episodes over one system prompt/task template).
    # ``generation`` skips past earlier windows' suffix sets over the SAME
    # shared prefix — the warm-up and measured windows (and each measured
    # round, via ``generations``) must not replay identical prompts (a
    # full-prompt radix match would measure memoization, not prefix
    # routing). Suffixes are split per class so round r's interactive set
    # never collides with round r-1's rollout set.
    n = n_interactive + n_rollout
    prompts = make_shared_prefix_prompts(
        n * (generation + generations),
        shared_frac=shared_frac,
        total_chars=prompt_chars,
    )[n * generation :]
    ni_all = n_interactive * generations
    return prompts[:ni_all] or None, prompts[ni_all:] or None


async def run_local_bench(
    n_replicas: int = 2,
    n_interactive: int = 8,
    n_rollout: int = 8,
    duration_s: float = 15.0,
    workload: str = "mixed",
    shared_frac: float = 0.8,
    prompt_chars: int = 400,
    interactive_tokens: int = 16,
    rollout_tokens: int = 128,
    interactive_deadline_s: float = 20.0,
    rollout_deadline_s: float = 30.0,
    turns: int = 1,
    rounds: int = 1,
    probe_prompts: list[str] | None = None,
    warmup_s: float = 0.0,
    load_profile: str | list | None = None,
    greedy: bool = False,
    kill_shard_at: float | None = None,
    post_probe_prompts: list[str] | None = None,
    **fleet_kw: Any,
) -> dict[str, Any]:
    fleet = LocalFleet(n_replicas=n_replicas, **fleet_kw)
    try:
        gateway_url, admin_key = await fleet.astart()
        resolver = _TierResolver(fleet.tier) if fleet.tier is not None else None
        probe_texts = None
        if probe_prompts:
            probe_texts = await _greedy_probes(
                gateway_url, admin_key, probe_prompts
            )
        if warmup_s > 0:
            # uncounted steady-state warm-up: first-use XLA compiles (incl.
            # the suffix-only prefill variant at its batched shapes) and
            # the radix/shadow warm-up must not land inside the measured
            # window of either A/B arm. Its prompts share the prefix but
            # none of the suffixes of the measured set (generation 0 vs 1).
            warm_ip, warm_rp = _workload_prompts(
                workload,
                n_interactive,
                n_rollout,
                shared_frac,
                prompt_chars,
                generation=0,
            )
            # FULL client count: the warm-up must reach the same batched
            # admission shapes (prefill A_pad x bucket x page-table width)
            # as the measured window, or first-use compiles land in it
            await drive_gateway(
                gateway_url,
                admin_key,
                n_interactive=n_interactive,
                n_rollout=n_rollout,
                duration_s=warmup_s,
                interactive_tokens=interactive_tokens,
                rollout_tokens=rollout_tokens,
                interactive_deadline_s=interactive_deadline_s,
                rollout_deadline_s=rollout_deadline_s,
                interactive_prompts=warm_ip,
                rollout_prompts=warm_rp,
                turns=turns,
                greedy=greedy,
                resolver=resolver,
            )
        ip, rp = _workload_prompts(
            workload,
            n_interactive,
            n_rollout,
            shared_frac,
            prompt_chars,
            generation=1 if warmup_s > 0 else 0,
            generations=max(1, rounds),
        )
        fleet.mark_baseline()
        if resolver is not None:
            # the measured window's scoreboard starts clean (warm-up
            # traffic attributed tokens too)
            resolver.shard_tokens = {}
            resolver.failovers = 0
        killed_shard = None
        kill_handle = None
        if kill_shard_at is not None and fleet.tier is not None:
            # the deterministic chaos point: hard-kill one shard T seconds
            # into the measured window (highest shard id — stable across
            # runs, so the kill and no-kill twins differ ONLY in the kill)
            killed_shard = sorted(fleet.tier.shards)[-1]
            kill_handle = asyncio.get_running_loop().call_later(
                max(0.0, kill_shard_at), fleet.tier.kill_shard, killed_shard
            )
        fleet.start_activity_sampler()
        report = await drive_gateway(
            gateway_url,
            admin_key,
            n_interactive=n_interactive,
            n_rollout=n_rollout,
            duration_s=duration_s,
            interactive_tokens=interactive_tokens,
            rollout_tokens=rollout_tokens,
            interactive_deadline_s=interactive_deadline_s,
            rollout_deadline_s=rollout_deadline_s,
            interactive_prompts=ip,
            rollout_prompts=rp,
            turns=turns,
            rounds=rounds,
            load_profile=load_profile,
            greedy=greedy,
            resolver=resolver,
        )
        if kill_handle is not None:
            kill_handle.cancel()  # no-op if it already fired
        active_mean = fleet.stop_activity_sampler()
        report["workload"] = workload
        report["turns"] = turns
        report["route_policy"] = fleet.route_policy
        report["fleet"] = fleet.engine_stats()
        report["fleet"]["active_replicas_mean"] = active_mean
        goodput = report["totals"]["goodput_tok_s"]
        report["goodput_per_replica_tok_s"] = (
            goodput / active_mean if active_mean else None
        )
        report["router"] = fleet.client.router.stats()
        report["router_hit_rate"] = report["fleet"]["prefix_hit_rate"]
        # the control plane's scoreboard entry: active setpoints + the
        # decision ledger (bench.py folds this into detail.autopilot)
        report["autopilot"] = (
            fleet.autopilot.status() if fleet.autopilot is not None else None
        )
        report["gateway_shards"] = fleet.n_gateways
        if resolver is not None:
            tier_report = resolver.report(report["duration_s"])
            tier_report["killed_shard"] = killed_shard
            tier_report["shard_stats"] = fleet.tier.shard_stats()
            report["gateway_tier"] = tier_report
        if probe_texts is not None:
            report["probe_texts"] = probe_texts
        if post_probe_prompts:
            # POST-drive identity evidence: in a kill run these greedy
            # completions ride a tier that already lost a shard — output
            # must still match the no-kill twin byte for byte (membership
            # moves placement, never sampling). Served from a live shard.
            url = (
                f"http://{fleet.tier.addresses()[0]}"
                if fleet.tier is not None
                else gateway_url
            )
            report["post_probe_texts"] = await _greedy_probes(
                url, admin_key, post_probe_prompts
            )
        return report
    finally:
        await fleet.astop()


async def run_ab(
    n_replicas: int = 3,
    n_interactive: int = 18,
    n_rollout: int = 18,
    duration_s: float = 4.0,
    workload: str = "shared_prefix",
    shared_frac: float = 0.1,
    # long unique base prompts (the A/B fleet runs a 1024-token context
    # and a prefill-costly bench model) with short completions: the
    # workload where prefix routing pays is prefill-dominated — the
    # multi-turn agent / RL-scoring shape. Short prompts + long decodes
    # are load-balancing's domain (the score's queue/inflight terms), not
    # a prefix-locality scoreboard.
    prompt_chars: int = 680,
    interactive_tokens: int = 4,
    rollout_tokens: int = 8,
    turns: int = 3,
    rounds: int = 2,
    **fleet_kw: Any,
) -> dict[str, Any]:
    """The routing scoreboard: one fresh fleet per arm (identical seeds,
    params, chaos schedule), round_robin then cache_aware, same
    80%-shared-prefix multi-turn workload, each arm warmed (probes + an
    uncounted drive) before its measured window.

    Workload shape: each session's base prompt is unique (plus a small
    fleet-global task preamble, ``shared_frac``); the ~80%+ prefix
    sharing is per-request CONVERSATION HISTORY — turn t's prompt extends
    turn t-1's sequence, so every turn past the first shares >85% of its
    tokens with state some replica already holds. That is the sharing a
    router can actually exploit: a fleet-global prefix replicates onto
    every replica within one warm-up pass and round-robin gets it for
    free, while session history lives on exactly ONE replica — blind
    rotation re-prefills it ~(N-1)/N of the time and prefix routing never
    does. Arrivals outpace service (open-loop saturation) so the saved
    prefill converts into wall-clock/goodput, not idle slots.

    The comparison block is what the driver reads: goodput, warm
    suffix-only prefill economics, and greedy byte-identity across arms
    (placement only, never output)."""
    # probes repeat 2 prompts x3 so every replica sees the shared prefix
    # at least once under round-robin too — compile + radix warm-up in
    # both arms, and 6 texts of identity evidence
    probe_prompts = make_shared_prefix_prompts(
        2, shared_frac=shared_frac, total_chars=prompt_chars, seed=97
    ) * 3
    arms: dict[str, dict[str, Any]] = {}
    for policy in ("round_robin", "cache_aware"):
        arms[policy] = await run_local_bench(
            n_replicas=n_replicas,
            n_interactive=n_interactive,
            n_rollout=n_rollout,
            duration_s=duration_s,
            workload=workload,
            shared_frac=shared_frac,
            prompt_chars=prompt_chars,
            interactive_tokens=interactive_tokens,
            rollout_tokens=rollout_tokens,
            turns=turns,
            rounds=rounds,
            probe_prompts=probe_prompts,
            warmup_s=max(2.0, duration_s / 2),
            route_policy=policy,
            max_seq_len=1024,
            model="small",
            **fleet_kw,
        )
    rr, ca = arms["round_robin"], arms["cache_aware"]
    comparison = {
        "goodput_tok_s": {
            "round_robin": rr["totals"]["goodput_tok_s"],
            "cache_aware": ca["totals"]["goodput_tok_s"],
        },
        "prefix_hit_rate": {
            "round_robin": rr["fleet"]["prefix_hit_rate"],
            "cache_aware": ca["fleet"]["prefix_hit_rate"],
        },
        "suffix_prefill_tokens": {
            "round_robin": rr["fleet"]["prefill_tokens"],
            "cache_aware": ca["fleet"]["prefill_tokens"],
        },
        "cache_aware_wins_goodput": (
            ca["totals"]["goodput_tok_s"] > rr["totals"]["goodput_tok_s"]
        ),
        "cache_aware_wins_prefill": (
            (ca["fleet"]["prefix_hit_rate"] or 0.0)
            > (rr["fleet"]["prefix_hit_rate"] or 0.0)
        ),
        "greedy_identical": rr.get("probe_texts") == ca.get("probe_texts"),
    }
    return {
        "bench": "gateway_route_ab",
        "workload": workload,
        "shared_frac": shared_frac,
        "prompt_chars": prompt_chars,
        "arms": arms,
        "comparison": comparison,
    }


async def run_tier_ab(
    n_replicas: int = 2,
    n_interactive: int = 90,
    n_rollout: int = 90,
    duration_s: float = 3.0,
    deadline_s: float = 20.0,
    shard_inflight: int = 2,
    kill_at_frac: float = 0.4,
    **fleet_kw: Any,
) -> dict[str, Any]:
    """The gateway-tier scoreboard (ISSUE 18 acceptance): the SAME fleet
    shape behind 1 gateway shard, 3 shards, and 3 shards with one killed
    mid-run.

    The workload is gateway-ADMISSION-bound by construction: each shard
    admits only ``shard_inflight`` concurrent completions (the per-process
    ceiling the tier exists to multiply), and per-request service time is
    dominated by a deterministic chaos stall on every engine call (wait,
    not compute — in-process shards share one CPU budget, so only
    latency-bound work can scale with admission slots, exactly like a
    production fleet whose gateway ceiling is connection/IO concurrency,
    not cycles). Demand is several times what ``shard_inflight`` slots
    can clear inside ``deadline_s``: the single-shard arm sheds clients
    out of their entire deadline budget while three shards clear the same
    demand in time. Scored on within-deadline goodput, the metric the
    whole gateway exists to protect; sub-linear scaling means the tier
    added contention on the request path (exactly what the shared-nothing
    design forbids).

    The kill twin asserts the robustness headline: zero responseless
    requests (every client completes, sheds, or reaps — never errors) and
    post-kill greedy outputs byte-identical to the no-kill twin's
    (membership moves placement, never sampling)."""
    probe_prompts = make_shared_prefix_prompts(
        2, shared_frac=0.5, total_chars=120, seed=53
    )
    common = dict(
        n_replicas=n_replicas,
        n_interactive=n_interactive,
        n_rollout=n_rollout,
        duration_s=duration_s,
        interactive_tokens=8,
        rollout_tokens=16,
        interactive_deadline_s=deadline_s,
        rollout_deadline_s=deadline_s,
        greedy=True,
        post_probe_prompts=probe_prompts,
        # every engine call stalls 0.4s: service time is wait-dominated
        # and identical across arms (same seed, same schedule), so the
        # admission ceiling is the only thing the arms disagree on
        chaos_stall_prob=1.0,
        chaos_stall_s=0.4,
        gateway_max_inflight=shard_inflight,
        **fleet_kw,
    )
    arms: dict[str, dict[str, Any]] = {}
    arms["shards_1"] = await run_local_bench(n_gateways=1, **common)
    arms["shards_3"] = await run_local_bench(n_gateways=3, **common)
    arms["shards_3_kill"] = await run_local_bench(
        n_gateways=3, kill_shard_at=duration_s * kill_at_frac, **common
    )
    g1 = arms["shards_1"]["totals"]["goodput_tok_s"]
    g3 = arms["shards_3"]["totals"]["goodput_tok_s"]
    kill = arms["shards_3_kill"]
    kill_errors = sum(
        kill["classes"][p]["errors"] for p in PRIORITIES
    )
    survivors = {
        sid: tok
        for sid, tok in kill["gateway_tier"]["per_shard_goodput_tok_s"].items()
        if sid != kill["gateway_tier"]["killed_shard"]
    }
    comparison = {
        "goodput_tok_s": {"shards_1": g1, "shards_3": g3},
        "scaling_x": (g3 / g1) if g1 > 0 else None,
        "near_linear": g1 > 0 and g3 / g1 >= 2.2,
        "killed_shard": kill["gateway_tier"]["killed_shard"],
        "kill_failovers": kill["gateway_tier"]["failovers"],
        "kill_errors": kill_errors,
        "kill_zero_responseless": kill_errors == 0,
        # the dead shard's keyspace must land on survivors, not vanish
        "survivors_absorbed": any(v > 0 for v in survivors.values()),
        "kill_greedy_identical": (
            kill.get("post_probe_texts")
            == arms["shards_3"].get("post_probe_texts")
        ),
    }
    return {
        "bench": "gateway_tier_ab",
        "shard_inflight": shard_inflight,
        "arms": arms,
        "comparison": comparison,
    }


def bench_autopilot_config(
    interval_s: float = 1.0,
    min_queue_depth: int = 2,
    max_queue_depth: int = 128,
    high_queue_wait_s: float = 2.0,
    low_queue_wait_s: float = 0.8,
    fleet: bool = False,
    fleet_floor: int = 1,
):
    """A fast-cadence AutopilotConfig tuned for short CPU benches and
    self-tests (sub-second control rounds, 1-2s cooldowns). Production
    deployments should keep the config defaults — 5s rounds and 10-30s
    cooldowns — and let hysteresis do its job over minutes, not seconds."""
    from areal_tpu.api.config import (
        AdmissionControllerConfig,
        AutopilotConfig,
        CacheControllerConfig,
        FleetControllerConfig,
        StalenessControllerConfig,
    )

    return AutopilotConfig(
        enabled=True,
        interval_s=interval_s,
        signal_ttl_s=10.0,
        staleness=StalenessControllerConfig(enabled=False),
        cache=CacheControllerConfig(enabled=False),
        admission=AdmissionControllerConfig(
            enabled=not fleet,
            cooldown_s=interval_s * 2,
            min_queue_depth=min_queue_depth,
            max_queue_depth=max_queue_depth,
            queue_depth_step=8,
            high_queue_wait_s=high_queue_wait_s,
            low_queue_wait_s=low_queue_wait_s,
            high_shed_rate_per_s=0.5,
            # the page-headroom subcontroller is the self-test's subject
            # (it needs a page-tight fleet to matter); on the short A/B it
            # would only add decision churn
            high_reap_rate_per_s=1e9,
            headroom_step=2,
            max_headroom=16,
            narrow_after_quiet_rounds=8,
        ),
        fleet=FleetControllerConfig(
            enabled=fleet,
            min_replicas=fleet_floor,
            drain_below_load=0.4,
            undrain_above_queue=0.3,
            sustain_rounds=3,
            undrain_sustain_rounds=1,
            cooldown_s=interval_s * 3,
        ),
    )


async def run_autopilot_ab(
    n_replicas: int = 1,
    n_interactive: int = 10,
    n_rollout: int = 80,
    duration_s: float = 16.0,
    load_profile: str = "burst",
    static_queue_depths: tuple[int, ...] = (24, 96),
    autopilot_start_depth: int = 24,
    deadline_s: float = 3.0,
    fleet_run: bool = False,
    **fleet_kw: Any,
) -> dict[str, Any]:
    """The autopilot acceptance scoreboard (ROADMAP item 6): one fresh
    fleet per arm, identical seeds/params/chaos schedule and the SAME
    time-varying ``load_profile``, comparing a small static-config sweep
    against autopilot-on.

    The admission run (default): static ``max_queue_depth`` arms must
    lose somewhere on a bursty profile — a small queue sheds the calm
    phase, a big one converts the spike into deadline-missed tail latency
    — while the autopilot's AIMD tracks the phase it is in. Scored on
    within-deadline goodput. The greedy probes double as the byte-identity
    evidence: the control plane moves ADMISSION, never sampling.

    ``fleet_run=True`` instead scores the fleet controller on
    goodput-per-replica-second over a diurnal profile: draining idle
    replicas during the trough returns capacity (the denominator) that a
    static fleet keeps burning.

    Every autopilot arm also reports its decision ledger, and the driver
    can join each setpoint change against the flight ring
    (``kind=autopilot_decision``) for the audit trail."""
    from areal_tpu.observability import timeline as tl_mod

    if fleet_run:
        n_replicas = max(3, n_replicas)
        load_profile = "diurnal"
        # mean demand ~60% of fleet capacity: the autoscaler's win is the
        # trough's returned replica-seconds, not overload admission
        n_rollout = min(n_rollout, 50)
        # bounded per-replica queues in BOTH arms: after a scale-down, a
        # rising wave must spill to siblings (429 -> failover) instead of
        # piling deadline-doomed work onto the survivor
        fleet_kw.setdefault("max_queue_depth", 8)
    probe_prompts = make_shared_prefix_prompts(
        2, shared_frac=0.5, total_chars=120, seed=31
    ) * 2
    common = dict(
        n_replicas=n_replicas,
        n_interactive=n_interactive,
        n_rollout=n_rollout,
        duration_s=duration_s,
        interactive_tokens=8,
        # rollout decodes are the capacity sink: on the decode-costly
        # "small" bench model, 256-token greedy decodes make per-request
        # service time a real fraction of the deadline, so the burst
        # overcommits the engine ~3x while the calm phases stay under
        # capacity — the regime where a static queue depth must pick its
        # poison: a deep queue decodes doomed work past its deadline
        # (measured: depth 96 loses ~10% goodput here), a shallow one
        # idles the engine between Retry-After waves
        rollout_tokens=256,
        interactive_deadline_s=deadline_s,
        rollout_deadline_s=deadline_s,
        load_profile=load_profile,
        probe_prompts=probe_prompts,
        warmup_s=3.0,
        model="small",
        max_batch_size=2,
        retry_after_s=0.4,
        greedy=True,
        **fleet_kw,
    )
    arms: dict[str, dict[str, Any]] = {}
    if fleet_run:
        # the static fleet-size sweep: the full fleet, always on
        static_arms = {f"static_{n_replicas}_replicas": dict(common)}
    else:
        static_arms = {
            f"static_depth_{d}": dict(common, max_queue_depth=d)
            for d in static_queue_depths
        }
    for name, kw in static_arms.items():
        arms[name] = await run_local_bench(**kw)
    # autopilot arm: count only ITS decisions (the ring is process-global)
    ring_seq0 = max(
        (e.get("seq", 0) for e in tl_mod.get_flight_recorder().snapshot()["events"]),
        default=0,
    )
    # floor 2 of 3: the trough returns one replica's worth of capacity
    # while two survivors keep every deadline coverable (a floor of 1
    # measured ~20% deadline reaps when the rising wave lands before the
    # undrain — scale-down depth is a safety knob, not a free lunch)
    ap_cfg = bench_autopilot_config(fleet=fleet_run, fleet_floor=2)
    auto_kw = dict(common, autopilot_cfg=ap_cfg)
    if not fleet_run:
        auto_kw["max_queue_depth"] = autopilot_start_depth
    arms["autopilot"] = await run_local_bench(**auto_kw)
    decisions = [
        e
        for e in tl_mod.get_flight_recorder().snapshot()["events"]
        if e.get("kind") == "autopilot_decision" and e.get("seq", 0) > ring_seq0
    ]
    metric = "goodput_per_replica_tok_s" if fleet_run else None

    def score(arm: dict[str, Any]) -> float:
        if metric:
            return float(arm.get(metric) or 0.0)
        return float(arm["totals"]["goodput_tok_s"])

    static_scores = {n: score(arms[n]) for n in static_arms}
    auto_score = score(arms["autopilot"])
    probe_sets = {n: arms[n].get("probe_texts") for n in arms}
    comparison = {
        "metric": metric or "goodput_tok_s",
        "load_profile": load_profile,
        "static": static_scores,
        "autopilot": auto_score,
        "autopilot_wins": bool(
            static_scores and auto_score > max(static_scores.values())
        ),
        "autopilot_decisions": len(decisions),
        "decisions_audited": all(
            (e.get("data") or {}).get("reason")
            and (e.get("data") or {}).get("knob")
            for e in decisions
        )
        and len(decisions) > 0,
        # placement/admission only, never output: greedy probes must be
        # byte-identical across every arm
        "greedy_identical": len({tuple(v or ()) for v in probe_sets.values()})
        == 1,
    }
    return {
        "bench": "gateway_autopilot_ab",
        "fleet_run": fleet_run,
        "arms": arms,
        "decisions": [e.get("data") for e in decisions[-32:]],
        "comparison": comparison,
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--gateway", default="", help="existing gateway base url")
    p.add_argument("--admin-key", default="", help="gateway admin API key")
    p.add_argument(
        "--local",
        action="store_true",
        help="spin a self-contained local fleet (tiny model) to bench",
    )
    p.add_argument("--replicas", type=int, default=None)
    p.add_argument("--interactive", type=int, default=None)
    p.add_argument("--rollout", type=int, default=None)
    p.add_argument("--duration", type=float, default=None)
    p.add_argument("--stall-prob", type=float, default=0.3)
    p.add_argument("--stall-s", type=float, default=0.1)
    p.add_argument("--max-inflight", type=int, default=0)
    p.add_argument("--headroom", type=int, default=0)
    p.add_argument(
        "--route-policy",
        choices=("round_robin", "cache_aware"),
        default="round_robin",
        help="replica-selection policy for the local fleet's client",
    )
    p.add_argument(
        "--workload",
        choices=("mixed", "shared_prefix"),
        default=None,
        help="shared_prefix = 80%%-shared multi-turn-style prompts (the "
        "prefix-locality routing scoreboard). Default: mixed, or "
        "shared_prefix under --ab",
    )
    p.add_argument(
        "--shared-frac",
        type=float,
        default=None,
        help="fleet-global shared task-preamble fraction of each base "
        "prompt. Default: 0.8, or 0.1 under --ab (there the ~80%% "
        "per-request sharing comes from multi-turn conversation history "
        "— the prefix structure a router can actually exploit)",
    )
    p.add_argument("--prompt-chars", type=int, default=None)
    p.add_argument(
        "--turns",
        type=int,
        default=None,
        help="chat turns per client session (default: 3 under --ab, else 1)",
    )
    p.add_argument(
        "--ab",
        action="store_true",
        help="run BOTH policies on fresh identical local fleets and emit "
        "one comparison report (goodput, suffix-prefill tokens, greedy "
        "byte-identity)",
    )
    p.add_argument(
        "--gateways",
        type=int,
        default=1,
        help="gateway shards for the local fleet (N>1 runs the "
        "consistent-hash tier; 1 keeps the pre-tier single gateway)",
    )
    p.add_argument(
        "--kill-shard-at",
        type=float,
        default=None,
        metavar="T",
        help="with --gateways N>1: hard-kill one shard T seconds into "
        "the measured window (the chaos point — clients must re-hash to "
        "survivors with zero responseless requests)",
    )
    p.add_argument(
        "--tier-ab",
        action="store_true",
        help="gateway-tier acceptance A/B: 1 vs 3 shards on the same "
        "fleet plus a mid-run-kill twin, one comparison report (scaling, "
        "zero responseless, greedy byte-identity)",
    )
    p.add_argument(
        "--load-profile",
        choices=("uniform", *sorted(LOAD_PROFILES)),
        default="uniform",
        help="time-varying open-loop arrival-rate profile (piecewise "
        "schedule recorded in the JSON artifact); uniform keeps the "
        "legacy even spread",
    )
    p.add_argument(
        "--autopilot-ab",
        action="store_true",
        help="autopilot acceptance A/B: a static max_queue_depth sweep vs "
        "autopilot-on under the chosen --load-profile (default: burst), "
        "scored on within-deadline goodput with the decision audit "
        "attached",
    )
    p.add_argument(
        "--fleet-run",
        action="store_true",
        help="with --autopilot-ab: score the FLEET controller instead "
        "(3 replicas, diurnal profile, goodput per replica-second)",
    )
    p.add_argument("-o", "--output", default="", help="JSON report path")
    args = p.parse_args(argv)
    # mode-dependent defaults: the A/B needs a saturated shared-prefix
    # multi-turn fleet; the plain bench keeps its standing configuration
    if args.workload is None:
        args.workload = "shared_prefix" if args.ab else "mixed"
    if args.turns is None:
        args.turns = 3 if args.ab else 1
    if args.replicas is None:
        args.replicas = 3 if args.ab else 2
    if args.interactive is None:
        args.interactive = 18 if args.ab else 8
    if args.rollout is None:
        args.rollout = 18 if args.ab else 8
    if args.duration is None:
        args.duration = 4.0 if args.ab else 15.0
    if args.shared_frac is None:
        args.shared_frac = 0.1 if args.ab else 0.8

    if args.tier_ab:
        report = asyncio.run(
            run_tier_ab(
                duration_s=args.duration if args.duration != 15.0 else 6.0,
            )
        )
    elif args.autopilot_ab:
        report = asyncio.run(
            run_autopilot_ab(
                load_profile=(
                    "burst"
                    if args.load_profile == "uniform" and not args.fleet_run
                    else args.load_profile
                ),
                fleet_run=args.fleet_run,
                chaos_stall_prob=args.stall_prob,
                chaos_stall_s=args.stall_s,
            )
        )
    elif args.ab:
        kw = {}
        if args.prompt_chars is not None:
            kw["prompt_chars"] = args.prompt_chars
        report = asyncio.run(
            run_ab(
                n_replicas=args.replicas,
                n_interactive=args.interactive,
                n_rollout=args.rollout,
                duration_s=args.duration,
                workload=args.workload,
                shared_frac=args.shared_frac,
                turns=args.turns,
                chaos_stall_prob=args.stall_prob,
                chaos_stall_s=args.stall_s,
                gateway_max_inflight=args.max_inflight,
                gateway_interactive_headroom=args.headroom,
                **kw,
            )
        )
    elif args.local or not args.gateway:
        report = asyncio.run(
            run_local_bench(
                n_replicas=args.replicas,
                n_interactive=args.interactive,
                n_rollout=args.rollout,
                duration_s=args.duration,
                workload=args.workload,
                shared_frac=args.shared_frac,
                prompt_chars=args.prompt_chars or 400,
                turns=args.turns,
                load_profile=args.load_profile,
                chaos_stall_prob=args.stall_prob,
                chaos_stall_s=args.stall_s,
                gateway_max_inflight=args.max_inflight,
                gateway_interactive_headroom=args.headroom,
                route_policy=args.route_policy,
                n_gateways=args.gateways,
                kill_shard_at=args.kill_shard_at,
            )
        )
    else:
        report = asyncio.run(
            drive_gateway(
                args.gateway,
                args.admin_key,
                n_interactive=args.interactive,
                n_rollout=args.rollout,
                duration_s=args.duration,
                load_profile=args.load_profile,
            )
        )
    text = json.dumps(report, indent=1)
    print(text)
    if args.output:
        from areal_tpu.utils import atomic_io

        atomic_io.atomic_write_text(args.output, text)
        print(f"wrote {args.output}")
    # non-null scoreboard or the run proved nothing
    if args.tier_ab:
        cmp_ = report["comparison"]
        ok = (
            cmp_["near_linear"]
            and cmp_["kill_zero_responseless"]
            and cmp_["survivors_absorbed"]
            and cmp_["kill_greedy_identical"]
        )
    elif args.autopilot_ab:
        cmp_ = report["comparison"]
        ok = (
            cmp_["autopilot_wins"]
            and cmp_["decisions_audited"]
            and cmp_["greedy_identical"]
        )
    elif args.ab:
        cmp_ = report["comparison"]
        ok = (
            cmp_["greedy_identical"]
            and cmp_["cache_aware_wins_prefill"]
            and all(
                arm["classes"][p]["ttft_p50_s"] is not None
                for arm in report["arms"].values()
                for p in PRIORITIES
            )
        )
    else:
        ok = all(
            report["classes"][p]["ttft_p50_s"] is not None for p in PRIORITIES
        )
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
