"""Many-client open-loop gateway goodput benchmark.

The standing scoreboard for ROADMAP item 3 (disaggregated, cache-aware
serving fleet): drive the OpenAI-compatible gateway with mixed
interactive/rollout priority traffic on per-request deadlines, at an
OPEN-LOOP arrival schedule (clients arrive on a clock, not when the
previous one finishes — so overload shows up as queueing/shedding, not as
a slower client), and report per class:

- p50/p99 TTFT (from the ``areal_timing`` extension the proxy stamps onto
  completions — the engine-side request-timeline breakdown)
- p50/p99 end-to-end latency
- goodput: tokens completed WITHIN deadline per second
- shed/429, deadline-reap, and error counts

as a JSON artifact (``--output``), so router changes (prefix-locality
routing, prefill/decode disaggregation) have a fixed number to move.

Usage:
    # self-contained local fleet (tiny model, CPU-safe) under chaos stalls:
    python -m areal_tpu.tools.bench_gateway --local --replicas 2 \
        --interactive 8 --rollout 8 --duration 20 -o report.json
    # against an existing gateway:
    python -m areal_tpu.tools.bench_gateway --gateway http://host:port \
        --admin-key KEY --interactive 64 --rollout 64 --duration 60
"""

from __future__ import annotations

import argparse
import asyncio
import json
import math
import time
from dataclasses import dataclass, field
from typing import Any

# the self-contained local fleet serves the toy char tokenizer — the bench
# measures serving latency, not tokenization; real deployments pass
# --gateway at a fleet whose proxies run the production tokenizer
from areal_tpu.infra.rpc.echo_engine import CharTokenizer  # noqa: F401
from areal_tpu.utils import logging as alog

logger = alog.getLogger("bench_gateway")

PRIORITIES = ("interactive", "rollout")


def _percentile(values: list[float], q: float) -> float | None:
    if not values:
        return None
    xs = sorted(values)
    idx = min(len(xs) - 1, max(0, math.ceil(q * len(xs)) - 1))
    return xs[idx]


@dataclass
class _ClassStats:
    sent: int = 0
    completed: int = 0
    # shed_429 counts 429 RESPONSES (a retrying client can collect several
    # before admission and shed_429 may exceed sent); shed_requests counts
    # requests that were shed at least once — the router-comparison ratio
    shed_429: int = 0
    shed_requests: int = 0
    deadline_reaped: int = 0
    errors: int = 0
    ttft_s: list[float] = field(default_factory=list)
    e2e_s: list[float] = field(default_factory=list)
    tokens: int = 0
    tokens_within_deadline: int = 0

    def report(self, duration_s: float) -> dict[str, Any]:
        return {
            "sent": self.sent,
            "completed": self.completed,
            "shed_429": self.shed_429,
            "shed_requests": self.shed_requests,
            "deadline_reaped": self.deadline_reaped,
            "errors": self.errors,
            "ttft_p50_s": _percentile(self.ttft_s, 0.50),
            "ttft_p99_s": _percentile(self.ttft_s, 0.99),
            "e2e_p50_s": _percentile(self.e2e_s, 0.50),
            "e2e_p99_s": _percentile(self.e2e_s, 0.99),
            "tokens": self.tokens,
            "tokens_within_deadline": self.tokens_within_deadline,
            "goodput_tok_s": (
                self.tokens_within_deadline / duration_s if duration_s > 0 else 0.0
            ),
        }


async def _one_client(
    http,
    gateway_url: str,
    admin_key: str,
    priority: str,
    deadline_s: float,
    max_completion_tokens: int,
    prompt: str,
    stats: _ClassStats,
) -> None:
    """One open-loop client: session -> one prioritized chat completion
    (honoring 429 Retry-After inside the deadline budget) -> end session.
    The session ends on EVERY exit path: an abandoned session burns one of
    the proxy's capacity units forever, and a bench that leaks capacity
    under sustained overload corrupts its own scoreboard (start_session
    eventually 429s and every later client counts as an error)."""
    stats.sent += 1
    t0 = time.monotonic()
    budget_end = t0 + deadline_s
    key = None
    try:
        admin = {"Authorization": f"Bearer {admin_key}"}
        async with http.post(
            f"{gateway_url}/rl/start_session",
            json={"task_id": f"bench-{priority}"},
            headers=admin,
        ) as r:
            if r.status != 200:
                stats.errors += 1
                return
            sess = await r.json(content_type=None)
        key = sess["api_key"]
        headers = {
            "Authorization": f"Bearer {key}",
            "x-areal-priority": priority,
            "x-areal-deadline": f"{time.time() + (budget_end - time.monotonic()):.6f}",
        }
        body = {
            "messages": [{"role": "user", "content": prompt}],
            "max_completion_tokens": max_completion_tokens,
            "model": "bench",
        }
        comp = None
        was_shed = False
        while True:
            async with http.post(
                f"{gateway_url}/v1/chat/completions", json=body, headers=headers
            ) as r:
                if r.status == 429:
                    stats.shed_429 += 1
                    if not was_shed:
                        was_shed = True
                        stats.shed_requests += 1
                    # floor: a foreign gateway's "Retry-After: 0" must not
                    # hot-spin the bench into amplifying the overload; the
                    # RFC 7231 HTTP-date form falls back to the default
                    # rather than misclassifying the shed as an error
                    try:
                        ra = float(r.headers.get("Retry-After", "0.5") or 0.5)
                    except ValueError:
                        ra = 0.5
                    ra = max(0.05, ra)
                    if time.monotonic() + ra >= budget_end:
                        return  # budget exhausted while shed
                    await asyncio.sleep(ra)
                    continue
                if r.status != 200:
                    stats.errors += 1
                    return
                comp = await r.json(content_type=None)
                break
        e2e = time.monotonic() - t0
        timing = comp.get("areal_timing") or {}
        usage = comp.get("usage") or {}
        n_tok = int(usage.get("completion_tokens") or 0)
        reaped = (
            timing.get("truncated_by") == "deadline"
            or timing.get("stop_reason") == "deadline"
        )
        stats.completed += 1
        stats.e2e_s.append(e2e)
        stats.tokens += n_tok
        if n_tok > 0 and timing.get("ttft_s"):
            # zero-token completions (queued-expiry reaps) never emitted a
            # first token: their fallback ttft is the full wall latency and
            # would saturate p99 at the deadline — they are counted by
            # deadline_reaped, not by the TTFT distribution
            stats.ttft_s.append(float(timing["ttft_s"]))
        if reaped:
            stats.deadline_reaped += 1
        elif e2e <= deadline_s:
            stats.tokens_within_deadline += n_tok
    except Exception as e:  # noqa: BLE001 — one client's failure is a data
        # point (errors count), not a bench abort
        logger.debug(f"bench client failed: {e!r}")
        stats.errors += 1
    finally:
        if key is not None:
            try:
                async with http.post(
                    f"{gateway_url}/rl/end_session",
                    json={},
                    headers={"Authorization": f"Bearer {key}"},
                ):
                    pass
            except Exception as e:  # noqa: BLE001 — best-effort release
                logger.debug(f"end_session failed: {e!r}")


async def drive_gateway(
    gateway_url: str,
    admin_key: str,
    n_interactive: int,
    n_rollout: int,
    duration_s: float,
    interactive_deadline_s: float = 20.0,
    rollout_deadline_s: float = 30.0,
    interactive_tokens: int = 16,
    rollout_tokens: int = 128,
) -> dict[str, Any]:
    """Open-loop drive: each class's clients start on a fixed arrival
    schedule spread over ``duration_s``. Returns the report dict."""
    import aiohttp

    stats = {p: _ClassStats() for p in PRIORITIES}
    t_start = time.monotonic()

    async def schedule(priority, n, deadline_s, max_tokens, prompt):
        async with aiohttp.ClientSession() as http:
            tasks = []
            for i in range(n):
                target = t_start + (i * duration_s / max(1, n))
                delay = max(0.0, target - time.monotonic())
                if delay:
                    await asyncio.sleep(delay)
                tasks.append(
                    asyncio.ensure_future(
                        _one_client(
                            http,
                            gateway_url,
                            admin_key,
                            priority,
                            deadline_s,
                            max_tokens,
                            prompt,
                            stats[priority],
                        )
                    )
                )
            await asyncio.gather(*tasks)

    await asyncio.gather(
        schedule(
            "interactive",
            n_interactive,
            interactive_deadline_s,
            interactive_tokens,
            "ping?",
        ),
        schedule(
            "rollout",
            n_rollout,
            rollout_deadline_s,
            rollout_tokens,
            "solve this problem step by step please",
        ),
    )
    wall = time.monotonic() - t_start
    report = {
        "bench": "gateway_goodput",
        "gateway": gateway_url,
        "duration_s": round(wall, 3),
        "classes": {p: stats[p].report(wall) for p in PRIORITIES},
    }
    tot = _ClassStats()
    for s in stats.values():
        tot.sent += s.sent
        tot.completed += s.completed
        tot.shed_429 += s.shed_429
        tot.shed_requests += s.shed_requests
        tot.deadline_reaped += s.deadline_reaped
        tot.errors += s.errors
        tot.ttft_s += s.ttft_s
        tot.e2e_s += s.e2e_s
        tot.tokens += s.tokens
        tot.tokens_within_deadline += s.tokens_within_deadline
    report["totals"] = tot.report(wall)
    return report


# ---------------------------------------------------------------------------
# self-contained local fleet (tiny model; CPU-safe) under chaos stalls
# ---------------------------------------------------------------------------


class LocalFleet:
    """N engine replicas + rollout client + OpenAI proxy + gateway, all
    in-process — the 2-replica-under-chaos configuration the ISSUE's
    acceptance scenario names. ``start`` returns (gateway_url, admin_key)."""

    def __init__(
        self,
        n_replicas: int = 2,
        max_batch_size: int = 4,
        chaos_stall_prob: float = 0.3,
        chaos_stall_s: float = 0.1,
        max_queue_depth: int = 32,
        gateway_max_inflight: int = 0,
        gateway_interactive_headroom: int = 0,
        seed: int = 7,
    ):
        self.n_replicas = n_replicas
        self.max_batch_size = max_batch_size
        self.chaos_stall_prob = chaos_stall_prob
        self.chaos_stall_s = chaos_stall_s
        self.max_queue_depth = max_queue_depth
        self.gateway_max_inflight = gateway_max_inflight
        self.gateway_interactive_headroom = gateway_interactive_headroom
        self.seed = seed
        self.servers: list[Any] = []
        self.client = None
        self._proxy_runner = None
        self._gateway_runner = None
        self.admin_key = "bench-admin"
        self.gateway_url = ""
        self.proxy_url = ""

    async def astart(self) -> tuple[str, str]:
        import jax
        from aiohttp import web

        from areal_tpu.api.config import (
            ChaosConfig,
            InferenceEngineConfig,
            MeshConfig,
            RequestLifecycleConfig,
            ServerConfig,
        )
        from areal_tpu.inference.client import RemoteJaxEngine
        from areal_tpu.inference.decode_engine import DecodeEngine
        from areal_tpu.inference.server import ServerThread
        from areal_tpu.models import qwen
        from areal_tpu.openai.proxy.gateway import (
            GatewayState,
            create_gateway_app,
        )
        from areal_tpu.openai.proxy.rollout_server import (
            ProxyState,
            create_proxy_app,
        )
        from areal_tpu.robustness import FaultInjector
        from areal_tpu.utils.network import find_free_port

        from areal_tpu.tools.validate_installation import tiny_model_config

        tiny = tiny_model_config()
        params = qwen.init_params(jax.random.PRNGKey(0), tiny)
        for i in range(self.n_replicas):
            cfg = ServerConfig(
                max_batch_size=self.max_batch_size,
                max_seq_len=512,
                decode_steps_per_call=4,
                seed=self.seed + i,
                mesh=MeshConfig(data=-1, fsdp=1, seq=1, model=1),
                lifecycle=RequestLifecycleConfig(
                    max_queue_depth=self.max_queue_depth,
                    retry_after_s=0.1,
                    watchdog_s=60.0,
                ),
            )
            eng = DecodeEngine(cfg, params=params, model_cfg=tiny)
            eng.initialize()
            st = ServerThread(cfg, eng)
            st.start()
            self.servers.append(st)
        self.client = RemoteJaxEngine(
            InferenceEngineConfig(
                max_concurrent_rollouts=64,
                consumer_batch_size=8,
                max_head_offpolicyness=1000,
                request_timeout=120,
                request_retries=3,
            ),
            addresses=[s.address for s in self.servers],
        )
        self.client.initialize()
        if self.chaos_stall_prob > 0:
            self.client.install_fault_injector(
                FaultInjector(
                    ChaosConfig(
                        enabled=True,
                        seed=self.seed,
                        stall_prob=self.chaos_stall_prob,
                        stall_s=self.chaos_stall_s,
                        path_prefix="/generate",
                    )
                )
            )
        proxy_state = ProxyState(
            self.client,
            CharTokenizer(),
            admin_api_key=self.admin_key,
            capacity=4096,
        )
        self._proxy_runner = web.AppRunner(create_proxy_app(proxy_state))
        await self._proxy_runner.setup()
        pport = find_free_port()
        await web.TCPSite(self._proxy_runner, "127.0.0.1", pport).start()
        self.proxy_url = f"http://127.0.0.1:{pport}"
        gw_state = GatewayState(
            [self.proxy_url],
            admin_api_key=self.admin_key,
            max_inflight=self.gateway_max_inflight,
            interactive_headroom=self.gateway_interactive_headroom,
            retry_after_s=0.2,
        )
        self._gateway_runner = web.AppRunner(create_gateway_app(gw_state))
        await self._gateway_runner.setup()
        gport = find_free_port()
        await web.TCPSite(self._gateway_runner, "127.0.0.1", gport).start()
        self.gateway_url = f"http://127.0.0.1:{gport}"
        return self.gateway_url, self.admin_key

    async def astop(self) -> None:
        from areal_tpu.inference.client import close_loop_sessions

        if self._gateway_runner is not None:
            await self._gateway_runner.cleanup()
        if self._proxy_runner is not None:
            await self._proxy_runner.cleanup()
        if self.client is not None:
            self.client.destroy()
        # the proxy drove agenerate on THIS loop: close its cached session
        # (destroy only reaches the client's executor-loop cache)
        await close_loop_sessions()
        for st in self.servers:
            st.stop()

    def engine_stats(self) -> dict[str, Any]:
        """Fleet-level engine counters folded into the report (deadline
        reaps and timeline health come from the engines themselves)."""
        out: dict[str, Any] = {"replicas": []}
        for st in self.servers:
            eng = st.engine
            out["replicas"].append(
                {
                    "address": st.address,
                    "generated_tokens": eng.stats["generated_tokens"],
                    "deadline_exceeded": eng.stats["deadline_exceeded"],
                    "timelines": eng.timeline.stats(),
                }
            )
        return out


async def run_local_bench(
    n_replicas: int = 2,
    n_interactive: int = 8,
    n_rollout: int = 8,
    duration_s: float = 15.0,
    **fleet_kw: Any,
) -> dict[str, Any]:
    fleet = LocalFleet(n_replicas=n_replicas, **fleet_kw)
    try:
        gateway_url, admin_key = await fleet.astart()
        report = await drive_gateway(
            gateway_url,
            admin_key,
            n_interactive=n_interactive,
            n_rollout=n_rollout,
            duration_s=duration_s,
        )
        report["fleet"] = fleet.engine_stats()
        return report
    finally:
        await fleet.astop()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--gateway", default="", help="existing gateway base url")
    p.add_argument("--admin-key", default="", help="gateway admin API key")
    p.add_argument(
        "--local",
        action="store_true",
        help="spin a self-contained local fleet (tiny model) to bench",
    )
    p.add_argument("--replicas", type=int, default=2)
    p.add_argument("--interactive", type=int, default=8)
    p.add_argument("--rollout", type=int, default=8)
    p.add_argument("--duration", type=float, default=15.0)
    p.add_argument("--stall-prob", type=float, default=0.3)
    p.add_argument("--stall-s", type=float, default=0.1)
    p.add_argument("--max-inflight", type=int, default=0)
    p.add_argument("--headroom", type=int, default=0)
    p.add_argument("-o", "--output", default="", help="JSON report path")
    args = p.parse_args(argv)

    if args.local or not args.gateway:
        report = asyncio.run(
            run_local_bench(
                n_replicas=args.replicas,
                n_interactive=args.interactive,
                n_rollout=args.rollout,
                duration_s=args.duration,
                chaos_stall_prob=args.stall_prob,
                chaos_stall_s=args.stall_s,
                gateway_max_inflight=args.max_inflight,
                gateway_interactive_headroom=args.headroom,
            )
        )
    else:
        report = asyncio.run(
            drive_gateway(
                args.gateway,
                args.admin_key,
                n_interactive=args.interactive,
                n_rollout=args.rollout,
                duration_s=args.duration,
            )
        )
    text = json.dumps(report, indent=1)
    print(text)
    if args.output:
        from areal_tpu.utils import atomic_io

        atomic_io.atomic_write_text(args.output, text)
        print(f"wrote {args.output}")
    # non-null scoreboard or the run proved nothing
    ok = all(
        report["classes"][p]["ttft_p50_s"] is not None for p in PRIORITIES
    )
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
