"""PPO-family algorithm layer: advantages, loss dispatch, update loop.

Behavioral parity with reference areal/trainer/ppo/actor.py (PPOActor:35-345,
grpo_loss_fn:357-520, prox approximation:520-683) and critic.py, re-plumbed
for this framework's alignment convention:

- Host-side data is **token-aligned** ([b, t] refers to token t; rollout
  logprobs, forward_batch outputs, values). ``compute_advantages`` converts
  per-token training keys to **label alignment** via roll(-1) exactly like
  the reference (actor.py:165-168, 236), because the train engine's model
  outputs logprobs/entropy at label positions.
- The proximal-logp log-linear approximation (docs/en/algorithms/prox_approx
  .md) is reformulated: the interpolation factor alpha depends only on
  per-token versions + the (host-known) current version, so it is computed
  host-side into a ``prox_alpha`` array — the in-jit loss then computes
  ``prox = old + alpha·(logp_theta − old)`` with no per-version recompiles.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from areal_tpu.api.config import MicroBatchSpec, PPOActorConfig, PPOCriticConfig
from areal_tpu.api.engine_api import TrainEngine
from areal_tpu.infra.staleness_manager import LAG_BUCKET_LABELS
from areal_tpu.observability import catalog as obs_catalog
from areal_tpu.ops import functional as F
from areal_tpu.utils import logging as alog, stats_tracker
from areal_tpu.utils.data import (
    Normalization,
    TensorDict,
    roll_to_label_alignment as _roll_back,
    split_padded_tensor_dict_into_mb_list,
)

logger = alog.getLogger("ppo")


def _lag_bucket_stats(
    version_lag: jax.Array, lmf: jax.Array, denom: jax.Array, stat: dict
) -> dict:
    """Staleness-conditioned loss diagnostics (jit-side; docs/observability
    .md "Learning-health observatory"): per-lag-bucket clip fraction,
    approx-KL, behave importance-weight mean + cap-hit tail mass, and token
    share, as masked reductions over the packed grid. All outputs are
    scalars, so they ride the engine's ONE step-fence device pull with the
    rest of the stats — zero new host syncs (the PR 11 PRF contract).

    Identity contract (tested): for any of clip_ratio / approx_kl, the
    token-share-weighted sum over buckets recomposes the batch-wide scalar
    exactly; behave stats recompose through ``behave_share``. Buckets
    partition the valid-token mask — unknown lags (< 0) clamp into "0".

    Every output here is normalized by the SAME ``denom`` — the
    microbatch's total valid tokens, which is also the engine's fold
    weight — so `_fold_weighted_stats` recombines them EXACTLY across a
    ``max_tokens_per_mb`` split (a weighted mean of ``sum_b/denom``
    quantities with weights == denom is the full-batch quantity). The
    documented per-bucket RATIOS (clip fraction of the bucket's tokens
    etc.) are quotients of these and are derived host-side AFTER the fold
    by `_finalize_lag_stats`; normalizing by bucket counts in-jit instead
    would make the fold weight (total tokens) disagree with the ratio's
    own denominator (bucket tokens) and bias every bucket stat whenever
    microbatches have different bucket mixes."""
    lag = jnp.clip(version_lag, 0, None)
    # keep in sync with staleness_manager.lag_bucket_index (edges 0/1/2/4+)
    bucket = jnp.where(
        lag >= 4, 3, jnp.where(lag >= 2, 2, jnp.where(lag >= 1, 1, 0))
    )
    clip_f = stat["clip_mask"].astype(jnp.float32)
    behave = "behave_mask" in stat
    if behave:
        behave_f = stat["behave_mask"].astype(jnp.float32)
    out: dict[str, jax.Array] = {}
    for i, label in enumerate(LAG_BUCKET_LABELS):
        bm = lmf * (bucket == i)
        out[f"lag_{label}/token_share"] = bm.sum() / denom
        out[f"lag_{label}/clip_frac"] = (clip_f * bm).sum() / denom
        out[f"lag_{label}/kl_frac"] = (stat["approx_kl"] * bm).sum() / denom
        if behave:
            bb = behave_f * bm  # uncapped tokens in this bucket
            out[f"lag_{label}/behave_frac"] = bb.sum() / denom
            out[f"lag_{label}/imp_weight_frac"] = (
                stat["behave_imp_weight"] * bb
            ).sum() / denom
            out[f"lag_{label}/behave_kl_frac"] = (
                stat["behave_approx_kl"] * bb
            ).sum() / denom
            # magnitude twin of the signed mean above: the signed one
            # recomposes the batch scalar; the abs one is the drift signal
            # the metrics/guard export (sign cancellation must not hide a
            # diverged bucket)
            out[f"lag_{label}/behave_abs_kl_frac"] = (
                jnp.abs(stat["behave_approx_kl"]) * bb
            ).sum() / denom
            out[f"lag_{label}/cap_frac"] = (bm - bb).sum() / denom
    return out


def _finalize_lag_stats(stats: dict[str, float]) -> dict[str, float]:
    """Host-side twin of `_lag_bucket_stats`: turn the fold-safe
    denom-normalized ``*_frac`` device stats into the documented
    per-bucket ratios (clip_ratio / approx_kl / behave_* / cap_hit_share).
    Runs AFTER the engine fold, so the ratios are exact even when a
    train_batch split into uneven microbatches — each quotient's numerator
    and denominator folded exactly. The batch-wide behave scalars
    (``behave_approx_kl``/``behave_imp_weight``) are behave-token-
    normalized in-jit while the engine folds by VALID tokens, so they
    carry the same split bias — rederived here from the bucket pieces
    (which partition the behave mask) so the identity closes on both
    sides. Internal ``*_frac`` keys are consumed and dropped; no-op for
    stats without the lag families."""
    if "lag_0/token_share" not in stats:
        return stats
    out = dict(stats)
    behave_total = sum(
        out.get(f"lag_{label}/behave_frac", 0.0)
        for label in LAG_BUCKET_LABELS
    )
    if behave_total > 0:
        out["behave_approx_kl"] = (
            sum(
                out.get(f"lag_{label}/behave_kl_frac", 0.0)
                for label in LAG_BUCKET_LABELS
            )
            / behave_total
        )
        out["behave_imp_weight"] = (
            sum(
                out.get(f"lag_{label}/imp_weight_frac", 0.0)
                for label in LAG_BUCKET_LABELS
            )
            / behave_total
        )
    for label in LAG_BUCKET_LABELS:
        share = out[f"lag_{label}/token_share"]
        d = share if share > 0 else 1.0
        out[f"lag_{label}/clip_ratio"] = (
            out.pop(f"lag_{label}/clip_frac", 0.0) / d
        )
        out[f"lag_{label}/approx_kl"] = (
            out.pop(f"lag_{label}/kl_frac", 0.0) / d
        )
        if f"lag_{label}/behave_frac" in out:
            bfrac = out.pop(f"lag_{label}/behave_frac")
            bd = bfrac if bfrac > 0 else 1.0
            out[f"lag_{label}/behave_imp_weight"] = (
                out.pop(f"lag_{label}/imp_weight_frac", 0.0) / bd
            )
            out[f"lag_{label}/behave_approx_kl"] = (
                out.pop(f"lag_{label}/behave_kl_frac", 0.0) / bd
            )
            out[f"lag_{label}/behave_abs_kl"] = (
                out.pop(f"lag_{label}/behave_abs_kl_frac", 0.0) / bd
            )
            out[f"lag_{label}/behave_share"] = bfrac / (
                behave_total if behave_total > 0 else 1.0
            )
            out[f"lag_{label}/cap_hit_share"] = (
                out.pop(f"lag_{label}/cap_frac", 0.0) / d
            )
    return out


def _per_sequence_stats(b: dict, lmf: jax.Array, stat: dict) -> dict:
    """Per-trajectory loss attribution through the packed-batch segment map
    (jit-side). ``seq_slot`` maps each grid cell to its grid-local sequence
    slot (-1 = padding); ``seq_slots`` is a host-shipped dummy whose SHAPE
    carries the static slot count, so the segment reduction needs no
    dynamic ``num_segments``. The array-valued outputs are pulled in the
    same step-fence device_get as the scalars; the engine maps them back to
    source sequences (``last_seq_stats``) for the trajectory lineage ring."""
    nseq = b["seq_slots"].shape[0]
    slot = b["seq_slot"].reshape(-1).astype(jnp.int32)
    slot = jnp.where(slot < 0, nseq, slot)  # padding -> trash slot, sliced off

    def seg(x: jax.Array) -> jax.Array:
        return jax.ops.segment_sum(
            x.reshape(-1), slot, num_segments=nseq + 1
        )[:nseq]

    out = {
        "seq__tokens": seg(lmf),
        "seq__clipped": seg(stat["clip_mask"].astype(jnp.float32)),
    }
    if "behave_mask" in stat:
        bf = stat["behave_mask"].astype(jnp.float32)
        out["seq__behave_tokens"] = seg(bf)
        # abs: per-trajectory drift magnitude (see _lag_bucket_stats note)
        out["seq__behave_kl_sum"] = seg(jnp.abs(stat["behave_approx_kl"]) * bf)
    return out


def grpo_loss_fn(outputs: dict, b: dict, cfg: PPOActorConfig):
    """Packed-grid policy loss (jit-side). ``outputs`` has label-aligned
    logprobs/entropy; ``b`` carries label-aligned per-token data prepared by
    compute_advantages. Mirrors reference grpo_loss_fn dispatch (actor.py
    :357-520): M2PO mask -> SAPO or PPO-clip/decoupled -> scalar stats."""
    logprobs = outputs["logprobs"]
    entropy = jax.lax.stop_gradient(outputs["entropy"])
    lm = (b["loss_mask"] > 0) & b["label_valid"]
    old_logp = b["old_logprobs"]

    # resolve proximal logprobs
    if "prox_logprobs" in b:
        prox_logp = b["prox_logprobs"]
    elif "prox_alpha" in b:  # loglinear approximation, no extra fwd pass
        prox_logp = old_logp + b["prox_alpha"] * (
            jax.lax.stop_gradient(logprobs) - old_logp
        )
    else:
        prox_logp = old_logp

    if cfg.use_m2po_loss:
        lm = F.m2po_loss_mask(old_logp, prox_logp, lm, cfg.m2po_tau)

    if cfg.use_sapo_loss:
        loss, stat = F.sapo_loss_fn(
            logprobs=logprobs,
            old_logprobs=old_logp,
            advantages=b["advantages"],
            loss_mask=lm,
            tau_pos=cfg.sapo_tau_pos,
            tau_neg=cfg.sapo_tau_neg,
            importance_sampling_level=cfg.imp_ratio_level,
        )
    else:
        loss, stat = F.ppo_actor_loss_fn(
            logprobs=logprobs,
            proximal_logprobs=prox_logp,
            old_logprobs=old_logp,
            advantages=b["advantages"],
            loss_mask=lm,
            eps_clip=cfg.eps_clip,
            eps_clip_higher=cfg.eps_clip_higher,
            c_clip=cfg.c_clip,
            behave_imp_weight_cap=cfg.behav_imp_weight_cap,
            importance_sampling_level=cfg.imp_ratio_level,
            behave_imp_weight_mode=(
                cfg.behave_imp_weight_mode if cfg.use_decoupled_loss else "disabled"
            ),
        )

    if cfg.entropy_coeff:
        ent_for_loss = outputs["entropy"]
        lmf = lm.astype(jnp.float32)
        loss = loss - cfg.entropy_coeff * (ent_for_loss * lmf).sum() / jnp.maximum(
            lmf.sum(), 1.0
        )

    # reduce per-token stat grids to scalars (reference pushes these through
    # stats_tracker with denominators; here the engine aggregates floats)
    lmf = lm.astype(jnp.float32)
    denom = jnp.maximum(lmf.sum(), 1.0)

    def tok_mean(x, mask=None):
        m = lmf if mask is None else mask.astype(jnp.float32)
        return (x * m).sum() / jnp.maximum(m.sum(), 1.0)

    stats = {
        "actor_loss": tok_mean(stat["loss"]),
        "importance_weight": tok_mean(stat["importance_weight"]),
        "approx_kl": tok_mean(stat["approx_kl"]),
        "entropy": tok_mean(entropy),
        "new_logp": tok_mean(jax.lax.stop_gradient(logprobs)),
        "old_logp": tok_mean(old_logp),
        "clip_ratio": (stat["clip_mask"].astype(jnp.float32)).sum() / denom,
        "dual_clip_ratio": (stat["dual_clip_mask"].astype(jnp.float32)).sum() / denom,
        "n_valid_tokens": lmf.sum(),
    }
    if "behave_imp_weight" in stat:
        stats["behave_imp_weight"] = tok_mean(
            stat["behave_imp_weight"], stat["behave_mask"]
        )
        stats["behave_approx_kl"] = tok_mean(
            stat["behave_approx_kl"], stat["behave_mask"]
        )
        stats["unclipped_behave_ratio"] = (
            stat["behave_mask"].astype(jnp.float32).sum() / denom
        )
    if "sapo_soft_gate" in stat:
        stats["sapo_soft_gate"] = tok_mean(stat["sapo_soft_gate"])
    # learning-health observatory: staleness-conditioned stats + the
    # per-trajectory attribution the lineage ring joins on — both emitted
    # only when the batch carries the host-prepared keys (presence is
    # static at trace time, so absent keys compile to nothing)
    if "version_lag" in b:
        stats.update(_lag_bucket_stats(b["version_lag"], lmf, denom, stat))
    if "seq_slot" in b and "seq_slots" in b:
        stats.update(_per_sequence_stats(b, lmf, stat))
    return loss, stats


def _export_learning_health(
    all_stats: list[dict[str, float]], weights: list[float] | None = None
) -> None:
    """Fold one update's minibatch stats into the catalogued
    ``areal_train_lag_*{lag_bucket}`` metrics: gauges carry this step's
    token-weighted view (dashboard), counters accumulate token-weighted
    sums (the autopilot's windowable signal). Minibatches are weighted by
    their HOST loss weight (valid-token count — the same weight the
    engine folds stats by): the engine's folded ``n_valid_tokens`` is a
    weight-weighted MEAN of per-microbatch counts, which under-scales as
    a total whenever a batch splits into uneven microbatches. With the
    host weights, single-minibatch updates recompose the batch scalars
    exactly (the identity the tests pin down) and the counters track the
    true trained-token totals."""
    keep = [
        (s, w)
        for s, w in zip(
            all_stats,
            weights
            if weights is not None
            else [s.get("n_valid_tokens", 0.0) for s in all_stats],
        )
        if "lag_0/token_share" in s
    ]
    if not keep:
        return
    stats = [s for s, _ in keep]
    m = obs_catalog.learning_health_metrics()
    total_tokens = sum(w for _, w in keep) or 1.0
    for label in LAG_BUCKET_LABELS:
        tok = [
            w * s.get(f"lag_{label}/token_share", 0.0) for s, w in keep
        ]
        ntok = sum(tok)
        d = max(ntok, 1.0)
        clip = (
            sum(
                t * s.get(f"lag_{label}/clip_ratio", 0.0)
                for t, s in zip(tok, stats)
            )
            / d
        )
        akl = (
            sum(
                t * s.get(f"lag_{label}/approx_kl", 0.0)
                for t, s in zip(tok, stats)
            )
            / d
        )
        m.token_share.labels(lag_bucket=label).set(ntok / total_tokens)
        m.clip_ratio.labels(lag_bucket=label).set(clip)
        m.approx_kl.labels(lag_bucket=label).set(akl)
        m.tokens_total.labels(lag_bucket=label).inc(ntok)
        m.clipped_total.labels(lag_bucket=label).inc(clip * ntok)
        if any(f"lag_{label}/behave_approx_kl" in s for s in stats):
            cap = (
                sum(
                    t * s.get(f"lag_{label}/cap_hit_share", 0.0)
                    for t, s in zip(tok, stats)
                )
                / d
            )
            btok = [
                t * (1.0 - s.get(f"lag_{label}/cap_hit_share", 0.0))
                for t, s in zip(tok, stats)
            ]
            nb = max(sum(btok), 1.0)
            bkl = (
                sum(
                    bt * s.get(f"lag_{label}/behave_abs_kl", 0.0)
                    for bt, s in zip(btok, stats)
                )
                / nb
            )
            biw = (
                sum(
                    bt * s.get(f"lag_{label}/behave_imp_weight", 0.0)
                    for bt, s in zip(btok, stats)
                )
                / nb
            )
            m.cap_hit.labels(lag_bucket=label).set(cap)
            m.behave_kl.labels(lag_bucket=label).set(bkl)
            m.imp_weight.labels(lag_bucket=label).set(biw)
            m.capped_total.labels(lag_bucket=label).inc(cap * ntok)
            m.behave_kl_sum.labels(lag_bucket=label).inc(bkl * sum(btok))


def _accumulate_lineage(
    acc: dict[int, dict[str, float]],
    lineage_ids: np.ndarray,
    seq_stats: dict[str, np.ndarray],
) -> None:
    """Fold one minibatch's per-sequence loss attribution (the engine's
    ``last_seq_stats``, mapped back from the packed grids) onto lineage
    ids. A GRPO group's sequences share one lineage id, so this is also
    the group -> trajectory aggregation."""
    toks = seq_stats.get("seq__tokens")
    if toks is None:
        return
    clipped = seq_stats.get("seq__clipped")
    btok = seq_stats.get("seq__behave_tokens")
    bkl = seq_stats.get("seq__behave_kl_sum")
    for i, lid in enumerate(np.ravel(np.asarray(lineage_ids))):
        lid = int(lid)
        if lid < 0 or i >= len(toks):
            continue
        a = acc.setdefault(
            lid,
            {
                "tokens": 0.0,
                "clipped": 0.0,
                "behave_tokens": 0.0,
                "behave_kl_sum": 0.0,
            },
        )
        a["tokens"] += float(toks[i])
        if clipped is not None:
            a["clipped"] += float(clipped[i])
        if btok is not None:
            a["behave_tokens"] += float(btok[i])
        if bkl is not None:
            a["behave_kl_sum"] += float(bkl[i])


def _commit_lineage(acc: dict[int, dict[str, float]], version: int) -> None:
    """Join the update's per-trajectory loss stats onto the lineage ring
    (observability/lineage.py) — the train-step end of the
    generate -> journal -> consume -> update chain."""
    if not acc:
        return
    from areal_tpu.observability import lineage as lineage_mod

    ring = lineage_mod.get_lineage()
    for lid, a in acc.items():
        ring.record_train(
            lid,
            version=version,
            tokens=a["tokens"],
            clip_fraction=a["clipped"] / max(a["tokens"], 1.0),
            behave_kl=(
                a["behave_kl_sum"] / max(a["behave_tokens"], 1.0)
                if a["behave_tokens"]
                else None
            ),
        )


class PPOActor:
    """Algorithm logic over a TrainEngine (reference trainer/ppo/actor.py)."""

    def __init__(self, config: PPOActorConfig, engine: TrainEngine):
        self.config = config
        self.engine = engine
        # group_reward_norm: normalize the scalar task reward within each
        # GRPO sample group (reference group_reward_norm semantics)
        self.reward_norm = (
            Normalization(
                mean_level="group",
                std_level="group",
                group_size=config.group_size,
            )
            if config.group_reward_norm
            else None
        )
        self.adv_norm = (
            Normalization(
                mean_level=config.adv_norm.mean_level,
                std_level=config.adv_norm.std_level,
                group_size=config.adv_norm.group_size or config.group_size,
                mean_leave1out=config.adv_norm.mean_leave1out,
                std_unbiased=config.adv_norm.std_unbiased,
            )
            if config.adv_norm
            else None
        )
        # one loss closure for the engine's jit cache (id-stable across steps)
        cfg = config
        self._loss_fn = lambda outputs, b: grpo_loss_fn(outputs, b, cfg)

    # -- engine delegation -------------------------------------------------
    def __getattr__(self, name):
        return getattr(self.engine, name)

    def compute_logp(self, data: TensorDict) -> np.ndarray:
        """Token-aligned logprobs of ``input_ids`` under the current policy."""
        return self.engine.forward_batch(data, output_key="logprobs")

    def should_compute_prox_logp(self) -> bool:
        c = self.config
        if c.use_decoupled_loss:
            return c.prox_logp_mode in ("recompute", "metrics")
        return c.recompute_logprob

    # -- advantages --------------------------------------------------------
    def compute_advantages(self, data: TensorDict) -> TensorDict:
        """Reward shaping + KL-regularized rewards + masked GAE + adv norm
        (reference actor.py:128-235). Host-side numpy; converts per-token
        keys to label alignment at the end."""
        cfg = self.config
        data = dict(data)
        attn = np.asarray(data["attention_mask"], bool)
        B, L = attn.shape
        loss_mask_tok = np.asarray(data["loss_mask"], np.float32) * attn

        # 1. sequence rewards: overlong penalty -> bias/scale/clip -> norm
        reward_score = np.asarray(data["rewards"], np.float32).reshape(B)
        if cfg.overlong_reward_penalty:
            # anchor to the fixed generation cap (reference actor.py uses
            # gconfig.max_new_tokens); a batch-derived cap would make the
            # penalty a silent no-op (ADVICE r1)
            if cfg.max_response_length <= 0:
                raise ValueError(
                    "overlong_reward_penalty=True requires "
                    "max_response_length > 0 (set it to the generation cap)"
                )
            resp_lens = loss_mask_tok.sum(-1)
            reward_score = np.asarray(
                F.reward_overlong_penalty(
                    jnp.asarray(reward_score),
                    jnp.asarray(resp_lens),
                    overlong_tokens=cfg.overlong_tokens,
                    overlong_penalty_factor=cfg.overlong_penalty_factor,
                    max_response_length=cfg.max_response_length,
                )
            )
        reward_score = (reward_score + cfg.reward_bias) * cfg.reward_scaling
        reward_score = np.clip(reward_score, -cfg.reward_clip, cfg.reward_clip)
        if self.reward_norm is not None:
            reward_score = self.reward_norm(reward_score)

        # 2. label-align the mask and logprobs (reference roll(-1))
        loss_mask = _roll_back(loss_mask_tok)
        if cfg.mask_too_long_tokens and "seq_no_eos_mask" in data:
            loss_mask[np.asarray(data["seq_no_eos_mask"], bool)] = 0.0

        prox_tok = data.pop("prox_logp", None)
        if not cfg.use_decoupled_loss and cfg.recompute_logprob:
            if prox_tok is None:
                raise ValueError("recompute_logprob=True but prox_logp missing")
            old_logp = _roll_back(np.asarray(prox_tok, np.float32))
            prox = old_logp
        else:
            old_logp = _roll_back(np.asarray(data["logprobs"], np.float32))
            prox = _roll_back(np.asarray(prox_tok, np.float32)) if prox_tok is not None else None

        ref_tok = data.pop("ref_logp", None)
        ref_logp = (
            _roll_back(np.asarray(ref_tok, np.float32))
            if ref_tok is not None
            else np.zeros_like(old_logp)
        )
        old_logp = old_logp * loss_mask
        ref_logp = ref_logp * loss_mask

        # 3. KL-regularized token rewards; task reward lands on the last
        #    generated label position (reference :180-197)
        seqlens = attn.sum(-1).astype(np.int64)
        if "seq_no_eos_mask" in data:
            seq_no_eos = np.asarray(data["seq_no_eos_mask"], bool).reshape(B)
        else:
            seq_no_eos = seqlens == L
        kl = np.asarray(
            F.approx_kl(jnp.asarray(old_logp), jnp.asarray(ref_logp), cfg.kl_estimator)
        )
        rewards = -cfg.kl_ctl * kl
        kl_rewards = rewards.copy()
        bidx = np.arange(B)
        rewards[bidx, seqlens - 1] = 0.0
        last_label = np.clip(seqlens - 2, 0, None)
        if cfg.mask_no_eos_with_zero:
            rewards[bidx, last_label] += np.where(seq_no_eos, 0.0, reward_score)
        else:
            rewards[bidx, last_label] += reward_score

        # 4. masked GAE (values are token-aligned; zeros for pure GRPO)
        values = np.asarray(
            data.get("values", np.zeros_like(rewards)), np.float32
        ).reshape(B, L)
        advantages = np.zeros((B, L), np.float32)
        nextvalues = values[:, L - 1] * seq_no_eos
        lastgaelam = np.zeros(B, np.float32)
        for t in range(L - 2, -1, -1):
            delta = rewards[:, t] + cfg.gamma * nextvalues - values[:, t]
            newgaelam = delta + cfg.gamma * cfg.lam * lastgaelam
            m = loss_mask[:, t]
            nextvalues = nextvalues * (1 - m) + values[:, t] * m
            lastgaelam = lastgaelam * (1 - m) + newgaelam * m
            advantages[:, t] = lastgaelam
        data["returns"] = advantages + values

        if self.adv_norm is not None:
            advantages = self.adv_norm(advantages, loss_mask > 0)

        # 5. store label-aligned training keys
        data["advantages"] = advantages.astype(np.float32)
        data["kl_rewards"] = kl_rewards
        data["tot_rewards"] = rewards
        data["loss_mask"] = loss_mask
        data["old_logprobs"] = old_logp
        if prox is not None:
            data["prox_logprobs"] = prox * loss_mask
        elif cfg.use_decoupled_loss and cfg.prox_logp_mode == "loglinear":
            data["prox_alpha"] = self._prox_alpha(data, loss_mask)
        if "versions" in data:
            # per-token version lag (label-aligned, like every loss key):
            # lag = consuming policy version - token's tagged version; -1
            # marks untagged positions (prompt tokens — masked out of the
            # loss anyway, and clamped into bucket "0" by the jit-side
            # bucketing). Host-side like prox_alpha: the consuming version
            # is host knowledge, so no per-version recompiles.
            v_theta = int(self.engine.get_version())
            versions_lbl = _roll_back(np.asarray(data["versions"], np.int64))
            lag = np.where(versions_lbl >= 0, v_theta - versions_lbl, -1)
            data["version_lag"] = np.clip(lag, -1, 2**31 - 1).astype(np.int32)
        data.pop("logprobs", None)
        return data

    def _prox_alpha(self, data: TensorDict, loss_mask: np.ndarray) -> np.ndarray:
        """Per-token interpolation factor for the log-linear proximal
        approximation (reference actor.py:520-600): alpha = clip((v_prox −
        v_behave)/(v_theta − v_behave), 0, 1), generated tokens only."""
        versions = _roll_back(np.asarray(data["versions"], np.int64))
        v_theta = float(self.engine.get_version())
        v_prox = v_theta - 1.0
        v_behave = versions.astype(np.float32)
        diff = v_theta - v_behave
        generated = versions >= 0
        alpha = np.where(generated & (diff > 0), (v_prox - v_behave) / np.maximum(diff, 1e-9), 0.0)
        return (np.clip(alpha, 0.0, 1.0) * loss_mask).astype(np.float32)

    # -- update ------------------------------------------------------------
    def ppo_update(self, data: TensorDict) -> list[dict[str, float]]:
        cfg = self.config
        data = dict(data)
        reward_score = np.asarray(data.get("rewards", np.zeros(1)), np.float32)
        attn = np.asarray(data["attention_mask"], bool)
        seqlens = attn.sum(-1)
        lm = np.asarray(data["loss_mask"], np.float32)
        with stats_tracker.scope("ppo_actor"):
            tr = stats_tracker.get()
            tr.scalar(
                task_reward=float(reward_score.mean()),
                correct_ratio=float((reward_score > 0).mean()),
                seq_len=float(seqlens.mean()),
                prompt_len=float((attn.sum(-1) - lm.sum(-1)).mean()),
                no_eos_ratio=float(
                    np.asarray(data.get("seq_no_eos_mask", np.zeros(1))).mean()
                ),
                advantages=float(
                    (np.asarray(data["advantages"]) * lm).sum() / max(lm.sum(), 1)
                ),
                final_reward=float(np.asarray(data["tot_rewards"]).sum(-1).mean()),
            )

        for key in ("rewards", "tot_rewards", "kl_rewards", "returns"):
            data.pop(key, None)
        mb_list = split_padded_tensor_dict_into_mb_list(
            data, MicroBatchSpec(n_mbs=cfg.ppo_n_minibatches)
        )
        all_stats = []
        mb_weights = []
        consuming_version = int(self.engine.get_version())
        lineage_acc: dict[int, dict[str, float]] = {}
        for mb in mb_list.mbs:
            train_stat = _finalize_lag_stats(
                self.engine.train_batch(
                    mb,
                    loss_fn=self._loss_fn,
                    loss_weight_fn=lambda x: float(
                        (np.asarray(x["loss_mask"]) > 0).sum()
                    ),
                )
            )
            mb_weights.append(float((np.asarray(mb["loss_mask"]) > 0).sum()))
            seq_stats = getattr(self.engine, "last_seq_stats", None)
            if seq_stats and "lineage_id" in mb:
                _accumulate_lineage(
                    lineage_acc, np.asarray(mb["lineage_id"]), seq_stats
                )
            with stats_tracker.scope("ppo_actor"):
                stats_tracker.get().scalar(**train_stat)
            all_stats.append(train_stat)
        _export_learning_health(all_stats, mb_weights)
        _commit_lineage(lineage_acc, consuming_version)
        return all_stats


def critic_loss_fn(outputs: dict, b: dict, cfg: PPOCriticConfig):
    lm = (b["loss_mask"] > 0) & b["label_valid"]
    loss, stat = F.ppo_critic_loss_fn(
        value=outputs["values"],
        old_value=b["old_values"],
        target_value=b["target_values"],
        loss_mask=lm,
        value_eps_clip=cfg.eps_clip,
    )
    lmf = lm.astype(jnp.float32)
    denom = jnp.maximum(lmf.sum(), 1.0)
    return loss, {
        "critic_loss": (stat["loss"] * lmf).sum() / denom,
        "value_clip_ratio": stat["clip_mask"].astype(jnp.float32).sum() / denom,
    }


class PPOCritic:
    """Value-function trainer (reference trainer/ppo/critic.py)."""

    def __init__(self, config: PPOCriticConfig, engine: TrainEngine):
        self.config = config
        self.engine = engine
        cfg = config
        self._loss_fn = lambda outputs, b: critic_loss_fn(outputs, b, cfg)

    def __getattr__(self, name):
        return getattr(self.engine, name)

    def compute_values(self, data: TensorDict) -> np.ndarray:
        """Token-aligned values: out[b, t] = V(prefix incl. token t)."""
        return self.engine.forward_batch(data, output_key="values")

    def ppo_update(self, data: TensorDict) -> list[dict[str, float]]:
        data = dict(data)
        # label-aligned targets: value at position t predicts return from t
        data["old_values"] = np.asarray(data.pop("values"), np.float32)
        data["target_values"] = np.asarray(data.pop("returns"), np.float32)
        # version_lag/lineage_id are actor-loss diagnostics — dead weight
        # (and a pointless grid transfer for version_lag) in the critic
        for key in (
            "rewards",
            "tot_rewards",
            "kl_rewards",
            "versions",
            "version_lag",
            "lineage_id",
        ):
            data.pop(key, None)
        mb_list = split_padded_tensor_dict_into_mb_list(
            data, MicroBatchSpec(n_mbs=self.config.ppo_n_minibatches)
        )
        all_stats = []
        for mb in mb_list.mbs:
            train_stat = self.engine.train_batch(
                mb,
                loss_fn=self._loss_fn,
                loss_weight_fn=lambda x: float(
                    (np.asarray(x["loss_mask"]) > 0).sum()
                ),
            )
            with stats_tracker.scope("ppo_critic"):
                stats_tracker.get().scalar(**train_stat)
            all_stats.append(train_stat)
        return all_stats
