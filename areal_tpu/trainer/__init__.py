from areal_tpu.trainer.ppo import PPOActor, PPOCritic, grpo_loss_fn
from areal_tpu.trainer.rl_trainer import PPOTrainer
from areal_tpu.trainer.sft_trainer import SFTTrainer

__all__ = ["PPOActor", "PPOCritic", "grpo_loss_fn", "PPOTrainer", "SFTTrainer"]
