"""PPOTrainer: the user-facing RL training facade + step loop.

Behavioral parity with reference areal/trainer/rl_trainer.py (86-498): build
actor/critic/ref engines and the rollout client, then per global step run
    prepare_batch -> [values] -> [recompute logp] -> [ref logp]
    -> compute_advantages -> ppo_update (+critic)
    -> pause rollout -> update_weights -> set_version -> save -> recover-ckpt
    -> eval -> log -> resume
Async-vs-sync is one knob: ``config.rollout.max_head_offpolicyness`` (0 =
synchronous; the staleness manager then admits exactly one batch per
version — reference blog AReaL_v0_3 η semantics).
"""

from __future__ import annotations

import os
import signal
import threading
import time
from typing import Any, Callable

import numpy as np

from areal_tpu.api.config import PPOConfig
from areal_tpu.api.io_struct import StepInfo, WeightUpdateMeta
from areal_tpu.engine.train_engine import JaxTrainEngine
from areal_tpu.infra.trajectory_journal import journal_from_config
from areal_tpu.infra.workflow_executor import RolloutInterrupted
from areal_tpu.observability import catalog as obs_catalog
from areal_tpu.observability import step_timeline
from areal_tpu.robustness.preemption import PreemptionHandler
from areal_tpu.trainer.ppo import PPOActor, PPOCritic
from areal_tpu.utils import logging as alog, perf_tracer, stats_tracker
from areal_tpu.utils.perf_tracer import Category
from areal_tpu.utils.data import StatefulDataLoader
from areal_tpu.utils.recover import RecoverHandler
from areal_tpu.utils.saver import Evaluator, Saver
from areal_tpu.utils.stats_logger import StatsLogger

logger = alog.getLogger("rl_trainer")


def resolve_weight_update_wire(config) -> str:
    """``weight_update_wire`` policy: "auto" -> "q8" when the serving fleet
    is int8-quantized (half the wire bytes, bit-identical to server-side
    quantization), else "bf16". Validates eagerly so a typo fails at
    trainer init, not at the first mid-training update."""
    wire = getattr(config, "weight_update_wire", "auto") or "auto"
    if wire == "auto":
        server_cfg = getattr(config, "server", None)
        wire = (
            "q8"
            if server_cfg is not None
            and getattr(server_cfg, "quantization", "none") == "int8"
            else "bf16"
        )
    if wire not in ("bf16", "q8"):
        raise ValueError(
            f"weight_update_wire={wire!r}; valid: auto|bf16|q8 "
            "(int8 is a ServerConfig.quantization value, not a wire format)"
        )
    if wire == "q8":
        server_cfg = getattr(config, "server", None)
        if getattr(server_cfg, "quantization", "none") != "int8":
            raise ValueError(
                "weight_update_wire='q8' requires an int8-serving fleet "
                "(set server.quantization='int8') — servers reject q8-wire "
                "leaves otherwise, at the first mid-training update"
            )
    return wire


class PPOTrainer:
    def __init__(
        self,
        config: PPOConfig,
        train_dataset,
        valid_dataset=None,
        rollout=None,
        eval_rollout=None,
        tokenizer=None,
        actor_engine=None,
        critic_engine=None,
        ref_engine=None,
    ):
        self.config = config
        self.tokenizer = tokenizer

        # allocation-mode DSL is the single topology knob (reference
        # rl_trainer.py:91): resolve it into engine/server MeshConfigs first
        from areal_tpu.api.alloc_mode import apply_allocation_mode

        self.allocation_mode = apply_allocation_mode(config)
        if config.cluster.name_resolve.type != "memory":
            # the discovery backend must be live BEFORE any rollout client
            # resolves server addresses (reference NameResolveConfig wiring)
            from areal_tpu.utils import name_resolve

            name_resolve.reconfigure_from_config(config.cluster.name_resolve)

        self.train_dataloader = StatefulDataLoader(
            train_dataset,
            batch_size=config.train_dataset.batch_size,
            shuffle=config.train_dataset.shuffle,
            seed=config.seed,
            drop_last=config.train_dataset.drop_last,
        )
        self.valid_dataset = valid_dataset
        from areal_tpu.api.io_struct import FinetuneSpec

        self.ft_spec = FinetuneSpec(
            total_train_epochs=config.total_train_epochs,
            dataset_size=len(train_dataset),
            train_batch_size=config.train_dataset.batch_size,
        )

        # engines (injectable for tests / custom backends)
        config.actor.temperature = config.gconfig.temperature
        self.actor_engine = actor_engine or JaxTrainEngine(config.actor)
        if getattr(self.actor_engine, "params", 1) is None or actor_engine is None:
            self.actor_engine.initialize(self.ft_spec)
        self.actor = PPOActor(config.actor, self.actor_engine)

        self.critic = None
        if config.critic is not None:
            eng = critic_engine or JaxTrainEngine(config.critic, value_head=True)
            if critic_engine is None:
                eng.initialize(self.ft_spec)
            self.critic = PPOCritic(config.critic, eng)

        self.ref = None
        if config.ref is not None:
            eng = ref_engine or JaxTrainEngine(config.ref, need_optimizer=False)
            if ref_engine is None:
                eng.initialize(self.ft_spec)
            self.ref = PPOActor(config.actor, eng)

        # rollout client
        if rollout is None:
            from areal_tpu.inference.client import RemoteJaxEngine

            addrs = os.environ.get("AREAL_TPU_SERVER_ADDRS", "")
            rollout = RemoteJaxEngine(
                config.rollout, addresses=[a for a in addrs.split(",") if a]
            )
            rollout.initialize()
        self.rollout = rollout
        # eval must NOT share the training executor: its results buffer
        # interleaves with async training trajectories (the reference builds
        # a separate eval_rollout client for the same reason)
        if eval_rollout is None and valid_dataset is not None:
            import dataclasses as _dc

            from areal_tpu.inference.client import RemoteJaxEngine

            eval_cfg = _dc.replace(
                config.rollout,
                max_head_offpolicyness=10_000_000,  # eval is version-agnostic
                max_concurrent_rollouts=config.rollout.max_concurrent_rollouts,
            )
            eval_rollout = RemoteJaxEngine(
                eval_cfg, addresses=list(self.rollout.addresses)
            )
            eval_rollout.initialize()
        self.eval_rollout = eval_rollout

        # weight update channel
        mode = config.weight_update_mode or config.actor.weight_update_mode
        update_dir = os.path.join(
            config.cluster.fileroot,
            config.experiment_name,
            config.trial_name,
            "update_weights",
        )
        wire = resolve_weight_update_wire(config)
        self.weight_update_meta = WeightUpdateMeta(
            type=mode, path=update_dir, with_version=True, wire_format=wire
        )
        self.actor_engine.connect_engine(self.rollout, self.weight_update_meta)

        # aux subsystems
        for c in (
            config.saver,
            config.checkpointer,
            config.evaluator,
            config.recover,
            config.stats_logger,
        ):
            c.experiment_name = c.experiment_name or config.experiment_name
            c.trial_name = c.trial_name or config.trial_name
            if hasattr(c, "fileroot"):
                c.fileroot = c.fileroot or config.cluster.fileroot
        perf_tracer.configure(config.perf_tracer, rank=0, role="trainer")
        self._obs = obs_catalog.trainer_metrics()
        # trainer goodput observatory (docs/observability.md "Trainer
        # observatory"): per-step phase timeline + utilization gauges, and
        # the XLA compile counters that make recompile storms visible
        self.step_recorder = step_timeline.StepTimelineRecorder()
        from areal_tpu.utils import compile_cache

        compile_cache.install_compile_counters()
        # on-demand device profiling: SIGUSR2 sets this flag (handler is
        # flag-only per the arealint SIG contract) and the NEXT step runs
        # under a jax.profiler trace, exactly like perf_tracer.profile_steps
        self._profile_requested = threading.Event()
        self.last_hbm_ledger: dict | None = None
        self.saver = Saver(config.saver, self.ft_spec)
        self.evaluator = Evaluator(config.evaluator, self.ft_spec)
        self.recover_handler = RecoverHandler(config.recover, self.ft_spec)
        self.stats_logger = StatsLogger(config.stats_logger, self.ft_spec)
        self.recover_info = self.recover_handler.load(
            self.actor_engine,
            saver=self.saver,
            evaluator=self.evaluator,
            dataloader=self.train_dataloader,
            inference_engine=self.rollout,
            weight_update_meta=self.weight_update_meta,
        )

        # durable trajectory journal (infra/trajectory_journal.py):
        # accepted-but-unconsumed rollouts survive trainer death; on a
        # recovered start the in-bound entries replay into the batch queue
        # instead of being re-generated
        self.journal = journal_from_config(
            config.rollout.journal,
            fileroot=config.cluster.fileroot,
            experiment=config.experiment_name,
            trial=config.trial_name,
        )
        if self.journal is not None and hasattr(self.rollout, "attach_journal"):
            self.rollout.attach_journal(self.journal)
            if self.recover_info is not None and hasattr(
                self.rollout, "replay_from_journal"
            ):
                t_replay = time.monotonic()
                replayed, dropped = self.rollout.replay_from_journal(
                    config.rollout.max_head_offpolicyness
                )
                if replayed or dropped:
                    logger.info(
                        f"recovered {replayed} journaled trajectories "
                        f"({dropped} over-stale dropped) in "
                        f"{time.monotonic() - t_replay:.2f}s — rollout "
                        "regeneration saved"
                    )

        # journal GC lags one recover generation: segments consumed below
        # this version are durable inside a checkpoint load() can reach
        self._journal_gc_version = (
            self.recover_info.last_step_info.global_step + 1
            if self.recover_info is not None
            else 0
        )

        # goodput autopilot (areal_tpu/autopilot/, docs/autopilot.md):
        # trainer-side placement — the staleness controller actuates the
        # in-process StalenessManager directly while the replica knobs
        # ride POST /autopilot/knobs. Off by default; the static config
        # then behaves exactly as before.
        self.autopilot = None
        ap_cfg = getattr(config.rollout, "autopilot", None)
        if ap_cfg is not None and ap_cfg.enabled:
            from areal_tpu.autopilot import autopilot_from_config

            self.autopilot = autopilot_from_config(
                ap_cfg,
                lambda: list(getattr(self.rollout, "addresses", []) or []),
                staleness_manager=getattr(
                    getattr(self.rollout, "executor", None), "staleness", None
                ),
            )
            if self.autopilot is not None:
                self.autopilot.seed_setpoints(
                    max_queue_depth=config.server.lifecycle.max_queue_depth,
                    min_free_pages=config.server.lifecycle.min_free_pages,
                    radix_max_fraction=config.server.prefix_cache.max_fraction,
                )
                self.autopilot.start()
                logger.info(
                    "goodput autopilot started: "
                    f"{[c.name for c in self.autopilot.controllers]} "
                    f"(signals: {ap_cfg.metrics_addr or 'local registry'})"
                )
                if not ap_cfg.metrics_addr:
                    # the trainer registry carries bubble/span but NOT the
                    # remote fleet's serving tails — without metrics_addr
                    # the admission/cache controllers hold on absent
                    # signals (areal_autopilot_signal_hold_total counts it)
                    logger.warning(
                        "autopilot.metrics_addr is unset: serving-side "
                        "signals (queue-wait, shed, prefix-hit, HBM) are "
                        "only visible for in-process fleets — point it at "
                        "the controller telemetry /metrics for a remote "
                        "fleet (docs/autopilot.md)"
                    )

        # preemption tolerance (robustness/preemption.py): the SIGTERM
        # handler only sets an event; the step loop polls it at phase
        # boundaries and the executor's blocking waits abort on it
        self.preempted = False
        self.preemption: PreemptionHandler | None = None
        if config.preemption.enabled:
            self.preemption = PreemptionHandler(
                role="trainer",
                grace_s=config.preemption.grace_s,
                handle_sigusr1=config.preemption.handle_sigusr1,
            )
            if hasattr(self.rollout, "set_interrupt"):
                self.rollout.set_interrupt(self.preemption.requested)

    # -- preemption (robustness/preemption.py) -----------------------------
    def _preempt_requested(self) -> bool:
        return self.preemption is not None and self.preemption.requested.is_set()

    def _handle_preemption(self, last_completed: StepInfo | None) -> None:
        """Grace-window drain: stop rollout submissions, force an
        emergency (sync, durable) recover dump of the last COMPLETED step,
        seal the trajectory journal, and mark the trial preempted — the
        caller exits cleanly and the relauncher resumes from here."""
        assert self.preemption is not None
        self.preemption.note_draining()
        t0 = time.monotonic()
        self.rollout.pause()
        if last_completed is not None:
            try:
                self.recover_handler.dump_emergency(
                    self.actor_engine,
                    last_completed,
                    saver=self.saver,
                    evaluator=self.evaluator,
                    dataloader=self.train_dataloader,
                    tokenizer=self.tokenizer,
                )
            except Exception:  # noqa: BLE001 — an older durable generation
                # (plus the journal) still recovers the trial; dying inside
                # the grace window with no exit is the one unacceptable path
                logger.exception("emergency recover dump failed")
        if self.journal is not None:
            self.journal.seal_active()
        self._dump_lineage("preempt")
        self.preemption.note_drained(time.monotonic() - t0)
        self.preempted = True
        logger.warning(
            "trainer preempted: emergency state durable, rollout drained — "
            "exiting the step loop cleanly"
        )

    def _on_profile_signal(self, signum, frame) -> None:
        # flag-only (arealint SIG family): the step loop does the work
        self._profile_requested.set()

    def _dump_lineage(self, reason: str) -> None:
        """Persist the trajectory-lineage ring next to the flight-recorder
        dumps (docs/observability.md "Learning-health observatory"):
        tools/postmortem.py merges both into one incident trace, joining
        generate -> journal -> consume -> update by trace id."""
        from areal_tpu.observability import lineage as lineage_mod

        ring = lineage_mod.get_lineage()
        if not ring.recent(1):
            return  # nothing recorded (e.g. SFT-style runs): no dump file
        try:
            ring.dump(lineage_mod.default_dump_path(reason), reason)
        except OSError:
            logger.exception("trajectory lineage dump failed")

    # -- step loop --------------------------------------------------------
    # arealint: hot-path — the RL step loop: every statement here runs once
    # per global step, so PRF flags any blocking device read added to it
    def train(
        self,
        workflow: Any = None,
        eval_workflow: Any = None,
        dynamic_filter_fn: Callable | None = None,
    ) -> None:
        config = self.config
        start_step = (
            self.recover_info.last_step_info.next().global_step
            if self.recover_info is not None
            else 0
        )
        steps_per_epoch = len(self.train_dataloader)
        max_steps = config.total_train_epochs * steps_per_epoch
        if config.total_train_steps is not None:
            max_steps = min(max_steps, config.total_train_steps)
        if self.preemption is not None:
            self.preemption.install()
        try:
            # docs/observability.md "On-demand device profiling": SIGUSR2
            # profiles the next step without restarting the trial
            signal.signal(signal.SIGUSR2, self._on_profile_signal)
        except ValueError:
            logger.debug("SIGUSR2 profile trigger unavailable off the main thread")
        last_completed: StepInfo | None = (
            self.recover_info.last_step_info
            if self.recover_info is not None
            else None
        )

        for global_step in range(start_step, max_steps):
            if self._preempt_requested():
                self._handle_preemption(last_completed)
                return
            epoch = global_step // steps_per_epoch
            step = global_step % steps_per_epoch
            t_step = time.monotonic()
            # detailed device profile at requested steps (perf_tracer
            # .profile_steps — reference knob; XLA profiler instead of
            # torch.profiler, traces viewable in TensorBoard/XProf), or on
            # demand via SIGUSR2 (flag consumed here, one step per signal)
            profiling = bool(
                config.perf_tracer.profile_steps
                and global_step in config.perf_tracer.profile_steps
            )
            if self._profile_requested.is_set():
                self._profile_requested.clear()
                profiling = True
                logger.info(f"SIGUSR2: device-profiling step {global_step}")
            if profiling:
                perf_tracer.start_device_profile()

            tl = self.step_recorder.start(global_step)
            try:
                with tl.phase("rollout_wait"), perf_tracer.trace_scope(
                    "train.rollout", Category.COMPUTE, {"global_step": global_step}
                ):
                    batch = self.rollout.prepare_batch(
                        self.train_dataloader,
                        workflow=workflow,
                        should_accept_fn=dynamic_filter_fn,
                    )
            except RolloutInterrupted:
                # SIGTERM landed while waiting on rollout: abort this step
                # (the executor raised out of its blocking wait; accepted
                # work is journaled and replays after relaunch)
                self.step_recorder.abandon(tl)
                self._handle_preemption(last_completed)
                return
            if self._preempt_requested():
                # signal landed after the batch was ready — the remaining
                # phases (fwd/bwd, weight push) can outlast the grace
                # window, so abort the step; the popped batch replays from
                # the journal (its consumption marker post-dates the dump)
                self.step_recorder.abandon(tl)
                self._handle_preemption(last_completed)
                return

            # device fwd passes + the update: the engine attributes its own
            # host_prep / forward_backward / optimizer spans into ``tl``
            # through the step_timeline.engine_phase hook — the superseded
            # per-block stats_tracker timing keys are gone (docs note)
            n_extra_fwd = 0
            if self.critic is not None:
                with perf_tracer.trace_scope(
                    "train.compute_values", Category.COMPUTE
                ):
                    batch["values"] = self.critic.compute_values(batch)
                n_extra_fwd += 1

            if self.actor.should_compute_prox_logp():
                with perf_tracer.trace_scope(
                    "train.recompute_logp", Category.COMPUTE
                ):
                    batch["prox_logp"] = self.actor.compute_logp(batch)
                n_extra_fwd += 1

            if self.ref is not None:
                with perf_tracer.trace_scope(
                    "train.ref_logp", Category.COMPUTE
                ):
                    batch["ref_logp"] = self.ref.compute_logp(batch)
                n_extra_fwd += 1

            with tl.phase("host_prep"), perf_tracer.trace_scope(
                "train.compute_advantages", Category.COMPUTE
            ):
                adv_batch = self.actor.compute_advantages(batch)

            t_train = time.monotonic()
            with perf_tracer.trace_scope("train.ppo_update", Category.COMPUTE):
                self.actor.ppo_update(adv_batch)
            if self.critic is not None:
                self.critic.ppo_update(adv_batch)
            train_step_secs = time.monotonic() - t_train

            # §3.4 protocol: stop submissions, push weights, advance version
            with tl.phase("weight_publish"), perf_tracer.trace_scope(
                "train.update_weights", Category.COMM
            ):
                self.rollout.pause()
                t_update = time.monotonic()
                new_version = global_step + 1
                self.actor_engine.update_weights(self.weight_update_meta)
                self.actor_engine.set_version(new_version)
                if self.critic is not None:
                    self.critic.engine.set_version(new_version)
                self.rollout.set_version(new_version)
                if self.eval_rollout is not None:
                    self.eval_rollout.set_version(new_version)
            self._obs.update_seconds.observe(time.monotonic() - t_update)
            self._obs.version.set(new_version)

            t_save = time.monotonic()
            with tl.phase("ckpt_eval"), perf_tracer.trace_scope(
                "train.save", Category.IO
            ):
                self.saver.maybe_save(
                    self.actor_engine, epoch, step, global_step, self.tokenizer
                )
                # async recover dump: the step loop pauses only for the
                # host snapshot; Orbax writes (and the recover records
                # land) on a background thread. Emergency dumps on the
                # preemption path stay synchronous.
                dumped = self.recover_handler.dump(
                    self.actor_engine,
                    StepInfo(
                        epoch=epoch,
                        epoch_step=step,
                        global_step=global_step,
                        steps_per_epoch=steps_per_epoch,
                    ),
                    saver=self.saver,
                    evaluator=self.evaluator,
                    dataloader=self.train_dataloader,
                    tokenizer=self.tokenizer,
                    async_=True,
                )
                if dumped is not None and self.journal is not None:
                    # GC journal segments fully consumed by steps the
                    # PREVIOUS dump already covers (this dump's write may
                    # still be in flight; the lag keeps gc safe even if it
                    # fails and recovery falls back a generation)
                    self.journal.gc(self._journal_gc_version)
                    self._journal_gc_version = new_version

            save_secs = time.monotonic() - t_save
            # resume BEFORE eval: the default eval client is the training
            # rollout client, whose dispatcher skips submissions while paused
            # (a dedicated eval_rollout keeps the reference's order anyway)
            self.rollout.resume()
            t_eval = time.monotonic()
            with tl.phase("ckpt_eval"):
                self._maybe_evaluate(eval_workflow or workflow, epoch, global_step)
            eval_secs = time.monotonic() - t_eval

            bd = self._complete_step_timeline(tl, batch, n_extra_fwd)
            stats = stats_tracker.export_all()
            stats.update(self.rollout.export_stats())
            stats.update(step_timeline.breakdown_stat_keys(bd))
            # backward-compatible timing keys (the per-block ad-hoc
            # record_timing scopes these replace; the dropped keys —
            # critic_values/recompute_logp/ref_logp/compute_advantages/
            # critic_train_step — are folded into the phase taxonomy)
            stats["timing/rollout"] = bd["rollout_wait_s"]
            stats["timing/train_step"] = train_step_secs
            stats["timing/update_weights"] = bd["weight_publish_s"]
            stats["timing/save"] = save_secs
            stats["timing/eval"] = eval_secs
            if self.last_hbm_ledger is not None:
                stats["hbm/in_use_bytes"] = float(
                    self.last_hbm_ledger["bytes_in_use"]
                )
                if self.last_hbm_ledger["headroom_fraction"] is not None:
                    stats["hbm/headroom_fraction"] = float(
                        self.last_hbm_ledger["headroom_fraction"]
                    )
            stats["step_secs"] = time.monotonic() - t_step
            self._obs.step_seconds.observe(stats["step_secs"])
            stats["version"] = float(new_version)
            logger.info(
                f"step {global_step}: {step_timeline.format_phase_line(bd)}"
            )
            self.stats_logger.commit(epoch, step, global_step, stats)
            last_completed = StepInfo(
                epoch=epoch,
                epoch_step=step,
                global_step=global_step,
                steps_per_epoch=steps_per_epoch,
            )
            if profiling:
                trace_dir = perf_tracer.stop_device_profile()
                if trace_dir:
                    logger.info(f"device profile captured: {trace_dir}")
            perf_tracer.save(step=global_step)

    def _complete_step_timeline(self, tl, batch, n_extra_fwd: int) -> dict:
        """Close the step's phase timeline (shared helper: utilization
        inputs + HBM ledger refresh — step_timeline.complete_trainer_step)."""
        bd, ledger = step_timeline.complete_trainer_step(
            self.step_recorder,
            tl,
            self.actor_engine,
            self.config.telemetry,
            batch,
            n_extra_forwards=n_extra_fwd,
            remat=bool(
                getattr(self.config.actor, "gradient_checkpointing", False)
            ),
        )
        if ledger is not None:
            self.last_hbm_ledger = ledger
        return bd

    def _maybe_evaluate(self, eval_workflow, epoch: int, global_step: int) -> None:
        if self.valid_dataset is None or eval_workflow is None:
            return

        def run_eval():
            client = self.eval_rollout
            if client is None:
                return
            batch = client.rollout_batch(
                list(self.valid_dataset), workflow=eval_workflow
            )
            rewards = np.asarray(batch["rewards"], np.float32)
            with stats_tracker.scope("eval"):
                stats_tracker.get().scalar(
                    reward=float(rewards.mean()),
                    n_seqs=float(rewards.shape[0]),
                )

        self.evaluator.maybe_evaluate(epoch, global_step, run_eval)

    def close(self) -> None:
        try:
            # a periodic async recover dump may still be writing: join it
            # so close() means "everything durable" (preemption's emergency
            # dump already forces this)
            self.saver.wait_async()
            self.recover_handler.saver.wait_async()
        except RuntimeError:
            logger.exception("async checkpoint write failed during close")
        if self.journal is not None:
            self.journal.close()
        self._dump_lineage("close")
        if self.autopilot is not None:
            self.autopilot.stop()
        if self.preemption is not None:
            self.preemption.uninstall()
        self.stats_logger.close()
        self.rollout.destroy()
