"""SFT + reward-model trainers (reference areal/trainer/sft_trainer.py:1-410,
sft/lm_engine.py:1-96, rw/rw_engine.py:1-79).

- ``lm_loss_fn``: packed cross-entropy over loss-masked labels (label-aligned
  inside the grid, so the host pre-rolls loss_mask like the PPO path).
- ``rw_loss_fn``: Bradley-Terry pairwise loss. Sequences arrive interleaved
  (chosen, rejected); the score is the value head at each sequence's last
  token. Pair grouping survives grid packing via per-token ``rw_pair_id``
  arrays + an in-jit ``segment_sum`` (static segment count = grid size), the
  shape-static TPU replacement for the reference's python pair indexing.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

from areal_tpu.api.config import MicroBatchSpec, SFTConfig
from areal_tpu.api.io_struct import FinetuneSpec, StepInfo
from areal_tpu.engine.train_engine import JaxTrainEngine
from areal_tpu.observability import step_timeline
from areal_tpu.utils import logging as alog, stats_tracker
from areal_tpu.utils.data import (
    StatefulDataLoader,
    pad_sequences_to_tensors,
    roll_to_label_alignment as _roll_back,
    split_padded_tensor_dict_into_mb_list,
)
from areal_tpu.utils.recover import RecoverHandler
from areal_tpu.utils.saver import Evaluator, Saver
from areal_tpu.utils.stats_logger import StatsLogger

logger = alog.getLogger("sft")


def lm_loss_fn(outputs: dict, b: dict):
    """Per-token NLL over masked labels (reference lm_engine.py train_lm)."""
    lm = (b["label_valid"] & (b["loss_mask"] > 0)).astype(jnp.float32)
    denom = jnp.maximum(lm.sum(), 1.0)
    nll = -(outputs["logprobs"] * lm).sum() / denom
    return nll, {
        "ppl_loss": jax.lax.stop_gradient(nll),
        "n_valid_tokens": lm.sum(),
    }


def rw_loss_fn(outputs: dict, b: dict):
    """Bradley-Terry: -log σ(score_chosen − score_rejected)."""
    values = outputs["values"]  # [G, L]
    G, L = values.shape
    flat = (values * b["rw_last_mask"] * b["rw_sign"]).reshape(-1)
    pair_id = b["rw_pair_id"].reshape(-1).astype(jnp.int32)
    n_seg = G * L
    diff = jax.ops.segment_sum(flat, pair_id, num_segments=n_seg)
    # a full pair contributes exactly 2 last-token markers
    marks = jax.ops.segment_sum(
        b["rw_last_mask"].reshape(-1), pair_id, num_segments=n_seg
    )
    valid = (marks >= 2.0).astype(jnp.float32)
    n_pairs = jnp.maximum(valid.sum(), 1.0)
    loss = -(jax.nn.log_sigmoid(diff) * valid).sum() / n_pairs
    acc = ((diff > 0).astype(jnp.float32) * valid).sum() / n_pairs
    return loss, {
        "rw_loss": jax.lax.stop_gradient(loss),
        "rw_acc": acc,
        "n_pairs": n_pairs,  # weight for cross-microbatch aggregation
    }


class LMEngine:
    """SFT update logic over a TrainEngine (reference sft/lm_engine.py)."""

    def __init__(self, engine, mb_spec: MicroBatchSpec | None = None):
        self.engine = engine
        self.mb_spec = mb_spec or MicroBatchSpec()

    def train_lm(self, data) -> dict[str, float]:
        data = dict(data)
        data["loss_mask"] = _roll_back(
            np.asarray(data["loss_mask"], np.float32)
            * np.asarray(data["attention_mask"], np.float32)
        )
        stats = self.engine.train_batch(
            data,
            loss_fn=lm_loss_fn,
            loss_weight_fn=lambda x: float((np.asarray(x["loss_mask"]) > 0).sum()),
        )
        return stats

    def eval_lm(self, data) -> dict[str, float]:
        data = dict(data)
        data["loss_mask"] = _roll_back(
            np.asarray(data["loss_mask"], np.float32)
            * np.asarray(data["attention_mask"], np.float32)
        )
        return self.engine.eval_batch(
            data,
            loss_fn=lm_loss_fn,
            loss_weight_fn=lambda x: float((np.asarray(x["loss_mask"]) > 0).sum()),
        )


class RWEngine:
    """Bradley-Terry reward-model updates (reference rw/rw_engine.py). The
    engine must be built with ``value_head=True``; batches interleave
    (chosen, rejected) rows."""

    def __init__(self, engine, mb_spec: MicroBatchSpec | None = None):
        import dataclasses

        self.engine = engine
        # never mutate a caller-shared spec; pairs must stay together
        self.mb_spec = dataclasses.replace(
            mb_spec or MicroBatchSpec(), granularity=2
        )
        # pair integrity is guaranteed by OUR granularity-2 split; the
        # engine's internal token-budget FFD re-split is pair-blind and
        # could strand a pair's halves in different grids (marks>=2 gate
        # would then silently drop them) — disable it per-call (never
        # mutate the caller's shared engine config; ADVICE r1)
        self._engine_mb_spec = MicroBatchSpec(max_tokens_per_mb=None)
        if self.mb_spec.max_tokens_per_mb is None:
            self.mb_spec = dataclasses.replace(
                self.mb_spec, max_tokens_per_mb=32768
            )

    def _prep(self, mb) -> dict:
        mb = dict(mb)
        attn = np.asarray(mb["attention_mask"], bool)
        B, L = attn.shape
        assert B % 2 == 0, "RW batches interleave chosen/rejected pairs"
        seqlens = attn.sum(-1)
        pair_id = np.broadcast_to((np.arange(B) // 2)[:, None], (B, L)).astype(np.int32)
        sign = np.broadcast_to(
            np.where(np.arange(B) % 2 == 0, 1.0, -1.0)[:, None], (B, L)
        ).astype(np.float32)
        last = np.zeros((B, L), np.float32)
        last[np.arange(B), seqlens - 1] = 1.0
        mb["rw_pair_id"] = pair_id * attn
        mb["rw_sign"] = sign * attn
        mb["rw_last_mask"] = last
        return mb

    def train_rw(self, data) -> list[dict[str, float]]:
        mb_list = split_padded_tensor_dict_into_mb_list(dict(data), self.mb_spec)
        out = []
        for mb in mb_list.mbs:
            stats = self.engine.train_batch(
                self._prep(mb),
                loss_fn=rw_loss_fn,
                loss_weight_fn=lambda x: float(len(np.asarray(x["rw_sign"]))) or 1.0,
                mb_spec=self._engine_mb_spec,
            )
            out.append(stats)
        return out


class SFTTrainer:
    """Supervised fine-tuning loop (reference trainer/sft_trainer.py). Dataset
    rows are pre-tokenized dicts {"input_ids": [...], "loss_mask": [...]}."""

    def __init__(
        self,
        config: SFTConfig,
        train_dataset,
        valid_dataset=None,
        tokenizer=None,
        engine=None,
    ):
        self.config = config
        self.tokenizer = tokenizer
        from areal_tpu.api.alloc_mode import apply_allocation_mode

        apply_allocation_mode(config)
        self.train_dataloader = StatefulDataLoader(
            train_dataset,
            batch_size=config.train_dataset.batch_size,
            shuffle=config.train_dataset.shuffle,
            seed=config.seed,
            drop_last=config.train_dataset.drop_last,
        )
        self.valid_dataset = valid_dataset
        self.ft_spec = FinetuneSpec(
            total_train_epochs=config.total_train_epochs,
            dataset_size=len(train_dataset),
            train_batch_size=config.train_dataset.batch_size,
        )
        self.engine = engine or JaxTrainEngine(
            config.model, value_head=self.value_head
        )
        if engine is None:
            self.engine.initialize(self.ft_spec)
        self.lm = LMEngine(self.engine, config.model.mb_spec)

        for c in (config.saver, config.checkpointer, config.evaluator, config.recover, config.stats_logger):
            c.experiment_name = c.experiment_name or config.experiment_name
            c.trial_name = c.trial_name or config.trial_name
            if hasattr(c, "fileroot"):
                c.fileroot = c.fileroot or config.cluster.fileroot
        self.saver = Saver(config.saver, self.ft_spec)
        self.evaluator = Evaluator(config.evaluator, self.ft_spec)
        self.recover_handler = RecoverHandler(config.recover, self.ft_spec)
        self.stats_logger = StatsLogger(config.stats_logger, self.ft_spec)
        # trainer goodput observatory: same step-phase contract as the RL
        # loop (rollout_wait stays 0 here — SFT has no async bubble)
        self.step_recorder = step_timeline.StepTimelineRecorder()
        self.last_hbm_ledger: dict | None = None
        from areal_tpu.utils import compile_cache

        compile_cache.install_compile_counters()
        self.recover_info = self.recover_handler.load(
            self.engine,
            saver=self.saver,
            evaluator=self.evaluator,
            dataloader=self.train_dataloader,
        )

    # subclass hooks (RWTrainer overrides): collate one dataloader batch
    # and run one optimizer step, returning the step's stats dict
    loss_key = "ppl_loss"
    value_head = False

    def _collate(self, rows) -> dict:
        return pad_sequences_to_tensors(
            [
                {
                    "input_ids": np.asarray(r["input_ids"], np.int32),
                    "loss_mask": np.asarray(r["loss_mask"], np.float32),
                }
                for r in rows
            ]
        )

    def _train_step(self, batch) -> dict:
        return self.lm.train_lm(batch)

    # arealint: hot-path — the SFT step loop: one pass per global step, so
    # PRF flags any blocking device read added to it
    def train(self) -> list[float]:
        config = self.config
        start_step = (
            self.recover_info.last_step_info.next().global_step
            if self.recover_info is not None
            else 0
        )
        steps_per_epoch = len(self.train_dataloader)
        max_steps = config.total_train_epochs * steps_per_epoch
        if config.total_train_steps is not None:
            max_steps = min(max_steps, config.total_train_steps)

        from areal_tpu.utils.data import cycle_dataloader

        gen = cycle_dataloader(self.train_dataloader)
        losses = []
        for global_step in range(start_step, max_steps):
            epoch = global_step // steps_per_epoch
            step = global_step % steps_per_epoch
            t0 = time.monotonic()
            tl = self.step_recorder.start(global_step)
            with tl.phase("host_prep"):
                rows = next(gen)
                batch = self._collate(rows)
            # the engine attributes its host_prep/forward_backward/
            # optimizer spans into ``tl`` via step_timeline.engine_phase
            stats = self._train_step(batch)
            self.engine.set_version(global_step + 1)
            losses.append(stats[self.loss_key])

            with tl.phase("ckpt_eval"):
                self.saver.maybe_save(
                    self.engine, epoch, step, global_step, self.tokenizer
                )
                self.recover_handler.dump(
                    self.engine,
                    StepInfo(
                        epoch=epoch,
                        epoch_step=step,
                        global_step=global_step,
                        steps_per_epoch=steps_per_epoch,
                    ),
                    saver=self.saver,
                    evaluator=self.evaluator,
                    dataloader=self.train_dataloader,
                    tokenizer=self.tokenizer,
                )
                if self.valid_dataset is not None:
                    self.evaluator.maybe_evaluate(
                        epoch, global_step, self._run_eval
                    )
            bd = self._complete_step_timeline(tl, batch)
            stats.update(step_timeline.breakdown_stat_keys(bd))
            if self.last_hbm_ledger is not None:
                stats["hbm/in_use_bytes"] = float(
                    self.last_hbm_ledger["bytes_in_use"]
                )
                if self.last_hbm_ledger["headroom_fraction"] is not None:
                    stats["hbm/headroom_fraction"] = float(
                        self.last_hbm_ledger["headroom_fraction"]
                    )
            stats["step_secs"] = time.monotonic() - t0
            stats.update(stats_tracker.export_all())
            self.stats_logger.commit(epoch, step, global_step, stats)
        return losses

    def _complete_step_timeline(self, tl, batch) -> dict:
        """Close the step timeline (shared helper — SFT has exactly one
        fwd/bwd pass per step, so no extra forwards)."""
        bd, ledger = step_timeline.complete_trainer_step(
            self.step_recorder,
            tl,
            self.engine,
            self.config.telemetry,
            batch,
            remat=bool(
                getattr(self.config.model, "gradient_checkpointing", False)
            ),
        )
        if ledger is not None:
            self.last_hbm_ledger = ledger
        return bd

    def _run_eval(self) -> None:
        bs = self.config.train_dataset.batch_size
        eval_dl = StatefulDataLoader(
            self.valid_dataset, batch_size=bs, shuffle=False, drop_last=False
        )
        loss_sum = tok_sum = 0.0
        for rows in eval_dl:
            batch = pad_sequences_to_tensors(
                [
                    {
                        "input_ids": np.asarray(r["input_ids"], np.int32),
                        "loss_mask": np.asarray(r["loss_mask"], np.float32),
                    }
                    for r in rows
                ]
            )
            stats = self.lm.eval_lm(batch)
            n = stats.get("n_valid_tokens", 1.0)
            loss_sum += stats["ppl_loss"] * n
            tok_sum += n
        with stats_tracker.scope("eval"):
            stats_tracker.get().scalar(ppl_loss=loss_sum / max(tok_sum, 1.0))

    def close(self) -> None:
        self.stats_logger.close()


class RWTrainer(SFTTrainer):
    """Reward-model training on the full SFTTrainer harness — saver,
    recover dumps, stats logging all inherited (reference rw training runs
    through the same trainer scaffolding). Dataset rows are
    {"chosen_ids", "rejected_ids"}; each step interleaves them so
    consecutive rows form Bradley-Terry pairs."""

    loss_key = "rw_loss"
    value_head = True

    def __init__(self, config, train_dataset, valid_dataset=None, **kw):
        assert valid_dataset is None, "RWTrainer has no eval loop yet"
        super().__init__(config, train_dataset, **kw)
        self.rw = RWEngine(self.engine, config.model.mb_spec)

    def _collate(self, rows) -> dict:
        return pad_sequences_to_tensors(
            [
                {
                    "input_ids": np.asarray(ids, np.int32),
                    "loss_mask": np.ones(len(ids), np.float32),
                }
                for item in rows
                for ids in (item["chosen_ids"], item["rejected_ids"])
            ]
        )

    def _train_step(self, batch) -> dict:
        stats_list = self.rw.train_rw(batch)
        # pair-count-weighted aggregate: logging only the last microbatch
        # would report a fraction of the step's pairs
        total = sum(float(s.get("n_pairs", 1.0)) for s in stats_list) or 1.0
        agg: dict[str, float] = {}
        for s in stats_list:
            w = float(s.get("n_pairs", 1.0)) / total
            for k, v in s.items():
                if isinstance(v, (int, float, np.floating)):
                    agg[k] = agg.get(k, 0.0) + float(v) * w
        agg["n_pairs"] = total
        return agg
