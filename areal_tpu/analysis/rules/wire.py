"""WIRE — wire-contract drift across the HTTP-coupled control plane.

The fleet's processes talk over a small HTTP protocol whose two sides
live in different files (often different processes owned by different
subsystems). Nothing type-checks the contract: a client posting a body
key no handler reads, a dashboard consuming a /statusz key no server
emits, or an error body returned with a success status all fail
*silently* — the review-hardening lists of PRs 4, 6, 8, 12 and 13 are
full of exactly these. The WIRE family checks both sides against the
extracted contract (analysis/wirecontract.py):

  WIRE001  client call to a path no server registers (dead endpoint or
           typo'd route)
  WIRE002  body-key drift: a client sends a JSON key no handler of the
           path reads, or omits a key every handler requires
           (subscript-accessed with no default)
  WIRE003  response-key drift: a consumer reads a key of a parsed
           response document that no handler of the path emits
           (``# arealint: wire-doc=<path>`` marks cross-function
           consumers like ReplicaSnapshot.from_statusz)
  WIRE004  status-code drift: an error-shaped response body returned
           with a success status (bare ``raise_for_status`` checks
           swallow it), or a client comparing against a status code no
           handler in the package ever returns
  WIRE005  ``x-areal-*`` header literal outside ``api/wire.py`` — the
           producer/consumer constants module WIRE005 exists to enforce

Like the dataflow families, unknown is SILENT: an unresolvable path,
a non-literal body, or an open handler schema (body/response escapes
into unresolvable code) never fires.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from areal_tpu.analysis import wirecontract as wc
from areal_tpu.analysis.core import (
    Finding,
    ProjectContext,
    SourceFile,
    make_key,
)

_HEADER_LITERAL_RE = re.compile(r"^x-areal-[a-z0-9-]+$", re.IGNORECASE)

# body keys that ride every areal JSON post via shared plumbing (none
# today; kept as the one extension point for envelope keys)
_ENVELOPE_KEYS: frozenset[str] = frozenset()


class WireContractChecker:
    FAMILY = "WIRE"
    RULES = {
        "WIRE001": "client call to a path no server registers",
        "WIRE002": "request body key drift between client and handler",
        "WIRE003": "response key consumed that no handler emits",
        "WIRE004": "status-code drift (swallowed error / dead status check)",
        "WIRE005": "x-areal-* header literal outside api/wire.py",
    }

    def check(self, sf: SourceFile, ctx: ProjectContext) -> Iterator[Finding]:
        contract = ctx.wire_for(sf)
        mod = contract.modules.get(sf.relpath) or wc.ModuleInfo(
            sf.relpath, sf.text, sf.tree
        )
        yield from self._check_header_literals(sf, ctx)
        yield from self._check_server_side(sf, mod)
        if contract.has_routes:
            yield from self._check_client_side(sf, mod, contract)
            yield from self._check_marked_docs(sf, mod, contract)

    # -- WIRE005: header literals ------------------------------------------
    def _check_header_literals(
        self, sf: SourceFile, ctx: ProjectContext
    ) -> Iterator[Finding]:
        if sf.relpath.endswith("api/wire.py"):
            return
        for node in ast.walk(sf.tree):
            if not (
                isinstance(node, ast.Constant) and isinstance(node.value, str)
            ):
                continue
            if not _HEADER_LITERAL_RE.match(node.value):
                continue
            yield Finding(
                rule="WIRE005",
                path=sf.relpath,
                line=node.lineno,
                message=(
                    f"header literal `{node.value}` bypasses the shared "
                    "constants module; import it from areal_tpu.api.wire "
                    "so producer and consumer cannot drift"
                ),
                key=make_key(
                    "WIRE005", sf.relpath, sf.scope_of(node), node.value.lower()
                ),
            )

    # -- WIRE004a: server-side swallowed errors ----------------------------
    def _check_server_side(
        self, sf: SourceFile, mod: wc.ModuleInfo
    ) -> Iterator[Finding]:
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            tail = wc.transport_callee_name(node) or ""
            if tail != "json_response":
                continue
            arg = node.args[0] if node.args else None
            lit = wc._dict_literal_keys(arg) if arg is not None else None
            if lit is None:
                continue
            keys, _ = lit
            error_shaped = "error" in keys or self._status_error_value(arg)
            if not error_shaped:
                continue
            status = 200
            for kw in node.keywords:
                if kw.arg == "status":
                    if isinstance(kw.value, ast.Constant) and isinstance(
                        kw.value.value, int
                    ):
                        status = kw.value.value
                    else:
                        status = -1  # dynamic: assume intentional
            if 200 <= status < 400:
                yield Finding(
                    rule="WIRE004",
                    path=sf.relpath,
                    line=node.lineno,
                    message=(
                        "error-shaped response body returned with a success "
                        "status: callers checking only "
                        "`raise_for_status()` treat this failure as success "
                        "— add `status=4xx/5xx`"
                    ),
                    key=make_key(
                        "WIRE004",
                        sf.relpath,
                        sf.scope_of(node),
                        "error_body_200",
                    ),
                )

    @staticmethod
    def _status_error_value(arg: ast.expr) -> bool:
        """dict literal carrying "status": "error"."""
        exprs = (
            [arg.body, arg.orelse] if isinstance(arg, ast.IfExp) else [arg]
        )
        for e in exprs:
            if not isinstance(e, ast.Dict):
                continue
            for k, v in zip(e.keys, e.values):
                if (
                    isinstance(k, ast.Constant)
                    and k.value == "status"
                    and isinstance(v, ast.Constant)
                    and v.value == "error"
                ):
                    return True
        return False

    # -- client side: WIRE001/002/003 + WIRE004b ---------------------------
    def _check_client_side(
        self, sf: SourceFile, mod: wc.ModuleInfo, contract: wc.WireContract
    ) -> Iterator[Finding]:
        all_statuses = contract.all_statuses()
        for fi in mod.funcs.values():
            if isinstance(fi.node, ast.Lambda):
                continue
            calls = list(wc.iter_client_calls(fi.node))
            for call in calls:
                handlers = contract.for_path(call.path)
                if not handlers:
                    yield Finding(
                        rule="WIRE001",
                        path=sf.relpath,
                        line=call.node.lineno,
                        message=(
                            f"client call to `{call.path}` but no server "
                            "in the package registers that route — dead "
                            "endpoint or typo'd path"
                        ),
                        key=make_key(
                            "WIRE001", sf.relpath, fi.qualname, call.path
                        ),
                    )
                    continue
                if call.body_keys is not None and not call.body_splat:
                    read, open_ = contract.body_reads(call.path)
                    if not open_:
                        for k in sorted(call.body_keys - read - _ENVELOPE_KEYS):
                            yield Finding(
                                rule="WIRE002",
                                path=sf.relpath,
                                line=call.node.lineno,
                                message=(
                                    f"body key `{k}` sent to `{call.path}` "
                                    "but no handler of that path reads it "
                                    "— silently dropped on the server"
                                ),
                                key=make_key(
                                    "WIRE002",
                                    sf.relpath,
                                    fi.qualname,
                                    f"{call.path}:{k}",
                                ),
                            )
                    required = contract.body_required(call.path)
                    for k in sorted(required - call.body_keys):
                        yield Finding(
                            rule="WIRE002",
                            path=sf.relpath,
                            line=call.node.lineno,
                            message=(
                                f"`{call.path}` handlers require body key "
                                f"`{k}` (subscript access, no default) but "
                                "this call omits it — the request 500s"
                            ),
                            key=make_key(
                                "WIRE002",
                                sf.relpath,
                                fi.qualname,
                                f"{call.path}:missing:{k}",
                            ),
                        )
                if call.resp_var is not None:
                    yield from self._check_doc_reads(
                        sf,
                        fi.qualname,
                        fi.node,
                        call.resp_var,
                        call.path,
                        contract,
                        start=call.node.lineno,
                    )
            # WIRE004b: status-literal comparisons against codes nothing
            # returns (only meaningful in functions that do wire traffic;
            # silent when any handler's status= is dynamic — the package
            # may then return any code)
            if calls and all_statuses is not None:
                yield from self._check_status_compares(
                    sf, fi.qualname, fi.node, all_statuses
                )

    def _check_status_compares(
        self, sf: SourceFile, qual: str, fn: ast.AST, statuses: set[int]
    ) -> Iterator[Finding]:
        for node in wc._own_nodes(fn):
            if not isinstance(node, ast.Compare) or len(node.ops) != 1:
                continue
            if not isinstance(node.ops[0], (ast.Eq, ast.NotEq)):
                continue
            sides = [node.left, node.comparators[0]]
            code = None
            is_status = False
            for s in sides:
                if isinstance(s, ast.Constant) and isinstance(s.value, int):
                    code = s.value
                elif isinstance(s, ast.Attribute) and s.attr in (
                    "status",
                    "status_code",
                ):
                    is_status = True
            if not is_status or code is None or code < 400:
                continue
            if code in statuses:
                continue
            yield Finding(
                rule="WIRE004",
                path=sf.relpath,
                line=node.lineno,
                message=(
                    f"status comparison against {code}, but no handler in "
                    "the package returns it — dead error-handling branch "
                    "(contract drift)"
                ),
                key=make_key(
                    "WIRE004", sf.relpath, qual, f"status:{code}"
                ),
            )

    # -- WIRE003 helpers ---------------------------------------------------
    def _check_doc_reads(
        self,
        sf: SourceFile,
        qual: str,
        fn: ast.AST,
        var: str,
        path: str,
        contract: wc.WireContract,
        start: int = 0,
    ) -> Iterator[Finding]:
        emits, open_ = contract.resp_emits(path)
        if open_ or not contract.for_path(path):
            return
        # only reads AFTER the binding and BEFORE the var's next rebind
        # belong to this response — a local dict reusing the name earlier
        # (or a later rebinding) is not the response document
        end = None
        for n in wc._own_nodes(fn):
            if (
                isinstance(n, ast.Assign)
                and len(n.targets) == 1
                and isinstance(n.targets[0], ast.Name)
                and n.targets[0].id == var
                and n.lineno > start
            ):
                end = n.lineno if end is None else min(end, n.lineno)
        seen: set[str] = set()
        for node in wc._own_nodes(fn):
            ln = getattr(node, "lineno", None)
            if ln is None or ln <= start or (end is not None and ln >= end):
                continue
            key = None
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "get"
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == var
                and node.args
            ):
                key = wc._const_key(node.args[0])
            elif (
                isinstance(node, ast.Subscript)
                and isinstance(node.value, ast.Name)
                and node.value.id == var
                and isinstance(node.ctx, ast.Load)
            ):
                key = wc._const_key(node.slice)
            if key is None or key in emits or key in seen:
                continue
            seen.add(key)
            yield Finding(
                rule="WIRE003",
                path=sf.relpath,
                line=node.lineno,
                message=(
                    f"reads key `{key}` of the `{path}` response, but no "
                    "handler of that path emits it — the consumer sees "
                    "an always-absent field"
                ),
                key=make_key(
                    "WIRE003", sf.relpath, qual, f"{path}:{key}"
                ),
            )

    def _check_marked_docs(
        self, sf: SourceFile, mod: wc.ModuleInfo, contract: wc.WireContract
    ) -> Iterator[Finding]:
        """``# arealint: wire-doc=<path>`` on (or directly above) a def:
        its first non-self/cls parameter is a parsed response document of
        that path."""
        for fi in mod.funcs.values():
            node = fi.node
            if isinstance(node, ast.Lambda):
                continue
            first = node.lineno
            if node.decorator_list:
                first = min(
                    first, min(d.lineno for d in node.decorator_list)
                )
            # decorator line .. def line (comments may sit between
            # decorators and the def), plus the contiguous comment
            # block directly above
            lines = list(range(first, node.lineno + 1))
            ln = first - 1
            while ln in mod.comments:
                lines.append(ln)
                ln -= 1
            path = param = None
            for line in lines:
                m = wc.WIRE_DOC_RE.search(mod.comments.get(line, ""))
                if m:
                    path, param = m.group(1), m.group(2)
                    break
            if path is None:
                continue
            params = [
                a.arg for a in node.args.args if a.arg not in ("self", "cls")
            ]
            if param is None:
                param = params[0] if params else None
            if param is None:
                continue
            yield from self._check_doc_reads(
                sf, fi.qualname, node, param, path, contract
            )
