"""LCK — lock & fence ordering in the threaded runtime.

Every long-lived component owns threads (decode loop, snapshot poller,
autopilot, fleet probe, supervisor) and a small set of
``threading.Lock``/``Condition``/``Event`` objects coordinating them.
The failure modes are classic and none of them raise: an A->B / B->A
acquisition-order cycle deadlocks only under the right interleaving, a
``Condition.wait`` outside a while-predicate loop drops wakeups on
spurious signals, a blocking call under a shared lock stalls every
other path that needs it (the decode loop included), and a state event
flipped outside its owning lock tears the check-then-act it guards.

  LCK001  inconsistent pairwise lock order: lock B acquired while A is
          held in one place and A while B is held in another (cycle in
          the class's acquisition-order graph, self-calls followed)
  LCK002  ``Condition.wait`` outside a ``while``-predicate loop —
          spurious wakeups and stolen predicates are real; ``if`` is
          not a retry
  LCK003  blocking call (HTTP transport, ``queue.get()`` without
          timeout, ``Event.wait()`` without timeout, ``urlopen``) while
          holding a lock that other methods of the class also take —
          every one of them stalls for the full wait
  LCK004  ``Event.set()``/``.clear()`` outside the lock that guards it
          at its other call sites (the hold/drain/stage state machines
          establish an owning lock; a bare flip tears their transitions)

Lock identity is constructor-resolved (``self._x = threading.Lock()``;
``Condition``/``RLock``/``Event`` tracked by kind) plus module-level
lock assignments; unknown receivers never fire.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Iterator

from areal_tpu.analysis import wirecontract as _wc
from areal_tpu.analysis.core import (
    Finding,
    ProjectContext,
    SourceFile,
    dotted_name,
    make_key,
)

_CTOR_KINDS = {
    "threading.Lock": "lock",
    "threading.RLock": "lock",
    "Lock": "lock",
    "RLock": "lock",
    "threading.Condition": "condition",
    "Condition": "condition",
    "threading.Event": "event",
    "Event": "event",
}

_QUEUEISH = ("queue", "_q", "backlog", "inbox")


def _self_attr(node: ast.AST) -> str | None:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


@dataclasses.dataclass
class _MethodFacts:
    """Per-method lock facts gathered in one pass."""

    name: str
    node: ast.AST
    # (acquired lock, locks already held, site node)
    acquisitions: list[tuple[str, frozenset, ast.AST]] = dataclasses.field(
        default_factory=list
    )
    # self-method calls: (callee name, locks held, site node)
    self_calls: list[tuple[str, frozenset, ast.AST]] = dataclasses.field(
        default_factory=list
    )
    # blocking sites: (description, locks held, site node)
    blocking: list[tuple[str, frozenset, ast.AST]] = dataclasses.field(
        default_factory=list
    )
    # event transitions: (event attr, op, locks held, site node)
    event_ops: list[tuple[str, str, frozenset, ast.AST]] = dataclasses.field(
        default_factory=list
    )
    # condition waits: (cond attr, inside-while?, site node)
    cond_waits: list[tuple[str, bool, ast.AST]] = dataclasses.field(
        default_factory=list
    )


class LockOrderChecker:
    FAMILY = "LCK"
    RULES = {
        "LCK001": "inconsistent pairwise lock acquisition order",
        "LCK002": "Condition.wait outside a while-predicate loop",
        "LCK003": "blocking call while holding a shared lock",
        "LCK004": "event/state transition outside its owning lock",
    }

    def check(self, sf: SourceFile, ctx: ProjectContext) -> Iterator[Finding]:
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ClassDef):
                yield from self._check_class(sf, node)
        yield from self._check_module_level(sf)

    # -- lock discovery ----------------------------------------------------
    @staticmethod
    def _attr_kinds(cls: ast.ClassDef) -> dict[str, str]:
        """self.<attr> -> "lock" | "condition" | "event" (ctor-resolved;
        attrs with mixed assignments keep the first kind seen)."""
        kinds: dict[str, str] = {}
        for node in ast.walk(cls):
            if not (
                isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)
            ):
                continue
            kind = _CTOR_KINDS.get(dotted_name(node.value.func) or "")
            if kind is None:
                continue
            for t in node.targets:
                attr = _self_attr(t)
                if attr:
                    kinds.setdefault(attr, kind)
        return kinds

    # -- per-method fact gathering ------------------------------------------
    def _gather(
        self, sf: SourceFile, meth: ast.FunctionDef, kinds: dict[str, str]
    ) -> _MethodFacts:
        facts = _MethodFacts(name=meth.name, node=meth)
        lockish = {
            a for a, k in kinds.items() if k in ("lock", "condition")
        }
        cond_attrs = {a for a, k in kinds.items() if k == "condition"}
        event_attrs = {a for a, k in kinds.items() if k == "event"}

        def walk(node: ast.AST, held: frozenset, in_while: bool) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    continue  # nested defs run on their own schedule
                child_held = held
                child_in_while = in_while or isinstance(node, ast.While)
                if isinstance(child, ast.withitem):
                    attr = _self_attr(child.context_expr)
                    if attr in lockish:
                        # in the single-statement `with self._a, self._b:`
                        # form the i-th item is acquired with the earlier
                        # items already held — record them, or the a->b
                        # edge is lost and LCK001 misses the idiomatic
                        # two-lock inversion
                        item_held = held
                        if isinstance(node, (ast.With, ast.AsyncWith)):
                            for prev in node.items:
                                if prev is child:
                                    break
                                pa = _self_attr(prev.context_expr)
                                if pa in lockish:
                                    item_held = item_held | {pa}
                        facts.acquisitions.append(
                            (attr, item_held, child.context_expr)
                        )
                if isinstance(node, (ast.With, ast.AsyncWith)) and child in node.body:
                    for item in node.items:
                        attr = _self_attr(item.context_expr)
                        if attr in lockish:
                            child_held = child_held | {attr}
                if isinstance(child, ast.Call):
                    self._gather_call(
                        child,
                        child_held,
                        child_in_while,
                        facts,
                        lockish,
                        cond_attrs,
                        event_attrs,
                    )
                walk(child, child_held, child_in_while)

        walk(meth, frozenset(), False)
        return facts

    def _gather_call(
        self,
        call: ast.Call,
        held: frozenset,
        in_while: bool,
        facts: _MethodFacts,
        lockish: set[str],
        cond_attrs: set[str],
        event_attrs: set[str],
    ) -> None:
        f = call.func
        if isinstance(f, ast.Attribute):
            recv_attr = _self_attr(f.value)
            # self.method(...) call edges
            if isinstance(f.value, ast.Name) and f.value.id == "self":
                facts.self_calls.append((f.attr, held, call))
            # condition waits
            if f.attr == "wait" and recv_attr in cond_attrs:
                facts.cond_waits.append((recv_attr, in_while, call))
                return
            # event transitions
            if f.attr in ("set", "clear") and recv_attr in event_attrs:
                facts.event_ops.append((recv_attr, f.attr, held, call))
                return
            # blocking: Event.wait() with no timeout
            if (
                f.attr == "wait"
                and recv_attr in event_attrs
                and not call.args
                and not any(k.arg == "timeout" for k in call.keywords)
            ):
                facts.blocking.append(
                    (f"`self.{recv_attr}.wait()` without timeout", held, call)
                )
                return
            # blocking: queue.get() with no timeout
            if (
                f.attr == "get"
                and not call.args
                and not any(k.arg == "timeout" for k in call.keywords)
            ):
                base = f.value
                base_name = (
                    base.attr
                    if isinstance(base, ast.Attribute)
                    else (base.id if isinstance(base, ast.Name) else "")
                )
                if any(h in base_name.lower() for h in _QUEUEISH):
                    facts.blocking.append(
                        (f"`{base_name}.get()` without timeout", held, call)
                    )
                    return
        # blocking: HTTP transport shapes (urlopen / _post_json* with a
        # literal "/"-path arg — a bare `.get("key")` dict read is not one)
        name = None
        if isinstance(f, ast.Attribute):
            name = f.attr
        elif isinstance(f, ast.Name):
            name = f.id
        if name == "urlopen" or (
            _wc.is_transport_call(call) and _wc.call_path(call) is not None
        ):
            facts.blocking.append((f"HTTP call `{name}(...)`", held, call))

    # -- class analysis ------------------------------------------------------
    def _check_class(
        self, sf: SourceFile, cls: ast.ClassDef
    ) -> Iterator[Finding]:
        kinds = self._attr_kinds(cls)
        if not kinds:
            return
        methods = [
            n for n in cls.body if isinstance(n, ast.FunctionDef)
        ]
        facts = {
            m.name: self._gather(sf, m, kinds) for m in methods
        }

        # locks shared across methods (with-acquired in >= 2 methods)
        acquire_methods: dict[str, set[str]] = {}
        for name, fa in facts.items():
            for lock, _, _ in fa.acquisitions:
                acquire_methods.setdefault(lock, set()).add(name)
        shared_locks = {
            lk for lk, ms in acquire_methods.items() if len(ms) >= 2
        }

        # transitive closures over self-calls: locks a method may acquire
        # and blocking sites it may reach
        def closure(fa: _MethodFacts, seen: frozenset):
            acquires = {lk for lk, _, _ in fa.acquisitions}
            blocks = list(fa.blocking)
            for callee, _, _ in fa.self_calls:
                if callee in seen or callee not in facts:
                    continue
                sub_a, sub_b = closure(facts[callee], seen | {callee})
                acquires |= sub_a
                blocks.extend(sub_b)
            return acquires, blocks

        closures = {
            name: closure(fa, frozenset({name})) for name, fa in facts.items()
        }

        # -- LCK001: acquisition-order graph + pairwise cycles ------------
        edges: dict[tuple[str, str], tuple[ast.AST, str]] = {}
        for name, fa in facts.items():
            for lock, held, site in fa.acquisitions:
                for h in held:
                    if h != lock:
                        edges.setdefault((h, lock), (site, name))
            for callee, held, site in fa.self_calls:
                if not held or callee not in facts:
                    continue
                callee_acquires = closures[callee][0]
                for h in held:
                    for lk in callee_acquires:
                        if lk != h:
                            edges.setdefault((h, lk), (site, name))
        reported_pairs: set[frozenset] = set()
        for (a, b), (site, name) in sorted(
            edges.items(), key=lambda kv: kv[1][0].lineno
        ):
            if (b, a) not in edges:
                continue
            pair = frozenset((a, b))
            if pair in reported_pairs:
                continue
            reported_pairs.add(pair)
            other_site, other_name = edges[(b, a)]
            yield Finding(
                rule="LCK001",
                path=sf.relpath,
                line=site.lineno,
                message=(
                    f"inconsistent lock order on `{cls.name}`: "
                    f"`{a}` -> `{b}` here (in `{name}`) but "
                    f"`{b}` -> `{a}` at line {other_site.lineno} "
                    f"(in `{other_name}`) — two threads taking opposite "
                    "orders deadlock; pick one order and hoist"
                ),
                key=make_key(
                    "LCK001",
                    sf.relpath,
                    cls.name,
                    "<->".join(sorted((a, b))),
                ),
            )

        # -- LCK002: Condition.wait outside while ---------------------------
        for name, fa in facts.items():
            for attr, in_while, site in fa.cond_waits:
                if in_while:
                    continue
                yield Finding(
                    rule="LCK002",
                    path=sf.relpath,
                    line=site.lineno,
                    message=(
                        f"`self.{attr}.wait()` in `{cls.name}.{name}` is "
                        "not inside a `while`-predicate loop: spurious "
                        "wakeups and stolen predicates make a bare wait "
                        "(or `if`-guarded wait) return with the condition "
                        "still false"
                    ),
                    key=make_key(
                        "LCK002", sf.relpath, cls.name, f"{name}:{attr}"
                    ),
                )

        # -- LCK003: blocking while holding a shared lock -------------------
        seen_blk: set[str] = set()
        for name, fa in facts.items():
            sites = list(fa.blocking)
            # one-hop: self-calls made while holding a lock, into methods
            # whose closure blocks
            for callee, held, site in fa.self_calls:
                if not held or callee not in facts:
                    continue
                for what, _, _ in closures[callee][1]:
                    sites.append(
                        (f"{what} via `self.{callee}()`", held, site)
                    )
            for what, held, site in sites:
                locks = sorted(h for h in held if h in shared_locks)
                if not locks:
                    continue
                token = f"{name}:{locks[0]}:{site.lineno}"
                if token in seen_blk:
                    continue
                seen_blk.add(token)
                yield Finding(
                    rule="LCK003",
                    path=sf.relpath,
                    line=site.lineno,
                    message=(
                        f"{what} in `{cls.name}.{name}` while holding "
                        f"`{locks[0]}`, which other methods of the class "
                        "also take — every one of them stalls for the "
                        "full wait; move the call outside the lock"
                    ),
                    key=make_key(
                        "LCK003",
                        sf.relpath,
                        cls.name,
                        f"{name}:{locks[0]}",
                    ),
                )

        # -- LCK004: event transitions outside their owning lock ------------
        by_event: dict[str, list[tuple[str, str, frozenset, ast.AST]]] = {}
        for name, fa in facts.items():
            for attr, op, held, site in fa.event_ops:
                by_event.setdefault(attr, []).append((name, op, held, site))
        for attr, ops in by_event.items():
            # candidate owning locks: held at >= 2 transition sites AND at
            # a strict majority — a convention, not a coincidence
            lock_counts: dict[str, int] = {}
            for _, _, held, _ in ops:
                for h in held:
                    lock_counts[h] = lock_counts.get(h, 0) + 1
            for lock, n in sorted(lock_counts.items()):
                if n < 2 or n <= len(ops) - n:
                    continue
                for name, op, held, site in ops:
                    if lock in held:
                        continue
                    yield Finding(
                        rule="LCK004",
                        path=sf.relpath,
                        line=site.lineno,
                        message=(
                            f"`self.{attr}.{op}()` in `{cls.name}.{name}` "
                            f"outside `{lock}`, which guards this event's "
                            f"other {n} transition(s) — an unguarded flip "
                            "tears the state machine's check-then-act"
                        ),
                        key=make_key(
                            "LCK004",
                            sf.relpath,
                            cls.name,
                            f"{attr}:{name}",
                        ),
                    )

    # -- module-level functions with module-level locks ---------------------
    def _check_module_level(self, sf: SourceFile) -> Iterator[Finding]:
        """Minimal module-scope coverage: Condition.wait-outside-while on
        module-level Condition objects (class analysis covers the rest)."""
        kinds: dict[str, str] = {}
        for node in sf.tree.body:
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                kind = _CTOR_KINDS.get(dotted_name(node.value.func) or "")
                if kind is None:
                    continue
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        kinds.setdefault(t.id, kind)
        conds = {n for n, k in kinds.items() if k == "condition"}
        if not conds:
            return
        for node in ast.walk(sf.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "wait"
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id in conds
            ):
                continue
            cur = sf.parents.get(id(node))
            in_while = False
            while cur is not None and not isinstance(
                cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Module)
            ):
                if isinstance(cur, ast.While):
                    in_while = True
                    break
                cur = sf.parents.get(id(cur))
            if in_while:
                continue
            yield Finding(
                rule="LCK002",
                path=sf.relpath,
                line=node.lineno,
                message=(
                    f"`{node.func.value.id}.wait()` is not inside a "
                    "`while`-predicate loop: spurious wakeups return "
                    "with the condition still false"
                ),
                key=make_key(
                    "LCK002",
                    sf.relpath,
                    sf.scope_of(node),
                    node.func.value.id,
                ),
            )
