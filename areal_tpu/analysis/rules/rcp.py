"""RCP — recompile-risk call patterns.

XLA compiles one program per (function identity, static args, pytree
structure, shapes) key. Each of those key components has a classic
footgun that turns steady-state serving into a recompile storm — exactly
what the PR 9 ``areal_xla_compiles_total`` counter exists to catch at
runtime; this family catches the patterns statically:

  RCP001  un-cached jit construction on a repeating path: ``jax.jit(...)``
          evaluated inside a loop, or wrapping a lambda/local closure
          inside a hot-path function without a cache guard — every
          evaluation creates a fresh function identity, so the compile
          cache never hits
  RCP002  static-argument drift: a call into a jit with
          static_argnums/static_argnames passing a loop-varying value in
          a static position — one full recompile per distinct value
  RCP003  unstable pytree structure: a dict built with condition-
          dependent keys passed to a jit'd call — every key-set change
          is a new pytree structure and a new compile

The accepted shape for per-variant compiles is the repo's fn-cache
idiom: ``if key not in self._fn_cache: self._fn_cache[key] = jax.jit(...)``
with the variant dimensions in ``key`` — RCP001 recognizes both the
subscript-cache store and the ``not in`` guard and stays silent.
"""

from __future__ import annotations

import ast
from typing import Iterator

from areal_tpu.analysis.core import (
    Finding,
    ProjectContext,
    SourceFile,
    dotted_name,
    make_key,
)
from areal_tpu.analysis.dataflow import JitIndex

_JIT_NAMES = {"jax.jit", "jit", "pjit", "jax.pjit"}


class RecompileRiskChecker:
    FAMILY = "RCP"
    RULES = {
        "RCP001": "un-cached jit construction on a repeating path",
        "RCP002": "loop-varying value in a jit static argument position",
        "RCP003": "condition-dependent pytree structure fed to a jit'd call",
    }

    def check(self, sf: SourceFile, ctx: ProjectContext) -> Iterator[Finding]:
        graph = ctx.graph_for(sf)
        mod = graph.modules.get(sf.relpath)
        if mod is None:
            return
        hot = graph.hot_funcs_in(sf.relpath)
        jit_idx = mod.jit_index()

        yield from self._check_uncached_jit(sf, mod, hot)
        yield from self._check_static_drift(sf, mod, jit_idx)
        yield from self._check_pytree_drift(sf, mod, jit_idx)

    # -- RCP001 ------------------------------------------------------------
    def _check_uncached_jit(self, sf: SourceFile, mod, hot) -> Iterator[Finding]:
        for call in ast.walk(sf.tree):
            if not isinstance(call, ast.Call):
                continue
            if dotted_name(call.func) not in _JIT_NAMES:
                continue
            in_loop = False
            cached = False
            cur = sf.parents.get(id(call))
            node: ast.AST = call
            while cur is not None:
                if isinstance(cur, (ast.For, ast.AsyncFor, ast.While)):
                    in_loop = True
                if isinstance(cur, ast.Assign) and any(
                    isinstance(t, ast.Subscript) for t in cur.targets
                ):
                    cached = True  # stored into a keyed cache
                if isinstance(cur, ast.If) and self._is_cache_guard(cur.test):
                    cached = True
                if isinstance(
                    cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
                ):
                    break
                node, cur = cur, sf.parents.get(id(cur))
            encl = mod.enclosing_func(call)
            encl_hot = encl is not None and id(encl.node) in hot
            wraps_closure = bool(call.args) and isinstance(
                call.args[0], ast.Lambda
            )
            if cached:
                continue
            if in_loop or (encl_hot and wraps_closure):
                where = (
                    "inside a loop"
                    if in_loop
                    else f"in hot-path function `{encl.qualname}`"
                )
                yield Finding(
                    rule="RCP001",
                    path=sf.relpath,
                    line=call.lineno,
                    message=(
                        f"jax.jit evaluated {where} without a cache guard: "
                        "each evaluation is a fresh function identity, so "
                        "XLA recompiles every call — hoist it or key it in "
                        "a fn-cache (`if key not in cache: cache[key] = "
                        "jax.jit(...)`)"
                    ),
                    key=make_key(
                        "RCP001",
                        sf.relpath,
                        sf.scope_of(call),
                        "jit-in-loop" if in_loop else "jit-closure",
                    ),
                )

    @staticmethod
    def _is_cache_guard(test: ast.expr) -> bool:
        """`key not in <cache>` (possibly inside a BoolOp)."""
        for node in ast.walk(test):
            if isinstance(node, ast.Compare) and any(
                isinstance(op, ast.NotIn) for op in node.ops
            ):
                return True
        return False

    # -- RCP002 ------------------------------------------------------------
    def _check_static_drift(
        self, sf: SourceFile, mod, jit_idx: JitIndex
    ) -> Iterator[Finding]:
        for fi in mod.funcs.values():
            fn = fi.node
            if isinstance(fn, ast.Lambda):
                continue
            loop_vars = self._loop_vars(fn)
            if not loop_vars:
                continue
            for call in ast.walk(fn):
                if not isinstance(call, ast.Call):
                    continue
                site = jit_idx.site_for_callsite(call)
                if site is None or not (site.static_pos or site.static_names):
                    continue
                if not self._inside_loop(sf, call, fn):
                    continue
                for idx, arg in enumerate(call.args):
                    pname = (
                        site.params[idx] if idx < len(site.params) else None
                    )
                    if not site.is_static(idx, pname):
                        continue
                    names = {
                        n.id
                        for n in ast.walk(arg)
                        if isinstance(n, ast.Name)
                    }
                    hit = names & loop_vars
                    if hit:
                        var = sorted(hit)[0]
                        yield Finding(
                            rule="RCP002",
                            path=sf.relpath,
                            line=call.lineno,
                            message=(
                                f"static argument "
                                f"`{pname or f'arg{idx}'}` receives loop-"
                                f"varying `{var}`: one full XLA recompile "
                                "per distinct value — bucket it or make "
                                "the argument traced"
                            ),
                            key=make_key(
                                "RCP002",
                                sf.relpath,
                                sf.scope_of(call),
                                f"{pname or idx}:{var}",
                            ),
                        )

    @staticmethod
    def _loop_vars(fn: ast.AST) -> set[str]:
        out: set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, (ast.For, ast.AsyncFor)):
                for n in ast.walk(node.target):
                    if isinstance(n, ast.Name):
                        out.add(n.id)
        return out

    @staticmethod
    def _inside_loop(sf: SourceFile, node: ast.AST, stop: ast.AST) -> bool:
        cur = sf.parents.get(id(node))
        while cur is not None and cur is not stop:
            if isinstance(cur, (ast.For, ast.AsyncFor, ast.While)):
                return True
            cur = sf.parents.get(id(cur))
        return False

    # -- RCP003 ------------------------------------------------------------
    def _check_pytree_drift(
        self, sf: SourceFile, mod, jit_idx: JitIndex
    ) -> Iterator[Finding]:
        for fi in mod.funcs.values():
            fn = fi.node
            if isinstance(fn, ast.Lambda):
                continue
            # dicts whose key set depends on a condition: d[k] = v inside
            # an `if` after `d = {...}` / `d = dict(...)`
            dict_names: set[str] = set()
            for stmt in ast.walk(fn):
                if isinstance(stmt, ast.Assign) and isinstance(
                    stmt.value, (ast.Dict, ast.DictComp)
                ):
                    for t in stmt.targets:
                        if isinstance(t, ast.Name):
                            dict_names.add(t.id)
                elif (
                    isinstance(stmt, ast.Assign)
                    and isinstance(stmt.value, ast.Call)
                    and dotted_name(stmt.value.func) == "dict"
                ):
                    for t in stmt.targets:
                        if isinstance(t, ast.Name):
                            dict_names.add(t.id)
            if not dict_names:
                continue
            conditional: dict[str, int] = {}  # name -> line of the branch add
            for node in ast.walk(fn):
                if not isinstance(node, ast.If):
                    continue
                for stmt in ast.walk(node):
                    if (
                        isinstance(stmt, ast.Assign)
                        and len(stmt.targets) == 1
                        and isinstance(stmt.targets[0], ast.Subscript)
                        and isinstance(stmt.targets[0].value, ast.Name)
                        and stmt.targets[0].value.id in dict_names
                    ):
                        conditional.setdefault(
                            stmt.targets[0].value.id, stmt.lineno
                        )
            if not conditional:
                continue
            for call in ast.walk(fn):
                if not isinstance(call, ast.Call):
                    continue
                if jit_idx.site_for_callsite(call) is None:
                    continue
                for arg in call.args:
                    if (
                        isinstance(arg, ast.Name)
                        and arg.id in conditional
                    ):
                        yield Finding(
                            rule="RCP003",
                            path=sf.relpath,
                            line=call.lineno,
                            message=(
                                f"dict `{arg.id}` gains keys under a "
                                f"condition (line {conditional[arg.id]}) "
                                "and feeds a jit'd call: every key-set "
                                "change is a new pytree structure and a "
                                "full recompile — make the key set static "
                                "(always-present keys, masked values)"
                            ),
                            key=make_key(
                                "RCP003",
                                sf.relpath,
                                sf.scope_of(call),
                                arg.id,
                            ),
                        )
