"""arealint rule families.

Each module exposes one checker class; :func:`all_checkers` returns fresh
instances in deterministic order.
"""

from __future__ import annotations


def all_checkers() -> list:
    from areal_tpu.analysis.rules.asy import AsyncSafetyChecker
    from areal_tpu.analysis.rules.cfg import ConfigDriftChecker
    from areal_tpu.analysis.rules.don import DonationChecker
    from areal_tpu.analysis.rules.exc import SilentExceptionChecker
    from areal_tpu.analysis.rules.jaxpurity import JaxPurityChecker
    from areal_tpu.analysis.rules.krn import PallasKernelChecker
    from areal_tpu.analysis.rules.lck import LockOrderChecker
    from areal_tpu.analysis.rules.msh import MeshCollectiveChecker
    from areal_tpu.analysis.rules.obs import MetricCatalogChecker
    from areal_tpu.analysis.rules.prf import HotPathSyncChecker
    from areal_tpu.analysis.rules.pvt import PrivateApiChecker
    from areal_tpu.analysis.rules.rcp import RecompileRiskChecker
    from areal_tpu.analysis.rules.shd import ShardingSpecChecker
    from areal_tpu.analysis.rules.sig import SignalSafetyChecker
    from areal_tpu.analysis.rules.thr import SharedStateChecker
    from areal_tpu.analysis.rules.wire import WireContractChecker

    return [
        AsyncSafetyChecker(),
        JaxPurityChecker(),
        SharedStateChecker(),
        ConfigDriftChecker(),
        MetricCatalogChecker(),
        SilentExceptionChecker(),
        SignalSafetyChecker(),
        HotPathSyncChecker(),
        DonationChecker(),
        ShardingSpecChecker(),
        RecompileRiskChecker(),
        WireContractChecker(),
        LockOrderChecker(),
        PallasKernelChecker(),
        PrivateApiChecker(),
        MeshCollectiveChecker(),
    ]
