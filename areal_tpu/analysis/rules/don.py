"""DON — buffer donation on jit'd train/optimizer steps.

A jit'd step that takes ``params``/``opt_state`` and returns their
updated versions holds BOTH generations live unless the inputs are
donated: for a model whose optimizer state is 2x params, the un-donated
step transiently doubles the largest tensors in HBM — the difference
between fitting a batch and OOM (the PR 9 HBM ledger's ``headroom``
gauge is the runtime view of the same budget). Donation is also a
correctness contract: a donated buffer is dead the moment the call
returns, so reading the old binding afterwards returns garbage on real
backends (and silently works on CPU, which is why it must be linted).

  DON001  jit'd step function takes a state-like argument (params /
          opt_state / grads / cache / *_state), rebinds it in the body
          and returns the update, but the argument is not in
          donate_argnums/donate_argnames
  DON002  use-after-donation: a name or ``self.<attr>`` passed in a
          donated position is read again after the call without being
          rebound

Call sites are resolved through the repo's two dispatch idioms (see
analysis/dataflow.py JitIndex): direct bindings ``g = jax.jit(f, ...)``
and jit-getter methods (``self._get_step()(...)``).
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from areal_tpu.analysis.core import (
    Finding,
    ProjectContext,
    SourceFile,
    dotted_name,
    make_key,
)
from areal_tpu.analysis.dataflow import JitIndex, ModuleInfo

_STATE_PARAM_RE = re.compile(
    r"^(params|opt_state|state|cache|grads?|mu|nu|opt|.*_state)$"
)


def _names_in(node: ast.AST) -> set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _own_nodes(fn: ast.AST):
    """Nodes of ``fn``'s own body, stopping at nested defs/lambdas — a
    scan body that rebinds its carry must not make the OUTER function
    look like it returns the update."""
    body = [fn.body] if isinstance(fn, ast.Lambda) else list(fn.body)
    stack: list[ast.AST] = body
    while stack:
        n = stack.pop()
        yield n
        if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            stack.extend(ast.iter_child_nodes(n))


def _returns_updated(fn: ast.AST, param: str) -> bool:
    """True when ``param`` is rebound in the body and flows into a
    return value — the donate-or-double shape."""
    rebound = False
    for node in _own_nodes(fn):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                targets = t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]
                if any(
                    isinstance(el, ast.Name) and el.id == param
                    for el in targets
                ):
                    rebound = True
        elif isinstance(node, ast.AugAssign):
            if isinstance(node.target, ast.Name) and node.target.id == param:
                rebound = True
    if not rebound:
        return False
    if isinstance(fn, ast.Lambda):
        return param in _names_in(fn.body)
    for node in _own_nodes(fn):
        if isinstance(node, ast.Return) and node.value is not None:
            if param in _names_in(node.value):
                return True
    return False


def _render_arg(node: ast.expr) -> str | None:
    """A stable token for trackable donated-argument expressions: bare
    names and ``self.<attr>`` chains."""
    if isinstance(node, ast.Name):
        return node.id
    d = dotted_name(node)
    if d is not None and d.startswith("self."):
        return d
    return None


class DonationChecker:
    FAMILY = "DON"
    RULES = {
        "DON001": "jit'd step missing donation of a state argument",
        "DON002": "use of a buffer after donating it to a jit'd call",
    }

    def check(self, sf: SourceFile, ctx: ProjectContext) -> Iterator[Finding]:
        graph = ctx.graph_for(sf)
        mod = graph.modules.get(sf.relpath)
        if mod is None:
            return
        jit_idx = mod.jit_index()

        # -- DON001: missing donation at the jit construction -------------
        for site in jit_idx.sites:
            if site.target is None or not site.params:
                continue
            for idx, p in enumerate(site.params):
                if not _STATE_PARAM_RE.match(p):
                    continue
                if site.donates(idx, p):
                    continue
                if site.is_static(idx, p):
                    continue
                if not _returns_updated(site.target, p):
                    continue
                yield Finding(
                    rule="DON001",
                    path=sf.relpath,
                    line=site.call.lineno,
                    message=(
                        f"jit'd step rebinds and returns `{p}` but does not "
                        f"donate it (add donate_argnums={idx} or "
                        f"donate_argnames=('{p}',)): both generations stay "
                        "live in HBM across the update"
                    ),
                    key=make_key(
                        "DON001",
                        sf.relpath,
                        sf.scope_of(site.call),
                        p,
                    ),
                )

        # -- DON002: use-after-donation at call sites ----------------------
        yield from self._check_use_after_donation(sf, mod, jit_idx)

    def _check_use_after_donation(
        self, sf: SourceFile, mod: ModuleInfo, jit_idx: JitIndex
    ) -> Iterator[Finding]:
        for fi in mod.funcs.values():
            fn = fi.node
            if isinstance(fn, ast.Lambda):
                continue
            # statements of this function only (not nested defs)
            stmts: list[ast.stmt] = []

            def collect(body: list[ast.stmt]) -> None:
                for s in body:
                    if isinstance(
                        s, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
                    ):
                        continue
                    stmts.append(s)
                    for attr in ("body", "orelse", "finalbody"):
                        collect(getattr(s, attr, []))
                    for h in getattr(s, "handlers", []):
                        collect(h.body)

            collect(fn.body)
            stmts.sort(key=lambda s: s.lineno)

            # anchor every call at its INNERMOST enclosing statement: a
            # multi-line donating call inside a `with` block must not be
            # re-walked from the `with` and have its own continuation
            # lines read as uses-after-donation
            def innermost_stmt(node: ast.AST) -> ast.stmt | None:
                cur = mod.parents.get(id(node))
                while cur is not None:
                    if isinstance(cur, ast.stmt) and cur in stmts:
                        return cur
                    cur = mod.parents.get(id(cur))
                return None

            def branch_chain(node: ast.AST) -> dict[int, str]:
                """id(If) -> 'body'|'orelse' for every If ancestor."""
                out: dict[int, str] = {}
                prev, cur = node, mod.parents.get(id(node))
                while cur is not None:
                    if isinstance(cur, ast.If):
                        out[id(cur)] = (
                            "body" if prev in cur.body else "orelse"
                        )
                    prev, cur = cur, mod.parents.get(id(cur))
                return out

            for call in ast.walk(fn):
                if not isinstance(call, ast.Call):
                    continue
                site = jit_idx.site_for_callsite(call)
                if site is None:
                    continue
                anchor = innermost_stmt(call)
                if anchor is None:
                    continue
                donated: list[tuple[str, str]] = []  # (token, param name)
                for idx, arg in enumerate(call.args):
                    pname = (
                        site.params[idx]
                        if idx < len(site.params)
                        else None
                    )
                    if not site.donates(idx, pname):
                        continue
                    token = _render_arg(arg)
                    if token is not None:
                        donated.append((token, pname or f"arg{idx}"))
                if donated:
                    # the statement containing the call may rebind the
                    # donated token itself (the canonical
                    # `x, y = step(x, y, ...)` shape)
                    rebound_here = self._stores_in(anchor)
                    anchor_branches = branch_chain(anchor)
                    for token, pname in donated:
                        if token in rebound_here:
                            continue
                        use = self._first_use_after(
                            stmts, anchor, token, anchor_branches, branch_chain
                        )
                        if use is not None:
                            yield Finding(
                                rule="DON002",
                                path=sf.relpath,
                                line=use,
                                message=(
                                    f"`{token}` was donated to the jit'd "
                                    f"call at line {call.lineno} "
                                    f"(parameter `{pname}`) and read again "
                                    "here without rebinding — the buffer "
                                    "is dead after donation"
                                ),
                                key=make_key(
                                    "DON002",
                                    sf.relpath,
                                    fi.qualname,
                                    token,
                                ),
                            )

    @staticmethod
    def _stores_in(stmt: ast.stmt) -> set[str]:
        out: set[str] = set()
        for node in ast.walk(stmt):
            if isinstance(node, (ast.Name, ast.Attribute)) and isinstance(
                getattr(node, "ctx", None), ast.Store
            ):
                tok = _render_arg(node)
                if tok is not None:
                    out.add(tok)
        return out

    def _first_use_after(
        self,
        stmts: list[ast.stmt],
        anchor: ast.stmt,
        token: str,
        anchor_branches: dict[int, str],
        branch_chain,
    ) -> int | None:
        """Line of the first Load of ``token`` in statements after the
        donating statement's full extent, stopping at the first rebind.
        Statements in the OPPOSITE branch of any If the anchor sits in
        are skipped — on that path the donation never executed. Loop
        back-edges are approximated away: a donation inside a loop whose
        same statement rebinds the token is the supported pattern."""
        end = getattr(anchor, "end_lineno", anchor.lineno) or anchor.lineno
        for stmt in stmts:
            if stmt.lineno <= end:
                continue
            sb = branch_chain(stmt)
            if any(
                sb.get(if_id) not in (None, which)
                for if_id, which in anchor_branches.items()
            ):
                continue  # mutually-exclusive branch: not a use-after
            for node in ast.walk(stmt):
                if not isinstance(node, (ast.Name, ast.Attribute)):
                    continue
                if _render_arg(node) != token:
                    continue
                if isinstance(getattr(node, "ctx", None), ast.Store):
                    return None
                return node.lineno
        return None
