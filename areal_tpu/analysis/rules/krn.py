"""KRN — Pallas kernel safety (the ROADMAP kernel arc's defect class).

Pallas failures are late and opaque: a BlockSpec index map with the wrong
arity for the launch grid is a TypeError deep inside tracing; a kernel
body whose positional refs drifted from the operand list reads the wrong
buffer silently (interpret mode often still "works"); a write through an
input ref aliases HBM the caller still owns; a ragged tail (grid dim from
a cdiv of a non-multiple size) without masking reads garbage rows; and a
kernel that never exposes ``interpret=`` cannot run in CI at all (the
paged-attention fork was red for 15 PRs precisely because its only
coverage needed a TPU). These rules check the launch-site geometry the
compiler only checks at trace time — and only on a TPU for some of it.

  KRN001  BlockSpec index-map arity differs from grid rank (+ prefetch):
          index maps are called with one argument per grid dimension plus
          one per scalar-prefetch operand (PrefetchScalarGridSpec)
  KRN002  kernel body positional-parameter count differs from the
          operand plan (prefetch + inputs + outputs + scratch)
  KRN003  kernel body writes through an input ref (scalar-prefetch or
          in_specs position) — inputs alias caller memory
  KRN004  grid dimension is a cdiv of a runtime size but the kernel body
          has no ``pl.when`` masking — the ragged tail reads/writes out
          of the logical bounds (warning: the size may be known-aligned)
  KRN005  ``pallas_call`` whose enclosing function does not expose an
          ``interpret`` parameter — the kernel cannot run on CPU, so it
          is invisible to tier-1 and to tools/kernelcheck.py parity runs

Everything is resolved statically and conservatively: names are followed
one assignment deep within the enclosing function (names bound more than
once are treated as unknown), ``functools.partial`` unwraps to local
defs, and any count that cannot be resolved to a literal silences the
rules that need it. Unknown stays silent.
"""

from __future__ import annotations

import ast
from typing import Iterator

from areal_tpu.analysis.core import (
    Finding,
    ProjectContext,
    SourceFile,
    SEVERITY_WARNING,
    dotted_name,
    make_key,
)


def _last(d: str | None) -> str:
    return (d or "").split(".")[-1]


def _single_assign_env(*bodies: list[ast.stmt]) -> dict[str, ast.expr]:
    """name -> value for names assigned exactly once across ``bodies``
    (simple ``x = expr`` only; re-bound names are unknown)."""
    counts: dict[str, int] = {}
    values: dict[str, ast.expr] = {}
    for body in bodies:
        for stmt in body:
            for node in ast.walk(ast.Module(body=[stmt], type_ignores=[])):
                if isinstance(node, ast.Assign) and len(node.targets) == 1 and (
                    isinstance(node.targets[0], ast.Name)
                ):
                    name = node.targets[0].id
                    counts[name] = counts.get(name, 0) + 1
                    values[name] = node.value
                elif isinstance(node, (ast.AugAssign, ast.AnnAssign)) and (
                    isinstance(node.target, ast.Name)
                ):
                    counts[node.target.id] = counts.get(node.target.id, 0) + 2
    return {n: v for n, v in values.items() if counts.get(n) == 1}


def _resolve(node: ast.expr | None, env: dict, depth: int = 3) -> ast.expr | None:
    while depth > 0 and isinstance(node, ast.Name) and node.id in env:
        node = env[node.id]
        depth -= 1
    return node


def _const_int(node: ast.expr | None, env: dict) -> int | None:
    node = _resolve(node, env)
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return node.value
    return None


def _seq_len(node: ast.expr | None, env: dict) -> int | None:
    node = _resolve(node, env)
    if isinstance(node, (ast.Tuple, ast.List)):
        if any(isinstance(e, ast.Starred) for e in node.elts):
            return None
        return len(node.elts)
    return None


def _positional_params(fn: ast.AST) -> list[str] | None:
    """Positional parameter names (None when *args makes the list open)."""
    args = getattr(fn, "args", None)
    if args is None or args.vararg is not None:
        return None
    return [a.arg for a in (*args.posonlyargs, *args.args)]


class _Site:
    """One pallas_call launch with whatever geometry resolved statically."""

    def __init__(self, call: ast.Call, sf: SourceFile, env: dict, defs: dict):
        self.call = call
        kw = {k.arg: k.value for k in call.keywords if k.arg}
        spec_src = kw
        gs = _resolve(kw.get("grid_spec"), env)
        if isinstance(gs, ast.Call) and _last(dotted_name(gs.func)) in (
            "PrefetchScalarGridSpec",
            "GridSpec",
        ):
            spec_src = {k.arg: k.value for k in gs.keywords if k.arg}
            self.num_prefetch = _const_int(
                spec_src.get("num_scalar_prefetch"), env
            ) or 0
        else:
            self.num_prefetch = 0
        self.grid = _resolve(spec_src.get("grid"), env)
        self.grid_len = _seq_len(spec_src.get("grid"), env)
        if self.grid_len is None and isinstance(self.grid, ast.Constant):
            self.grid_len = 1 if isinstance(self.grid.value, int) else None
        self.in_specs = _resolve(spec_src.get("in_specs"), env)
        self.n_in = _seq_len(spec_src.get("in_specs"), env)
        self.out_specs = _resolve(spec_src.get("out_specs"), env)
        self.n_out = _seq_len(spec_src.get("out_specs"), env)
        if self.n_out is None and self.out_specs is not None:
            self.n_out = 1  # single spec = single output
        if self.n_out is None:
            self.n_out = _seq_len(kw.get("out_shape"), env)
            if self.n_out is None and isinstance(
                _resolve(kw.get("out_shape"), env), ast.Call
            ):
                self.n_out = 1
        self.n_scratch = _seq_len(spec_src.get("scratch_shapes"), env)
        if "scratch_shapes" not in spec_src:
            self.n_scratch = 0
        self.interpret_kw = "interpret" in kw
        # kernel: first positional arg, through functools.partial if needed
        self.kernel_def: ast.AST | None = None
        self.kernel_name = "<kernel>"
        self.partial_kw_names: set[str] = set()
        self.partial_pos = 0
        target = _resolve(call.args[0], env) if call.args else None
        if isinstance(target, ast.Call) and _last(dotted_name(target.func)) == (
            "partial"
        ):
            self.partial_kw_names = {k.arg for k in target.keywords if k.arg}
            self.partial_pos = len(target.args) - 1
            target = _resolve(target.args[0], env) if target.args else None
        if isinstance(target, ast.Lambda):
            self.kernel_def = target
            self.kernel_name = "<lambda>"
        elif isinstance(target, ast.Name) and target.id in defs:
            self.kernel_def = defs[target.id]
            self.kernel_name = target.id

    def specs(self) -> Iterator[ast.expr]:
        for group in (self.in_specs, self.out_specs):
            if isinstance(group, (ast.Tuple, ast.List)):
                yield from group.elts
            elif group is not None:
                yield group


class PallasKernelChecker:
    FAMILY = "KRN"
    RULES = {
        "KRN001": "BlockSpec index-map arity differs from grid rank",
        "KRN002": "kernel parameter count differs from operand plan",
        "KRN003": "kernel writes through an input ref",
        "KRN004": "cdiv-derived grid dimension without pl.when masking",
        "KRN005": "pallas_call not reachable with interpret= (not CPU-testable)",
    }

    def check(self, sf: SourceFile, ctx: ProjectContext) -> Iterator[Finding]:
        # local defs by name, vetoed when the name is also re-assigned
        defs: dict[str, ast.AST] = {}
        assigned: set[str] = set()
        for node in ast.walk(sf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs[node.name] = node
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    for el in t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]:
                        if isinstance(el, ast.Name):
                            assigned.add(el.id)
        for name in assigned:
            defs.pop(name, None)

        module_env = _single_assign_env(sf.tree.body)
        for call in ast.walk(sf.tree):
            if not isinstance(call, ast.Call):
                continue
            if _last(dotted_name(call.func)) != "pallas_call":
                continue
            env = dict(module_env)
            encl = self._enclosing_functions(sf, call)
            for fn in encl:
                env.update(_single_assign_env(fn.body))
            site = _Site(call, sf, env, defs)
            yield from self._check_index_maps(sf, site, env, defs)
            yield from self._check_kernel_arity(sf, site)
            yield from self._check_input_writes(sf, site)
            yield from self._check_ragged_tail(sf, site, env)
            yield from self._check_interpret(sf, site, encl)

    def _enclosing_functions(self, sf: SourceFile, node: ast.AST) -> list[ast.AST]:
        out = []
        cur = sf.parents.get(id(node))
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.append(cur)
            cur = sf.parents.get(id(cur))
        return out

    # -- KRN001 -------------------------------------------------------------
    def _check_index_maps(
        self, sf: SourceFile, site: _Site, env: dict, defs: dict
    ) -> Iterator[Finding]:
        if site.grid_len is None:
            return
        expected = site.grid_len + site.num_prefetch
        for spec in site.specs():
            spec = _resolve(spec, env)
            if not (
                isinstance(spec, ast.Call)
                and _last(dotted_name(spec.func)) == "BlockSpec"
            ):
                continue
            imap = None
            if len(spec.args) >= 2:
                imap = spec.args[1]
            for k in spec.keywords:
                if k.arg == "index_map":
                    imap = k.value
            imap = _resolve(imap, env)
            fn: ast.AST | None = None
            if isinstance(imap, ast.Lambda):
                fn = imap
            elif isinstance(imap, ast.Name) and imap.id in defs:
                fn = defs[imap.id]
            if fn is None:
                continue
            params = _positional_params(fn)
            if params is None or len(params) == expected:
                continue
            yield Finding(
                rule="KRN001",
                path=sf.relpath,
                line=spec.lineno,
                message=(
                    f"BlockSpec index map takes {len(params)} argument(s) "
                    f"but the launch calls it with {expected} "
                    f"({site.grid_len} grid dim(s)"
                    + (
                        f" + {site.num_prefetch} scalar-prefetch ref(s)"
                        if site.num_prefetch
                        else ""
                    )
                    + ")"
                ),
                key=make_key(
                    "KRN001",
                    sf.relpath,
                    sf.scope_of(spec),
                    f"{site.kernel_name}:{len(params)}v{expected}",
                ),
            )

    # -- KRN002 -------------------------------------------------------------
    def _check_kernel_arity(self, sf: SourceFile, site: _Site) -> Iterator[Finding]:
        if site.kernel_def is None:
            return
        if None in (site.n_in, site.n_out, site.n_scratch):
            return
        params = _positional_params(site.kernel_def)
        if params is None:
            return
        # partial keyword bindings only consume a ref slot when they bind a
        # POSITIONAL parameter; binding a keyword-only config (scale=,
        # blk_q=) leaves the positional ref zip untouched
        free = [
            p
            for p in params[site.partial_pos :]
            if p not in site.partial_kw_names
        ]
        have = len(free)
        want = site.num_prefetch + site.n_in + site.n_out + site.n_scratch
        if have == want:
            return
        yield Finding(
            rule="KRN002",
            path=sf.relpath,
            line=site.call.lineno,
            message=(
                f"kernel `{site.kernel_name}` takes {have} ref parameter(s) "
                f"but the launch supplies {want} "
                f"({site.num_prefetch} prefetch + {site.n_in} in + "
                f"{site.n_out} out + {site.n_scratch} scratch); refs zip "
                "positionally — drift reads the wrong buffer silently"
            ),
            key=make_key(
                "KRN002",
                sf.relpath,
                sf.scope_of(site.call),
                f"{site.kernel_name}:{have}v{want}",
            ),
        )

    # -- KRN003 -------------------------------------------------------------
    def _check_input_writes(self, sf: SourceFile, site: _Site) -> Iterator[Finding]:
        if site.kernel_def is None or site.n_in is None:
            return
        params = _positional_params(site.kernel_def)
        if params is None:
            return
        # refs bound by a keyword partial are config scalars, not refs; the
        # input range is the first prefetch+n_in UNBOUND positional params
        # after any positionally-bound partial args
        free = [
            p
            for p in params[site.partial_pos :]
            if p not in site.partial_kw_names
        ]
        inputs = set(free[: site.num_prefetch + site.n_in])
        body = getattr(site.kernel_def, "body", site.kernel_def)
        nodes = []
        for stmt in body if isinstance(body, list) else [body]:
            nodes.extend(ast.walk(stmt))
        for node in nodes:
            targets: list[ast.expr] = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = [node.target]
            for t in targets:
                if (
                    isinstance(t, ast.Subscript)
                    and isinstance(t.value, ast.Name)
                    and t.value.id in inputs
                ):
                    yield Finding(
                        rule="KRN003",
                        path=sf.relpath,
                        line=t.lineno,
                        message=(
                            f"kernel `{site.kernel_name}` writes through "
                            f"input ref `{t.value.id}`; input refs alias "
                            "caller memory — route results through an "
                            "output or scratch ref"
                        ),
                        key=make_key(
                            "KRN003",
                            sf.relpath,
                            sf.scope_of(site.call),
                            f"{site.kernel_name}:{t.value.id}",
                        ),
                    )

    # -- KRN004 -------------------------------------------------------------
    def _check_ragged_tail(
        self, sf: SourceFile, site: _Site, env: dict
    ) -> Iterator[Finding]:
        if site.kernel_def is None or not isinstance(
            site.grid, (ast.Tuple, ast.List)
        ):
            return
        ragged = None
        for dim in site.grid.elts:
            dim = _resolve(dim, env)
            if isinstance(dim, ast.Call) and _last(dotted_name(dim.func)) == "cdiv":
                ragged = dim
                break
        if ragged is None:
            return
        body = getattr(site.kernel_def, "body", [])
        for node in (n for stmt in body for n in ast.walk(stmt)):
            if isinstance(node, ast.Call) and _last(dotted_name(node.func)) == (
                "when"
            ):
                return
            if isinstance(node, ast.Compare):
                return  # any predicate in the body counts as masking intent
        yield Finding(
            rule="KRN004",
            path=sf.relpath,
            line=ragged.lineno,
            severity=SEVERITY_WARNING,
            message=(
                f"grid dimension is a cdiv but kernel "
                f"`{site.kernel_name}` has no pl.when/predicate masking: "
                "the last program instance covers a ragged tail of "
                "out-of-bounds rows"
            ),
            key=make_key(
                "KRN004",
                sf.relpath,
                sf.scope_of(site.call),
                site.kernel_name,
            ),
        )

    # -- KRN005 -------------------------------------------------------------
    def _check_interpret(
        self, sf: SourceFile, site: _Site, encl: list[ast.AST]
    ) -> Iterator[Finding]:
        for fn in encl:
            args = fn.args
            names = {
                a.arg
                for a in (*args.posonlyargs, *args.args, *args.kwonlyargs)
            }
            if "interpret" in names:
                return
        yield Finding(
            rule="KRN005",
            path=sf.relpath,
            line=site.call.lineno,
            message=(
                "no enclosing function exposes an `interpret` parameter: "
                "this pallas_call can only ever run on a TPU, so tier-1 "
                "and tools/kernelcheck.py parity runs cannot cover it"
            ),
            key=make_key(
                "KRN005",
                sf.relpath,
                sf.scope_of(site.call),
                site.kernel_name,
            ),
        )
