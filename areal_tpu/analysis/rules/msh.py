"""MSH — SPMD/collective consistency against the mesh/axis environment.

Collectives are stringly-typed the same way PartitionSpecs are (SHD):
``jax.lax.psum(x, "modle")`` raises nothing until trace time inside a
real mapped region, and ``shard_map`` out_specs that disagree with the
callee's return structure fail as opaque pytree errors. Worse, on the
pinned jax 0.4.37 the old ``shard_map`` manualizes EVERY mesh axis, so a
raw ``jax.lax.with_sharding_constraint`` inside any mapped body dies at
*lowering* time ("Axis ... is also found in manual_axes") — the exact
failure that kept tests/test_pp_engine.py red since seed. The fix routes
every constraint through ``utils/jax_compat.with_sharding_constraint``
(which drops manual axes); MSH003 pins that routing so the next
refactor cannot silently reintroduce the raw call.

  MSH001  collective axis name not in the mesh/axis vocabulary
          (package MESH_AXES + file-local MESH_AXES + ad-hoc Mesh
          constructions + pmap/vmap ``axis_name=`` bindings)
  MSH002  shard_map out_specs tuple length differs from the callee's
          literal tuple return (both fully literal; a single spec is a
          legal pytree prefix and is never flagged)
  MSH003  raw ``jax.lax.with_sharding_constraint`` call — on jax 0.4.x
          this cannot be expressed inside shard_map regions; route
          through areal_tpu.utils.jax_compat.with_sharding_constraint

Only names that resolve to jax (``jax.lax.*`` / ``lax.*`` dotted paths,
or bare names imported from a jax module) are checked, so an unrelated
local ``all_gather`` helper never false-positives. Unknown stays silent.
"""

from __future__ import annotations

import ast
from typing import Iterator

from areal_tpu.analysis.core import (
    Finding,
    ProjectContext,
    SourceFile,
    dotted_name,
    make_key,
)
from areal_tpu.analysis.rules.shd import (
    _declared_mesh_axes,
    _local_mesh_axes,
)

_COLLECTIVES = {
    # name -> positional index of the axis-name argument
    "psum": 1,
    "pmean": 1,
    "pmax": 1,
    "pmin": 1,
    "ppermute": 1,
    "pshuffle": 1,
    "psum_scatter": 1,
    "all_gather": 1,
    "all_to_all": 1,
    "axis_index": 0,
    "axis_size": 0,
}


def _jax_bound_names(tree: ast.Module) -> set[str]:
    """Bare local names that resolve into jax (``from jax.lax import
    all_gather``, ``from areal_tpu.utils.jax_compat import axis_size``)."""
    out: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module and (
            node.module.startswith("jax")
            or node.module.endswith("jax_compat")
        ):
            for a in node.names:
                out.add(a.asname or a.name)
    return out


def _axis_names(node: ast.expr | None) -> list[str] | None:
    """Literal axis name(s): "axis" or a tuple/list of them. None = skip."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                out.append(e.value)
            else:
                return None
        return out
    return None


def _bound_axis_names(tree: ast.Module) -> set[str]:
    """Axis names bound by pmap/vmap/shard_map-adjacent ``axis_name=``."""
    axes: set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        for k in node.keywords:
            if k.arg in ("axis_name", "axis_names"):
                got = _axis_names(k.value)
                if got:
                    axes.update(got)
    return axes


class MeshCollectiveChecker:
    FAMILY = "MSH"
    RULES = {
        "MSH001": "collective axis name not in the mesh vocabulary",
        "MSH002": "shard_map out_specs length differs from callee return",
        "MSH003": "raw with_sharding_constraint (manual-axes-unsafe on 0.4.x)",
    }

    def check(self, sf: SourceFile, ctx: ProjectContext) -> Iterator[Finding]:
        axes = _local_mesh_axes(sf.tree)
        if axes is None:
            axes = ctx.mesh_axes
        axes = frozenset(
            axes | _declared_mesh_axes(sf.tree) | _bound_axis_names(sf.tree)
        )
        jax_names = _jax_bound_names(sf.tree)
        yield from self._check_collectives(sf, axes, jax_names)
        yield from self._check_out_specs(sf)
        yield from self._check_raw_constraint(sf)

    # -- MSH001 -------------------------------------------------------------
    def _check_collectives(
        self, sf: SourceFile, axes: frozenset[str], jax_names: set[str]
    ) -> Iterator[Finding]:
        if not axes:
            return
        for call in ast.walk(sf.tree):
            if not isinstance(call, ast.Call):
                continue
            d = dotted_name(call.func)
            if d is None:
                continue
            last = d.split(".")[-1]
            if last not in _COLLECTIVES:
                continue
            if "." in d:
                head = d.split(".")[0]
                if head not in ("jax", "lax"):
                    continue
            elif last not in jax_names:
                continue
            arg: ast.expr | None = None
            for k in call.keywords:
                if k.arg == "axis_name":
                    arg = k.value
            if arg is None:
                idx = _COLLECTIVES[last]
                if len(call.args) > idx:
                    arg = call.args[idx]
            names = _axis_names(arg)
            if not names:
                continue
            for axis in names:
                if axis in axes:
                    continue
                yield Finding(
                    rule="MSH001",
                    path=sf.relpath,
                    line=call.lineno,
                    message=(
                        f"collective `{last}` names axis '{axis}' which is "
                        f"not in the mesh/axis vocabulary "
                        f"({', '.join(sorted(axes))}); an unbound axis "
                        "name fails only at trace time inside the mapped "
                        "region"
                    ),
                    key=make_key(
                        "MSH001",
                        sf.relpath,
                        sf.scope_of(call),
                        f"{last}:{axis}",
                    ),
                )

    # -- MSH002 -------------------------------------------------------------
    def _check_out_specs(self, sf: SourceFile) -> Iterator[Finding]:
        local_defs: dict[str, ast.AST] = {}
        assigned: set[str] = set()
        for node in ast.walk(sf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                local_defs[node.name] = node
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    for el in t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]:
                        if isinstance(el, ast.Name):
                            assigned.add(el.id)
        for name in assigned:
            local_defs.pop(name, None)
        for call in ast.walk(sf.tree):
            if not isinstance(call, ast.Call):
                continue
            d = dotted_name(call.func)
            if d is None or d.split(".")[-1] != "shard_map":
                continue
            if not call.args:
                continue
            target = call.args[0]
            fn: ast.AST | None = None
            if isinstance(target, ast.Lambda):
                fn = target
            elif isinstance(target, ast.Name):
                fn = local_defs.get(target.id)
            if fn is None:
                continue
            out_specs = next(
                (k.value for k in call.keywords if k.arg == "out_specs"), None
            )
            if out_specs is None and len(call.args) >= 4:
                out_specs = call.args[3]
            if not isinstance(out_specs, (ast.Tuple, ast.List)):
                continue  # single spec = legal pytree prefix
            n_specs = len(out_specs.elts)
            returns: set[int] = set()
            if isinstance(fn, ast.Lambda):
                body = fn.body
                returns.add(
                    len(body.elts) if isinstance(body, ast.Tuple) else 1
                )
            else:
                for node in ast.walk(fn):
                    if isinstance(node, ast.Return) and node.value is not None:
                        v = node.value
                        if isinstance(v, ast.Tuple):
                            returns.add(len(v.elts))
                        elif isinstance(v, (ast.Name, ast.Constant, ast.Call)):
                            returns.add(1)
            if len(returns) != 1:
                continue  # inconsistent/unresolvable returns: skip
            n_ret = returns.pop()
            if n_ret == n_specs:
                continue
            yield Finding(
                rule="MSH002",
                path=sf.relpath,
                line=call.lineno,
                message=(
                    f"shard_map out_specs has {n_specs} entries but "
                    f"`{getattr(fn, 'name', '<lambda>')}` returns "
                    f"{n_ret} value(s); the mismatch fails as an opaque "
                    "pytree-structure error at trace time"
                ),
                key=make_key(
                    "MSH002",
                    sf.relpath,
                    sf.scope_of(call),
                    getattr(fn, "name", "<lambda>"),
                ),
            )

    # -- MSH003 -------------------------------------------------------------
    def _check_raw_constraint(self, sf: SourceFile) -> Iterator[Finding]:
        # bare-name calls count only when imported from jax.lax directly
        raw_names = set()
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ImportFrom) and node.module in (
                "jax.lax",
                "jax.experimental.pjit",
            ):
                for a in node.names:
                    if a.name == "with_sharding_constraint":
                        raw_names.add(a.asname or a.name)
        for call in ast.walk(sf.tree):
            if not isinstance(call, ast.Call):
                continue
            d = dotted_name(call.func)
            if d is None:
                continue
            flagged = d in (
                "jax.lax.with_sharding_constraint",
                "lax.with_sharding_constraint",
            ) or ("." not in d and d in raw_names)
            if not flagged:
                continue
            yield Finding(
                rule="MSH003",
                path=sf.relpath,
                line=call.lineno,
                message=(
                    "raw jax.lax.with_sharding_constraint: on jax 0.4.x "
                    "the old shard_map manualizes every mesh axis and this "
                    "call fails at LOWERING time inside any mapped region "
                    "(the pp_engine failure class); route through "
                    "areal_tpu.utils.jax_compat.with_sharding_constraint"
                ),
                key=make_key(
                    "MSH003",
                    sf.relpath,
                    sf.scope_of(call),
                    "with_sharding_constraint",
                ),
            )
