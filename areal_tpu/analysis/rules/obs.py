"""OBS — metric-name drift against the observability catalog.

``observability/catalog.py`` is the single source of truth for every
metric family: the dashboard, the fleet aggregator, and external scrape
configs all join on these names. A metric registered elsewhere, or a name
referenced that the catalog does not define, silently produces an
always-empty dashboard panel. This rule subsumes the ad-hoc name lint
that used to live in ``tools/validate_installation.py``. Rules:

  OBS001  metric registered outside the catalog module
  OBS002  reference to a metric name the catalog does not define
  OBS003  catalog metric name violates ``^areal_[a-z0-9_]+$``
  OBS004  catalog metric registered without help text
  OBS005  duplicate metric name registered in the catalog

Reference detection (OBS002) is prefix-scoped to avoid false positives:
only string literals whose first two ``_``-separated tokens match an
existing catalog family prefix are treated as metric references, with
Prometheus ``_sum``/``_count``/``_bucket`` suffixes stripped first.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from areal_tpu.analysis.core import (
    Finding,
    ProjectContext,
    SourceFile,
    const_str,
    make_key,
)

_NAME_RE = re.compile(r"^areal_[a-z0-9_]+$")
_REF_RE = re.compile(r"^areal_[a-z][a-z0-9_]*[a-z0-9]$")
_HISTO_SUFFIXES = ("_sum", "_count", "_bucket")
_REGISTER_METHODS = ("counter", "gauge", "histogram")


class MetricCatalogChecker:
    FAMILY = "OBS"
    RULES = {
        "OBS001": "metric registered outside observability/catalog.py",
        "OBS002": "reference to a metric name missing from the catalog",
        "OBS003": "catalog metric name violates the naming convention",
        "OBS004": "catalog metric registered without help text",
        "OBS005": "duplicate metric name registered in the catalog",
    }

    def check(self, sf: SourceFile, ctx: ProjectContext) -> Iterator[Finding]:
        is_catalog = sf.relpath == ctx.catalog_relpath
        registered_args: set[int] = set()
        seen_names: dict[str, int] = {}

        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            if not (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _REGISTER_METHODS
            ):
                continue
            name = const_str(node.args[0]) if node.args else None
            if name is None or not name.startswith("areal_"):
                continue
            registered_args.add(id(node.args[0]))
            if not is_catalog:
                yield Finding(
                    rule="OBS001",
                    path=sf.relpath,
                    line=node.lineno,
                    message=(
                        f"metric `{name}` registered outside the catalog; "
                        "add a factory in observability/catalog.py so the "
                        "name has one source of truth"
                    ),
                    key=make_key(
                        "OBS001", sf.relpath, sf.scope_of(node), name
                    ),
                )
                continue
            # catalog-side lint (formerly validate_installation metrics_lint)
            if not _NAME_RE.match(name) or name.endswith("_") or "__" in name:
                yield Finding(
                    rule="OBS003",
                    path=sf.relpath,
                    line=node.lineno,
                    message=(
                        f"metric `{name}` violates the naming convention "
                        "(lower_snake, `areal_` prefix, no trailing/double "
                        "underscores)"
                    ),
                    key=make_key("OBS003", sf.relpath, sf.scope_of(node), name),
                )
            help_arg = node.args[1] if len(node.args) > 1 else None
            help_text = const_str(help_arg)
            if help_text is None or not help_text.strip():
                yield Finding(
                    rule="OBS004",
                    path=sf.relpath,
                    line=node.lineno,
                    message=f"metric `{name}` registered without help text",
                    key=make_key("OBS004", sf.relpath, sf.scope_of(node), name),
                )
            if name in seen_names:
                yield Finding(
                    rule="OBS005",
                    path=sf.relpath,
                    line=node.lineno,
                    message=(
                        f"metric `{name}` already registered at line "
                        f"{seen_names[name]}"
                    ),
                    key=make_key("OBS005", sf.relpath, sf.scope_of(node), name),
                )
            else:
                seen_names[name] = node.lineno

        if is_catalog or not ctx.metric_names:
            return

        # -- references elsewhere must resolve against the catalog ---------
        for node in ast.walk(sf.tree):
            s = const_str(node)
            if (
                s is None
                or id(node) in registered_args
                or not _REF_RE.match(s)
            ):
                continue
            prefix = "_".join(s.split("_")[:2])
            if prefix not in ctx.metric_prefixes:
                continue  # not metric-shaped (logger names, context keys…)
            base = s
            for suf in _HISTO_SUFFIXES:
                if base.endswith(suf) and base[: -len(suf)] in ctx.metric_names:
                    base = base[: -len(suf)]
                    break
            if base not in ctx.metric_names:
                yield Finding(
                    rule="OBS002",
                    path=sf.relpath,
                    line=node.lineno,
                    message=(
                        f"metric name `{s}` is not defined in "
                        "observability/catalog.py (drifted or misspelled)"
                    ),
                    key=make_key("OBS002", sf.relpath, sf.scope_of(node), s),
                )
