"""THR — unguarded shared state written from thread targets.

Every long-lived component here owns a background thread (dispatcher,
decode loop, RPC engine thread, telemetry scraper). An attribute the
thread writes and another thread reads without a lock is a data race that
CPython's GIL usually hides — until a torn multi-step update (check-then-
act, read-modify-write) corrupts accounting under load. Rule:

  THR001  attribute written inside a thread-target method without holding
          a lock, while other (non-``__init__``) methods of the class also
          access it

Thread targets are found from ``threading.Thread(target=self._m)`` and
``threading.Thread(target=local_fn)``; the analysis follows ``self``
method calls transitively, so helpers invoked from the loop body count as
thread code. Writes inside ``with self.<lock>:`` blocks are considered
guarded, where ``<lock>`` is any attribute assigned a
``threading.Lock/RLock/Condition`` or with a lock-like name.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from areal_tpu.analysis.core import (
    Finding,
    ProjectContext,
    SourceFile,
    dotted_name,
    make_key,
)

_LOCK_CTORS = {
    "threading.Lock",
    "threading.RLock",
    "threading.Condition",
    "Lock",
    "RLock",
    "Condition",
}
_LOCKISH_NAME = re.compile(r"(^|_)(lock|cv|cond|mutex)")


def _self_attr(node: ast.AST) -> str | None:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


class SharedStateChecker:
    FAMILY = "THR"
    RULES = {
        "THR001": "unguarded attribute write on a thread target",
    }

    def check(self, sf: SourceFile, ctx: ProjectContext) -> Iterator[Finding]:
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ClassDef):
                yield from self._check_class(sf, node)

    def _check_class(
        self, sf: SourceFile, cls: ast.ClassDef
    ) -> Iterator[Finding]:
        methods: dict[str, ast.FunctionDef] = {
            n.name: n for n in cls.body if isinstance(n, ast.FunctionDef)
        }
        if not methods:
            return

        # lock-like attributes (by constructor or by name)
        lock_attrs: set[str] = set()
        for node in ast.walk(cls):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                if dotted_name(node.value.func) in _LOCK_CTORS:
                    for t in node.targets:
                        attr = _self_attr(t)
                        if attr:
                            lock_attrs.add(attr)

        def is_lockish(attr: str) -> bool:
            return attr in lock_attrs or bool(_LOCKISH_NAME.search(attr))

        # thread entry points: Thread(target=self._m | local_fn)
        target_methods: set[str] = set()
        local_targets: list[ast.FunctionDef] = []
        for node in ast.walk(cls):
            if not (
                isinstance(node, ast.Call)
                and dotted_name(node.func) in ("threading.Thread", "Thread")
            ):
                continue
            target = next(
                (kw.value for kw in node.keywords if kw.arg == "target"), None
            )
            if target is None:
                continue
            attr = _self_attr(target)
            if attr and attr in methods:
                target_methods.add(attr)
            elif isinstance(target, ast.Name):
                # local function defined in some enclosing method
                enclosing = sf.parents.get(id(node))
                while enclosing is not None and not isinstance(
                    enclosing, ast.FunctionDef
                ):
                    enclosing = sf.parents.get(id(enclosing))
                if enclosing is not None:
                    for stmt in ast.walk(enclosing):
                        if (
                            isinstance(stmt, ast.FunctionDef)
                            and stmt.name == target.id
                        ):
                            local_targets.append(stmt)
                            break
        if not target_methods and not local_targets:
            return

        # transitive closure over self-method calls from the targets
        thread_methods: set[str] = set()
        frontier = list(target_methods)
        while frontier:
            m = frontier.pop()
            if m in thread_methods or m not in methods:
                continue
            thread_methods.add(m)
            for sub in ast.walk(methods[m]):
                if isinstance(sub, ast.Call):
                    callee = _self_attr(sub.func)
                    if callee and callee in methods:
                        frontier.append(callee)

        thread_nodes: list[ast.FunctionDef] = [
            methods[m] for m in thread_methods
        ] + local_targets

        # attributes accessed from OTHER methods (excluding __init__, which
        # runs before any thread starts)
        outside_attrs: set[str] = set()
        for name, meth in methods.items():
            if name == "__init__" or name in thread_methods:
                continue
            for sub in ast.walk(meth):
                attr = _self_attr(sub)
                if attr:
                    outside_attrs.add(attr)

        def guarded(node: ast.AST, root: ast.AST) -> bool:
            cur = sf.parents.get(id(node))
            while cur is not None and id(cur) != id(root):
                if isinstance(cur, ast.With):
                    for item in cur.items:
                        attr = _self_attr(item.context_expr)
                        if attr and is_lockish(attr):
                            return True
                cur = sf.parents.get(id(cur))
            return False

        reported: set[str] = set()
        for tnode in thread_nodes:
            for sub in ast.walk(tnode):
                if not isinstance(sub, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                    continue
                targets = (
                    sub.targets if isinstance(sub, ast.Assign) else [sub.target]
                )
                for t in targets:
                    attr = _self_attr(t)
                    if (
                        attr is None
                        or attr in lock_attrs
                        or attr not in outside_attrs
                    ):
                        continue
                    # writes from nested defs that are not thread code
                    # themselves still count: they execute on this thread
                    if guarded(sub, tnode):
                        continue
                    token = f"{cls.name}.{attr}"
                    if token in reported:
                        continue  # one finding per (class, attr)
                    reported.add(token)
                    yield Finding(
                        rule="THR001",
                        path=sf.relpath,
                        line=sub.lineno,
                        message=(
                            f"`self.{attr}` is written on thread target "
                            f"`{tnode.name}` without a lock but accessed "
                            "from other methods; guard both sides or "
                            "document why the race is benign"
                        ),
                        key=make_key("THR001", sf.relpath, cls.name, attr),
                    )
