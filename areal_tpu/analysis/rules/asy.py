"""ASY — blocking calls reachable from ``async def`` bodies.

The rollout side runs thousands of concurrent coroutines on ONE event loop
(infra/async_task_runner.py). A single ``time.sleep`` or synchronous HTTP
call inside any of them stalls every in-flight generation at once — the
classic async-RL throughput bug that never raises. Rules:

  ASY001  ``time.sleep`` in an async function (use ``await asyncio.sleep``)
  ASY002  synchronous I/O (urllib/requests/http.client/socket/subprocess)
          in an async function (use aiohttp / run_in_executor)
  ASY003  blocking lock acquisition in an async function: un-awaited
          ``*.acquire()`` or ``with <lock-like attr>:`` (a threading lock
          held across the loop blocks every other coroutine)
  ASY004  call from an async function into a local sync helper that itself
          blocks (one-hop reachability)
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from areal_tpu.analysis.core import (
    Finding,
    ProjectContext,
    SourceFile,
    dotted_name,
    make_key,
)

# dotted callee -> rule id
_BLOCKING = {
    "time.sleep": "ASY001",
    "urllib.request.urlopen": "ASY002",
    "socket.create_connection": "ASY002",
    "os.system": "ASY002",
    "subprocess.run": "ASY002",
    "subprocess.call": "ASY002",
    "subprocess.check_call": "ASY002",
    "subprocess.check_output": "ASY002",
    "http.client.HTTPConnection": "ASY002",
    "http.client.HTTPSConnection": "ASY002",
}
_REQUESTS_METHODS = {
    "get", "post", "put", "delete", "head", "patch", "options", "request",
}
_LOCKISH_RE = re.compile(r"(^|_)(lock|cv|cond|mutex|sem)")


def _blocking_rule(call: ast.Call) -> tuple[str, str] | None:
    """(rule_id, token) when ``call`` is a known blocking call."""
    dotted = dotted_name(call.func)
    if dotted is None:
        return None
    if dotted in _BLOCKING:
        return _BLOCKING[dotted], dotted
    parts = dotted.split(".")
    if parts[0] == "requests" and parts[-1] in _REQUESTS_METHODS:
        return "ASY002", dotted
    if parts[-1] == "acquire" and len(parts) > 1:
        return "ASY003", dotted
    return None


class AsyncSafetyChecker:
    FAMILY = "ASY"
    RULES = {
        "ASY001": "time.sleep inside an async function",
        "ASY002": "synchronous I/O inside an async function",
        "ASY003": "blocking lock acquisition inside an async function",
        "ASY004": "async function calls a local helper that blocks",
    }

    def check(self, sf: SourceFile, ctx: ProjectContext) -> Iterator[Finding]:
        tree = sf.tree
        awaited = {id(n.value) for n in ast.walk(tree) if isinstance(n, ast.Await)}

        # -- pass 1: sync defs (module-level or methods) that block -------
        # maps "name" and "self.name" call shapes to the first blocking
        # line inside the helper, for ASY004 one-hop reachability. Only the
        # helper's OWN body counts: nested defs are separate callables whose
        # blocking calls must not be attributed to the enclosing function.
        def own_nodes(fn: ast.FunctionDef):
            stack = list(fn.body)
            while stack:
                n = stack.pop()
                yield n
                if not isinstance(
                    n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
                ):
                    stack.extend(ast.iter_child_nodes(n))

        # blockers are scoped: module-level helpers by bare name, methods by
        # (class, name) — a blocking `A.flush` must never be attributed to
        # an unrelated `B.flush` called as `self.flush()` elsewhere
        module_blockers: dict[str, tuple[str, int]] = {}
        method_blockers: dict[tuple[str, str], tuple[str, int]] = {}

        def first_block(fn: ast.FunctionDef) -> tuple[str, int] | None:
            for sub in own_nodes(fn):
                if isinstance(sub, ast.Call):
                    hit = _blocking_rule(sub)
                    if hit and hit[0] in ("ASY001", "ASY002"):
                        return (hit[1], sub.lineno)
            return None

        for node in tree.body:
            if isinstance(node, ast.FunctionDef):
                hit = first_block(node)
                if hit:
                    module_blockers[node.name] = hit
            elif isinstance(node, ast.ClassDef):
                for meth in node.body:
                    if isinstance(meth, ast.FunctionDef):
                        hit = first_block(meth)
                        if hit:
                            method_blockers[(node.name, meth.name)] = hit

        def enclosing_class(node: ast.AST) -> str | None:
            cur = sf.parents.get(id(node))
            while cur is not None:
                if isinstance(cur, ast.ClassDef):
                    return cur.name
                cur = sf.parents.get(id(cur))
            return None

        # -- pass 2: walk async bodies ------------------------------------
        def visit(node: ast.AST, in_async: bool) -> Iterator[Finding]:
            if isinstance(node, ast.AsyncFunctionDef):
                for child in node.body:
                    yield from visit(child, True)
                return
            if isinstance(node, (ast.FunctionDef, ast.Lambda, ast.ClassDef)):
                body = node.body if not isinstance(node, ast.Lambda) else [node.body]
                for child in body:
                    yield from visit(child, False)
                return
            if in_async and isinstance(node, ast.Call) and id(node) not in awaited:
                hit = _blocking_rule(node)
                if hit:
                    rule, token = hit
                    hint = {
                        "ASY001": "use `await asyncio.sleep(...)`",
                        "ASY002": "use aiohttp or `loop.run_in_executor`",
                        "ASY003": "a threading lock blocks the whole event loop",
                    }[rule]
                    yield Finding(
                        rule=rule,
                        path=sf.relpath,
                        line=node.lineno,
                        message=f"blocking call `{token}` in async context; {hint}",
                        key=make_key(rule, sf.relpath, sf.scope_of(node), token),
                    )
                else:
                    # one-hop: plain-name call into a module-level helper,
                    # or self-method call into a method of THIS class
                    callee = None
                    blocked = None
                    if isinstance(node.func, ast.Name):
                        callee = node.func.id
                        blocked = module_blockers.get(callee)
                    elif (
                        isinstance(node.func, ast.Attribute)
                        and isinstance(node.func.value, ast.Name)
                        and node.func.value.id == "self"
                    ):
                        callee = node.func.attr
                        cls = enclosing_class(node)
                        if cls is not None:
                            blocked = method_blockers.get((cls, callee))
                    if blocked is not None:
                        blocked_by, bline = blocked
                        yield Finding(
                            rule="ASY004",
                            path=sf.relpath,
                            line=node.lineno,
                            message=(
                                f"async context calls `{callee}` which blocks "
                                f"(`{blocked_by}` at line {bline}); run it in "
                                "an executor or make it async"
                            ),
                            key=make_key(
                                "ASY004", sf.relpath, sf.scope_of(node), callee
                            ),
                        )
            if in_async and isinstance(node, ast.With):
                for item in node.items:
                    ce = item.context_expr
                    if (
                        isinstance(ce, ast.Attribute)
                        and isinstance(ce.value, ast.Name)
                        and ce.value.id == "self"
                        and _LOCKISH_RE.search(ce.attr)
                    ):
                        yield Finding(
                            rule="ASY003",
                            path=sf.relpath,
                            line=node.lineno,
                            message=(
                                f"`with self.{ce.attr}:` in async context "
                                "blocks the event loop while contended; use "
                                "an asyncio primitive"
                            ),
                            key=make_key(
                                "ASY003",
                                sf.relpath,
                                sf.scope_of(node),
                                f"with:self.{ce.attr}",
                            ),
                        )
            for child in ast.iter_child_nodes(node):
                yield from visit(child, in_async)

        for top in tree.body:
            yield from visit(top, False)
