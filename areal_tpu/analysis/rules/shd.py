"""SHD — PartitionSpec / shard_map consistency against the declared mesh.

GSPMD sharding is stringly-typed: a ``PartitionSpec("modle")`` with a
typo'd axis raises nothing at construction — it fails at `device_put` /
trace time on a real mesh, or (worse) silently falls back to replication
under some jax versions' permissive paths. The mesh axis vocabulary is
declared exactly once (``parallel/mesh.py MESH_AXES``); every literal
axis name in the package must come from it. ``ProjectContext.mesh_axes``
carries the parsed tuple; a file can extend it locally by defining its
own ``MESH_AXES = (...)`` (the jax_compat shim and tests do).

  SHD001  PartitionSpec axis name not declared on the mesh
  SHD002  shard_map in_specs/out_specs arity differs from the wrapped
          function's signature (specs zip positionally with args; a
          mismatch is a TypeError at trace time at best, a silently
          mis-sharded closure capture at worst)
  SHD003  the same mesh axis used twice in one PartitionSpec — an array
          dimension cannot shard over an axis that another dimension
          already consumed

Only call sites whose callee name binds to ``jax.sharding.PartitionSpec``
(via import aliasing, e.g. ``PartitionSpec as P``) are checked, so an
unrelated local ``P(...)`` helper never false-positives. Non-literal
spec entries (names, unpacking) are skipped — unknown stays silent.
"""

from __future__ import annotations

import ast
from typing import Iterator

from areal_tpu.analysis.core import (
    Finding,
    ProjectContext,
    SourceFile,
    dotted_name,
    make_key,
)


def _spec_aliases(tree: ast.Module) -> set[str]:
    """Local names bound to jax.sharding.PartitionSpec."""
    aliases: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module and (
            node.module.startswith("jax")
        ):
            for a in node.names:
                if a.name == "PartitionSpec":
                    aliases.add(a.asname or a.name)
        elif isinstance(node, ast.Assign) and isinstance(
            node.value, (ast.Name, ast.Attribute)
        ):
            d = dotted_name(node.value)
            if d in ("jax.sharding.PartitionSpec", "PartitionSpec"):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        aliases.add(t.id)
    return aliases


def _local_mesh_axes(tree: ast.Module) -> frozenset[str] | None:
    for node in tree.body:
        if isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == "MESH_AXES"
            for t in node.targets
        ):
            if isinstance(node.value, (ast.Tuple, ast.List)):
                return frozenset(
                    e.value
                    for e in node.value.elts
                    if isinstance(e, ast.Constant) and isinstance(e.value, str)
                )
    return None


def _declared_mesh_axes(tree: ast.Module) -> frozenset[str]:
    """Axis names a file declares itself by constructing a Mesh:
    ``Mesh(devs, ("stage",))`` / ``axis_names=(...)`` — tests and smoke
    scripts build ad-hoc meshes whose axes are legitimate in that file."""
    mesh_names = {"Mesh", "make_mesh"}
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module and (
            node.module.startswith("jax")
        ):
            for a in node.names:
                if a.name == "Mesh" and a.asname:
                    mesh_names.add(a.asname)
    axes: set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        d = dotted_name(node.func)
        if d is None or d.split(".")[-1] not in mesh_names:
            continue
        candidates: list[ast.expr] = []
        if len(node.args) >= 2:
            candidates.append(node.args[1])
        for kw in node.keywords:
            if kw.arg == "axis_names":
                candidates.append(kw.value)
        for cand in candidates:
            if isinstance(cand, (ast.Tuple, ast.List)):
                for e in cand.elts:
                    if isinstance(e, ast.Constant) and isinstance(e.value, str):
                        axes.add(e.value)
            elif isinstance(cand, ast.Constant) and isinstance(cand.value, str):
                axes.add(cand.value)
    return frozenset(axes)


def _literal_axes(entry: ast.expr) -> list[str] | None:
    """Axis strings of one spec entry: "axis", ("a", "b"), or None.
    Returns None when the entry is not fully literal (skip)."""
    if isinstance(entry, ast.Constant):
        if entry.value is None:
            return []
        if isinstance(entry.value, str):
            return [entry.value]
        return None
    if isinstance(entry, (ast.Tuple, ast.List)):
        out: list[str] = []
        for e in entry.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                out.append(e.value)
            elif isinstance(e, ast.Constant) and e.value is None:
                continue
            else:
                return None
        return out
    return None


def _positional_arity(fn: ast.AST) -> int | None:
    """Positional parameter count (None when *args makes it open)."""
    args = getattr(fn, "args", None)
    if args is None:
        return None
    if args.vararg is not None:
        return None
    n = len(args.posonlyargs) + len(args.args)
    if n and args.args and args.args[0].arg in ("self", "cls"):
        n -= 1
    return n


class ShardingSpecChecker:
    FAMILY = "SHD"
    RULES = {
        "SHD001": "PartitionSpec axis not declared on the mesh",
        "SHD002": "shard_map spec arity differs from function signature",
        "SHD003": "mesh axis used twice in one PartitionSpec",
    }

    def check(self, sf: SourceFile, ctx: ProjectContext) -> Iterator[Finding]:
        axes = _local_mesh_axes(sf.tree)
        if axes is None:
            axes = ctx.mesh_axes
        axes = frozenset(axes | _declared_mesh_axes(sf.tree))
        aliases = _spec_aliases(sf.tree)
        if aliases and axes:
            yield from self._check_specs(sf, aliases, axes)
        yield from self._check_shard_map(sf)

    def _check_specs(
        self, sf: SourceFile, aliases: set[str], axes: frozenset[str]
    ) -> Iterator[Finding]:
        for call in ast.walk(sf.tree):
            if not isinstance(call, ast.Call):
                continue
            if not (
                isinstance(call.func, ast.Name) and call.func.id in aliases
            ):
                continue
            seen: dict[str, int] = {}
            for entry in call.args:
                lit = _literal_axes(entry)
                if lit is None:
                    continue
                for axis in lit:
                    if axis not in axes:
                        yield Finding(
                            rule="SHD001",
                            path=sf.relpath,
                            line=call.lineno,
                            message=(
                                f"PartitionSpec axis '{axis}' is not a "
                                f"declared mesh axis "
                                f"({', '.join(sorted(axes))}); typo'd axes "
                                "fail only at trace time on a real mesh"
                            ),
                            key=make_key(
                                "SHD001",
                                sf.relpath,
                                sf.scope_of(call),
                                axis,
                            ),
                        )
                    seen[axis] = seen.get(axis, 0) + 1
            for axis, count in seen.items():
                if count > 1 and axis in axes:
                    yield Finding(
                        rule="SHD003",
                        path=sf.relpath,
                        line=call.lineno,
                        message=(
                            f"mesh axis '{axis}' appears {count} times in "
                            "one PartitionSpec: a dimension cannot shard "
                            "over an axis another dimension already consumed"
                        ),
                        key=make_key(
                            "SHD003",
                            sf.relpath,
                            sf.scope_of(call),
                            f"dup:{axis}",
                        ),
                    )

    def _check_shard_map(self, sf: SourceFile) -> Iterator[Finding]:
        # local defs by name for callee resolution — vetoed for any name
        # that is ALSO the target of an assignment somewhere in the file
        # (`fn = gpipe(...)` must not resolve to an unrelated `def fn`)
        local_defs: dict[str, ast.AST] = {}
        assigned: set[str] = set()
        for node in ast.walk(sf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                local_defs[node.name] = node
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    els = t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]
                    for el in els:
                        if isinstance(el, ast.Name):
                            assigned.add(el.id)
        for name in assigned:
            local_defs.pop(name, None)
        for call in ast.walk(sf.tree):
            if not isinstance(call, ast.Call):
                continue
            d = dotted_name(call.func)
            if d is None or d.split(".")[-1] != "shard_map":
                continue
            if not call.args:
                continue
            target = call.args[0]
            fn: ast.AST | None = None
            if isinstance(target, ast.Lambda):
                fn = target
            elif isinstance(target, ast.Name):
                fn = local_defs.get(target.id)
            if fn is None:
                continue
            arity = _positional_arity(fn)
            if arity is None:
                continue
            kw = {k.arg: k.value for k in call.keywords if k.arg}
            in_specs = kw.get("in_specs")
            if len(call.args) >= 3 and in_specs is None:
                in_specs = call.args[2]  # shard_map(f, mesh, in_specs, ...)
            if isinstance(in_specs, (ast.Tuple, ast.List)) and (
                len(in_specs.elts) != arity
            ):
                yield Finding(
                    rule="SHD002",
                    path=sf.relpath,
                    line=call.lineno,
                    message=(
                        f"shard_map in_specs has {len(in_specs.elts)} "
                        f"entries but `{getattr(fn, 'name', '<lambda>')}` "
                        f"takes {arity} positional argument(s); specs zip "
                        "positionally with arguments"
                    ),
                    key=make_key(
                        "SHD002",
                        sf.relpath,
                        sf.scope_of(call),
                        getattr(fn, "name", "<lambda>"),
                    ),
                )
