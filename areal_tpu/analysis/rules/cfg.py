"""CFG — config-field drift between api/config.py dataclasses and call sites.

The config tree is plain dataclasses with no runtime attribute checking on
reads: ``cfg.max_concurent_rollouts`` (typo) raises AttributeError only on
the code path that executes it — in async RL that is often a rarely-taken
branch deep inside a worker. The rule type-tracks variables annotated or
constructed as api/config.py dataclasses (including ``self.config = ...``
captures and nested section chains like ``cfg.saver.freq_steps``) and
flags accesses that name no declared field. Scopes are walked with proper
environment chaining: a nested function inherits the enclosing bindings
minus any name it rebinds, so an inner parameter shadowing ``cfg`` never
borrows the outer type. Rules:

  CFG001  attribute access on a config dataclass that names no declared field
  CFG002  constructor keyword that names no declared field
  CFG003  ``getattr(cfg, "literal", default)`` whose literal names no
          declared field — the default silently masks drift: a typo in the
          literal (or a removed field) makes the call ALWAYS take the
          fallback, with no error on any path
"""

from __future__ import annotations

import ast
from typing import Iterator

from areal_tpu.analysis.core import (
    Finding,
    ProjectContext,
    SourceFile,
    config_class_of_annotation,
    make_key,
)

_ALLOWED = {
    "__class__",
    "__dict__",
    "__doc__",
    "__dataclass_fields__",
    "__module__",
}

_DEF = (ast.FunctionDef, ast.AsyncFunctionDef)


def _own_nodes(fn: ast.AST) -> Iterator[ast.AST]:
    """Nodes of ``fn``'s own body, yielding nested def/lambda/class nodes
    themselves but not descending into them (separate scopes)."""
    body = [fn.body] if isinstance(fn, ast.Lambda) else list(fn.body)
    stack: list[ast.AST] = body
    while stack:
        n = stack.pop()
        yield n
        if not isinstance(n, _DEF + (ast.Lambda, ast.ClassDef)):
            stack.extend(ast.iter_child_nodes(n))


def _params_of(fn: ast.AST) -> list[ast.arg]:
    a = fn.args
    out = list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)
    if a.vararg:
        out.append(a.vararg)
    if a.kwarg:
        out.append(a.kwarg)
    return out


def _bound_names(fn: ast.AST) -> set[str]:
    """Names (re)bound inside ``fn``'s own scope — these shadow the
    enclosing environment for nested lookups."""
    names = {p.arg for p in _params_of(fn)}
    for n in _own_nodes(fn):
        if isinstance(n, ast.Assign):
            for t in n.targets:
                names |= {x.id for x in ast.walk(t) if isinstance(x, ast.Name)}
        elif isinstance(n, (ast.AnnAssign, ast.AugAssign, ast.NamedExpr)):
            if isinstance(n.target, ast.Name):
                names.add(n.target.id)
        elif isinstance(n, (ast.For, ast.AsyncFor)):
            names |= {x.id for x in ast.walk(n.target) if isinstance(x, ast.Name)}
        elif isinstance(n, _DEF + (ast.ClassDef,)):
            names.add(n.name)
    return names


class ConfigDriftChecker:
    FAMILY = "CFG"
    RULES = {
        "CFG001": "attribute access names no declared config field",
        "CFG002": "constructor keyword names no declared config field",
        "CFG003": "getattr literal names no declared config field",
    }

    def check(self, sf: SourceFile, ctx: ProjectContext) -> Iterator[Finding]:
        if not ctx.config_fields:
            return
        # the registry source itself defines the classes; analyzing it
        # against itself only produces noise on the loader helpers
        if sf.relpath.endswith("api/config.py"):
            return
        registry = ctx.config_fields
        # skip shadowed names: a module defining its own class of the same
        # name is not talking about the config tree
        shadowed = {
            n.name
            for n in ast.walk(sf.tree)
            if isinstance(n, ast.ClassDef) and n.name in registry
        }
        known_names = set(registry) - shadowed

        def class_of_annotation(ann: ast.expr | None) -> str | None:
            return config_class_of_annotation(ann, known_names)

        def class_of_call(call: ast.Call) -> str | None:
            name = None
            if isinstance(call.func, ast.Name):
                name = call.func.id
            elif isinstance(call.func, ast.Attribute):
                name = call.func.attr
            return name if name in known_names else None

        # -- per-class: self.<attr> captures of config-typed values --------
        # (class name, attr) -> config class
        self_attr_types: dict[tuple[str, str], str] = {}
        for cls in (n for n in ast.walk(sf.tree) if isinstance(n, ast.ClassDef)):
            for meth in (n for n in cls.body if isinstance(n, _DEF)):
                param_types = {
                    a.arg: class_of_annotation(a.annotation)
                    for a in _params_of(meth)
                }
                for stmt in _own_nodes(meth):
                    if not isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                        continue
                    targets = (
                        stmt.targets
                        if isinstance(stmt, ast.Assign)
                        else [stmt.target]
                    )
                    value = stmt.value
                    vtype: str | None = None
                    if isinstance(stmt, ast.AnnAssign):
                        vtype = class_of_annotation(stmt.annotation)
                    if vtype is None and isinstance(value, ast.Name):
                        vtype = param_types.get(value.id)
                    if vtype is None and isinstance(value, ast.Call):
                        vtype = class_of_call(value)
                    if vtype is None:
                        continue
                    for t in targets:
                        if (
                            isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"
                        ):
                            key = (cls.name, t.attr)
                            if self_attr_types.get(key, vtype) != vtype:
                                self_attr_types[key] = "__conflict__"
                            else:
                                self_attr_types[key] = vtype

        def type_of(
            expr: ast.AST, env: dict[str, str], cls_name: str | None
        ) -> str | None:
            if isinstance(expr, ast.Name):
                return env.get(expr.id)
            if isinstance(expr, ast.Call):
                return class_of_call(expr)
            if isinstance(expr, ast.Attribute):
                if (
                    isinstance(expr.value, ast.Name)
                    and expr.value.id == "self"
                    and cls_name is not None
                ):
                    t = self_attr_types.get((cls_name, expr.attr))
                    if t and t != "__conflict__":
                        return t
                base = type_of(expr.value, env, cls_name)
                if base is None:
                    return None
                return ctx.config_field_types.get(base, {}).get(expr.attr)
            return None

        def check_ctor_kwargs(node: ast.Call) -> Iterator[Finding]:
            base = class_of_call(node)
            if base is None:
                return
            fields = ctx.config_fields.get(base, set())
            for kw in node.keywords:
                if kw.arg is not None and kw.arg not in fields:
                    yield Finding(
                        rule="CFG002",
                        path=sf.relpath,
                        line=node.lineno,
                        message=(
                            f"`{base}(...)` has no field `{kw.arg}`; the "
                            "constructor will raise TypeError at runtime"
                        ),
                        key=make_key(
                            "CFG002",
                            sf.relpath,
                            sf.scope_of(node),
                            f"{base}.{kw.arg}",
                        ),
                    )

        def allowed_attrs(base: str) -> set[str]:
            return (
                ctx.config_fields.get(base, set())
                | ctx.config_methods.get(base, set())
                | _ALLOWED
            )

        seen_calls: set[int] = set()

        def check_scope(
            fn: ast.AST, outer_env: dict[str, str], cls_name: str | None
        ) -> Iterator[Finding]:
            """Check one function scope with proper environment chaining,
            then recurse into nested scopes."""
            env = {
                k: v for k, v in outer_env.items() if k not in _bound_names(fn)
            }
            for p in _params_of(fn):
                t = class_of_annotation(p.annotation)
                if t:
                    env[p.arg] = t
            for stmt in _own_nodes(fn):
                if isinstance(stmt, ast.AnnAssign) and isinstance(
                    stmt.target, ast.Name
                ):
                    t = class_of_annotation(stmt.annotation)
                    if t:
                        env[stmt.target.id] = t
                elif isinstance(stmt, ast.Assign):
                    t = None
                    if isinstance(stmt.value, ast.Call):
                        t = class_of_call(stmt.value)
                    elif (
                        isinstance(stmt.value, ast.Attribute)
                        and isinstance(stmt.value.value, ast.Name)
                        and stmt.value.value.id == "self"
                        and cls_name is not None
                    ):
                        cand = self_attr_types.get((cls_name, stmt.value.attr))
                        if cand and cand != "__conflict__":
                            t = cand
                    if t:
                        for tgt in stmt.targets:
                            if isinstance(tgt, ast.Name):
                                env[tgt.id] = t

            for node in _own_nodes(fn):
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "getattr"
                    and len(node.args) >= 2
                ):
                    seen_calls.add(id(node))
                    base = type_of(node.args[0], env, cls_name)
                    name = None
                    if isinstance(node.args[1], ast.Constant) and isinstance(
                        node.args[1].value, str
                    ):
                        name = node.args[1].value
                    if base is not None and name is not None:
                        if name not in allowed_attrs(base):
                            yield Finding(
                                rule="CFG003",
                                path=sf.relpath,
                                line=node.lineno,
                                message=(
                                    f"getattr names `{name}`, which is not "
                                    f"a declared field of `{base}` — the "
                                    "fallback masks drift (declare the "
                                    "field, or suppress with the subclass "
                                    "that provides it)"
                                ),
                                key=make_key(
                                    "CFG003",
                                    sf.relpath,
                                    sf.scope_of(node),
                                    f"{base}.{name}",
                                ),
                            )
                elif isinstance(node, ast.Attribute):
                    base = type_of(node.value, env, cls_name)
                    if base is not None and node.attr not in allowed_attrs(base):
                        yield Finding(
                            rule="CFG001",
                            path=sf.relpath,
                            line=node.lineno,
                            message=(
                                f"`{base}` has no field `{node.attr}` "
                                "(declared fields: see api/config.py)"
                            ),
                            key=make_key(
                                "CFG001",
                                sf.relpath,
                                sf.scope_of(node),
                                f"{base}.{node.attr}",
                            ),
                        )
                elif isinstance(node, ast.Call):
                    seen_calls.add(id(node))
                    yield from check_ctor_kwargs(node)

            # nested scopes inherit this env (minus their own bindings)
            for node in _own_nodes(fn):
                if isinstance(node, _DEF + (ast.Lambda,)):
                    yield from check_scope(node, env, cls_name)
                elif isinstance(node, ast.ClassDef):
                    for meth in node.body:
                        if isinstance(meth, _DEF):
                            yield from check_scope(meth, env, node.name)

        # drive: every def not nested inside another def, with class context
        def scan(node: ast.AST, cls_name: str | None) -> Iterator[Finding]:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, _DEF):
                    yield from check_scope(child, {}, cls_name)
                elif isinstance(child, ast.ClassDef):
                    yield from scan(child, child.name)
                elif not isinstance(child, ast.Lambda):
                    yield from scan(child, cls_name)

        yield from scan(sf.tree, None)

        # constructor kwargs are checkable anywhere, including module scope
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Call) and id(node) not in seen_calls:
                seen_calls.add(id(node))
                yield from check_ctor_kwargs(node)
