"""PRF — host-device synchronization on hot paths.

The paper's throughput claim rests on the device never waiting for the
host: the decode loop dispatches chunk N+1 before pulling chunk N's
tokens, and the trainer queues every microbatch before reading a single
stat. One blocking read in the wrong place re-serializes all of it —
``float(device_scalar)`` stalls host dispatch until the device drains,
and inside a per-microbatch or per-token loop that happens every
iteration. None of this raises; it just shows up as bubble fraction in
the PR 9 step timeline.

The family is dataflow-gated (analysis/dataflow.py): a site only fires
when its enclosing function is *hot-path reachable* (call-graph BFS from
the decode loop / trainer step seeds, jit-traced callables, and
``# arealint: hot-path`` markers), and value-dependent shapes
(``float(x)``, ``np.asarray(x)``) additionally require ``x`` to have
*device* origin. Cold-path syncs and host-array conversions never fire.

  PRF001  explicit sync API on a hot path (`jax.device_get`,
          `block_until_ready`) outside a loop — one blocking round-trip
          per call; batch it at a chunk/step boundary or suppress with
          the boundary rationale
  PRF002  device->host coercion on a hot path (`float()`/`int()`/
          `bool()`/`np.asarray()`/`.item()` on a device value) outside
          a loop
  PRF003  any of the above lexically inside a `for`/`while` loop of a
          hot function — one blocking round-trip *per iteration*; hoist
          the read out of the loop and fetch once at the boundary
"""

from __future__ import annotations

import ast
from typing import Iterator

from areal_tpu.analysis.core import (
    Finding,
    ProjectContext,
    SourceFile,
    dotted_name,
    make_key,
)
from areal_tpu.analysis import dataflow
from areal_tpu.analysis.dataflow import DEVICE, OriginTracker

_SYNC_CALLS = {"jax.device_get", "jax.block_until_ready"}
_COERCIONS = {"float", "int", "bool"}
_NP_TRANSFERS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array"}


class HotPathSyncChecker:
    FAMILY = "PRF"
    RULES = {
        "PRF001": "blocking sync API on a hot path",
        "PRF002": "device->host coercion on a hot path",
        "PRF003": "per-iteration device sync inside a hot-path loop",
    }

    def check(self, sf: SourceFile, ctx: ProjectContext) -> Iterator[Finding]:
        graph = ctx.graph_for(sf)
        hot = graph.hot_funcs_in(sf.relpath)
        if not hot:
            return
        mod = graph.modules[sf.relpath]
        jit_idx = mod.jit_index()
        device_names = set(jit_idx.direct) | set(jit_idx.getters)
        attr_cache: dict[str, set[str]] = {}

        for fid, (fi, seed) in hot.items():
            fn = fi.node
            if isinstance(fn, ast.Lambda):
                continue
            if fi.cls is not None and fi.cls not in attr_cache:
                attr_cache[fi.cls] = dataflow.device_attrs_of_class(
                    mod, fi.cls
                )
            tracker = OriginTracker(
                fn,
                device_names=device_names,
                device_attrs=attr_cache.get(fi.cls or "", set()),
                jit_index=jit_idx,
            )
            yield from self._scan(sf, fi, seed, tracker)

    # -- per-function scan -------------------------------------------------
    def _scan(
        self, sf: SourceFile, fi, seed: str, tracker: OriginTracker
    ) -> Iterator[Finding]:
        fn = fi.node
        where = (
            "" if seed == fi.qualname else f", reachable from hot `{seed}`"
        )

        def emit(node: ast.AST, in_loop: bool, what: str, token: str) -> Finding:
            if in_loop:
                rule = "PRF003"
                msg = (
                    f"{what} inside a loop of hot-path function "
                    f"`{fi.qualname}`{where}: one blocking device round-trip "
                    "per iteration — hoist the read and batch the transfer "
                    "at the chunk/step boundary"
                )
            else:
                rule = "PRF001" if what.startswith("sync API") else "PRF002"
                msg = (
                    f"{what} in hot-path function `{fi.qualname}`{where}: "
                    "blocks host dispatch until the device drains"
                )
            return Finding(
                rule=rule,
                path=sf.relpath,
                line=node.lineno,
                message=msg,
                key=make_key(rule, sf.relpath, sf.scope_of(node), token),
            )

        # walk own nodes tracking loop depth; nested defs are separate
        # graph nodes (hot on their own merit), so stop at them
        def walk(node: ast.AST, in_loop: bool) -> Iterator[tuple[ast.AST, bool]]:
            for child in ast.iter_child_nodes(node):
                if isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
                ):
                    continue
                child_in_loop = in_loop or isinstance(
                    node, (ast.For, ast.AsyncFor, ast.While)
                ) and child in (
                    getattr(node, "body", []) + getattr(node, "orelse", [])
                )
                yield child, child_in_loop
                yield from walk(child, child_in_loop)

        for node, in_loop in walk(fn, False):
            if not isinstance(node, ast.Call):
                continue
            d = dotted_name(node.func)
            if d in _SYNC_CALLS:
                yield emit(node, in_loop, f"sync API `{d}`", d)
                continue
            # x.block_until_ready()
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "block_until_ready"
            ):
                yield emit(
                    node, in_loop, "sync API `block_until_ready`",
                    "block_until_ready",
                )
                continue
            # .item() on a device value
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "item"
                and tracker.origin_of(node.func.value) == DEVICE
            ):
                yield emit(
                    node, in_loop, "device->host read `.item()`", "item"
                )
                continue
            # float()/int()/bool()/np.asarray() on a device value
            if d in _COERCIONS or d in _NP_TRANSFERS:
                if node.args and tracker.origin_of(node.args[0]) == DEVICE:
                    yield emit(
                        node,
                        in_loop,
                        f"device->host coercion `{d}(...)` of a device value",
                        d,
                    )
