"""SIG — signal handlers must only set flags/events.

A CPython signal handler runs on the main thread between two arbitrary
bytecodes. Whatever the interrupted code was holding — the flight-ring
lock, a metrics shard lock, a half-mutated dict — is frozen underneath the
handler, so anything beyond flipping a flag risks deadlock (blocking on a
lock the frozen frame holds), re-entrancy corruption, or eating the
platform's preemption grace window inside the handler itself. The
sanctioned pattern (robustness/preemption.py): the handler sets a
``threading.Event``; a pre-armed drainer thread or the owning loop does
the real work. Rules:

  SIG001  blocking call in signal-handler context: file/network I/O,
          ``time.sleep``, ``.join``/``.wait``/``.acquire``, subprocess,
          logging, print, flight/ring dumps
  SIG002  lock usage in signal-handler context (``with <lock>:`` or
          ``.acquire()``) — the interrupted frame may already hold it
  SIG003  allocation of threads/processes/executors or bulk containers
          (comprehensions) in signal-handler context

Handler context = the function registered via ``signal.signal(sig, fn)``
(named function, ``self.method``, or lambda), plus same-file helpers it
calls DIRECTLY. Functions merely referenced (e.g. as a ``Thread`` target —
they run on that thread, not in handler context) are not followed.
``asyncio`` ``add_signal_handler`` callbacks run on the event loop, not in
handler context, and are exempt. Allowed in handlers: assignments,
``Event.set/clear``, ``signal.*`` re-arming, clock reads
(``time.monotonic``/``time.time``), ``os.kill``/``os._exit``/
``sys.exit``, and control flow.
"""

from __future__ import annotations

import ast
from typing import Iterator

from areal_tpu.analysis.core import (
    Finding,
    ProjectContext,
    SourceFile,
    dotted_name,
    make_key,
)

# exact dotted callees that block (I/O, sleeps, process waits)
_BLOCKING_NAMES = {
    "open",
    "print",
    "time.sleep",
    "os.system",
    "os.fsync",
    "os.makedirs",
    "os.replace",
    "os.rename",
    "os.remove",
    "os.unlink",
    "input",
}
_BLOCKING_PREFIXES = (
    "urllib.",
    "requests.",
    "socket.",
    "http.client.",
    "shutil.",
    "subprocess.",
    "logging.",
    "pickle.",
    "json.",
)
# attribute-call suffixes that block wherever they appear
_BLOCKING_SUFFIXES = {
    "join",
    "wait",
    "sleep",
    "urlopen",
    "dump",
    "dumps",  # ring/trace dumps write disk (FlightRecorder.dump)
    "flush",
    "fsync",
    "write",
    "read",
    "recv",
    "send",
    "sendall",
    "connect",
}
# names that are (or conventionally hold) loggers — logging takes the
# logging module's module-level lock AND writes to a stream
_LOGGERISH = {"logger", "log", "logging", "alog"}
_LOG_METHODS = {
    "debug",
    "info",
    "warning",
    "warn",
    "error",
    "exception",
    "critical",
}
_THREADISH_CTORS = {
    "threading.Thread",
    "Thread",
    "multiprocessing.Process",
    "Process",
    "subprocess.Popen",
    "Popen",
    "ThreadPoolExecutor",
    "ProcessPoolExecutor",
    "concurrent.futures.ThreadPoolExecutor",
    "concurrent.futures.ProcessPoolExecutor",
}
_COMPREHENSIONS = (ast.ListComp, ast.DictComp, ast.SetComp, ast.GeneratorExp)


def _last_part(dotted: str) -> str:
    return dotted.rsplit(".", 1)[-1]


def _lockish(dotted: str | None) -> bool:
    if not dotted:
        return False
    last = _last_part(dotted).lower()
    return any(t in last for t in ("lock", "mutex", "cv", "cond", "sem"))


def _iter_direct(root: ast.AST):
    """Walk without entering nested defs/lambdas/classes — code inside a
    nested def does not run in handler context unless called (the one-hop
    resolution below handles direct calls)."""
    stack = list(ast.iter_child_nodes(root))
    while stack:
        n = stack.pop()
        if isinstance(
            n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)
        ):
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


class SignalSafetyChecker:
    FAMILY = "SIG"
    RULES = {
        "SIG001": "blocking call in signal-handler context",
        "SIG002": "lock usage in signal-handler context",
        "SIG003": "allocation/thread creation in signal-handler context",
    }

    # -- handler discovery -------------------------------------------------
    def _defs_by_name(self, sf: SourceFile) -> dict[str, list[ast.AST]]:
        out: dict[str, list[ast.AST]] = {}
        for node in ast.walk(sf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.setdefault(node.name, []).append(node)
        return out

    def _handler_roots(self, sf: SourceFile) -> list[tuple[str, ast.AST]]:
        """(handler_name, body_root) for every resolvable handler passed to
        ``signal.signal``. Unresolvable expressions (``prev or SIG_DFL``,
        ``signal.SIG_IGN``, names imported from elsewhere) are skipped —
        this rule is about handlers defined here."""
        defs = self._defs_by_name(sf)
        roots: list[tuple[str, ast.AST]] = []
        for node in ast.walk(sf.tree):
            if not (isinstance(node, ast.Call) and len(node.args) >= 2):
                continue
            callee = dotted_name(node.func)
            if callee not in ("signal.signal", "signal"):
                continue
            target = node.args[1]
            if isinstance(target, ast.Lambda):
                roots.append(("<lambda>", target))
                continue
            name = None
            if isinstance(target, ast.Name):
                name = target.id
            elif isinstance(target, ast.Attribute):
                # self._on_signal / handler.method — resolve by attr name
                name = target.attr
            if name and not name.startswith("SIG"):
                for d in defs.get(name, []):
                    roots.append((name, d))
        return roots

    # -- body analysis -----------------------------------------------------
    def _analyze(
        self,
        sf: SourceFile,
        handler: str,
        root: ast.AST,
        defs: dict[str, list[ast.AST]],
        seen: set[int],
        depth: int,
    ) -> Iterator[Finding]:
        if id(root) in seen or depth > 2:
            return
        seen.add(id(root))
        via = "" if depth == 0 else f" (reached from handler '{handler}')"
        for node in _iter_direct(root):
            if isinstance(node, _COMPREHENSIONS):
                yield Finding(
                    rule="SIG003",
                    path=sf.relpath,
                    line=node.lineno,
                    message=(
                        "bulk container built in signal-handler context"
                        + via
                        + "; handlers must only set flags/events"
                    ),
                    key=make_key(
                        "SIG003", sf.relpath, f"handler:{handler}", "comprehension"
                    ),
                )
                continue
            if isinstance(node, ast.With):
                for item in node.items:
                    ctx = item.context_expr
                    ctx_name = dotted_name(
                        ctx.func if isinstance(ctx, ast.Call) else ctx
                    )
                    if _lockish(ctx_name):
                        yield Finding(
                            rule="SIG002",
                            path=sf.relpath,
                            line=node.lineno,
                            message=(
                                f"`with {ctx_name}:` in signal-handler "
                                "context" + via + "; the interrupted frame "
                                "may already hold the lock (deadlock)"
                            ),
                            key=make_key(
                                "SIG002",
                                sf.relpath,
                                f"handler:{handler}",
                                ctx_name or "with",
                            ),
                        )
                continue
            if not isinstance(node, ast.Call):
                continue
            callee = dotted_name(node.func)
            last = _last_part(callee) if callee else (
                node.func.attr if isinstance(node.func, ast.Attribute) else ""
            )
            if not callee and not last:
                continue
            # allowed portals: Event.set/clear, signal re-arm, clock reads,
            # process exits, os.kill
            if last in ("set", "clear", "is_set", "monotonic", "time", "kill",
                        "_exit", "exit", "raise_signal", "getsignal", "signal"):
                continue
            if callee in _THREADISH_CTORS or (
                callee and _last_part(callee) in {"Thread", "Process", "Popen"}
            ):
                yield Finding(
                    rule="SIG003",
                    path=sf.relpath,
                    line=node.lineno,
                    message=(
                        f"`{callee}` created in signal-handler context"
                        + via
                        + "; arm worker threads BEFORE installing the "
                        "handler and have the handler set their event"
                    ),
                    key=make_key(
                        "SIG003", sf.relpath, f"handler:{handler}", callee or last
                    ),
                )
                continue
            if last == "acquire":
                yield Finding(
                    rule="SIG002",
                    path=sf.relpath,
                    line=node.lineno,
                    message=(
                        f"`{callee or last}` in signal-handler context"
                        + via
                        + "; the interrupted frame may already hold the "
                        "lock (deadlock)"
                    ),
                    key=make_key(
                        "SIG002", sf.relpath, f"handler:{handler}", callee or last
                    ),
                )
                continue
            blocking = (
                (callee in _BLOCKING_NAMES)
                or (
                    callee
                    and any(callee.startswith(p) for p in _BLOCKING_PREFIXES)
                )
                or (last in _BLOCKING_SUFFIXES)
                or (
                    isinstance(node.func, ast.Attribute)
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id in _LOGGERISH
                    and last in _LOG_METHODS
                )
            )
            if blocking:
                yield Finding(
                    rule="SIG001",
                    path=sf.relpath,
                    line=node.lineno,
                    message=(
                        f"blocking call `{callee or last}` in signal-handler "
                        "context" + via + "; handlers must only set "
                        "flags/events — move the work to a pre-armed "
                        "drainer thread"
                    ),
                    key=make_key(
                        "SIG001", sf.relpath, f"handler:{handler}", callee or last
                    ),
                )
                continue
            # one-hop reachability: a same-file function called DIRECTLY
            # runs in handler context too
            if isinstance(node.func, ast.Name) and node.func.id in defs:
                for d in defs[node.func.id]:
                    yield from self._analyze(
                        sf, handler, d, defs, seen, depth + 1
                    )

    def check(self, sf: SourceFile, ctx: ProjectContext) -> Iterator[Finding]:
        roots = self._handler_roots(sf)
        if not roots:
            return
        defs = self._defs_by_name(sf)
        for handler, root in roots:
            yield from self._analyze(sf, handler, root, defs, set(), 0)
