"""JAX — purity of functions captured by jit/pjit/scan/grad traces.

XLA traces a Python function ONCE per (shape, dtype) signature and replays
the compiled artifact forever after. Any side effect inside the traced
function — a print, a logger call, host RNG, mutation of ``self`` — runs
only during tracing and silently disappears (or worse, bakes a
trace-time value into every subsequent step). Iterating a ``set`` during
tracing produces host-dependent HLO: two processes of an SPMD job can
compile DIFFERENT programs and deadlock at the first collective. Rules:

  JAX001  print/logging inside a traced function (runs at trace time only)
  JAX002  host RNG or wall-clock read inside a traced function (frozen
          into the compiled program; use jax.random with threaded keys)
  JAX003  mutation of enclosing state (``self.x = ...``, global/nonlocal)
          inside a traced function (applied at trace time only)
  JAX004  iteration over a set inside a traced function (nondeterministic
          trace order; SPMD processes may compile different programs)
  JAX005  dynamic ``getattr`` inside a traced function (the resolved
          attribute — and, with a default, the fallback decision — is
          frozen into the compiled program and invisible to the jit
          cache key; hoist the read to host code before tracing)

Traced functions are found from decorators (``@jax.jit``,
``@partial(jax.jit, ...)``), call sites (``jax.jit(f)``,
``lax.scan(body, ...)``, ``jax.value_and_grad(lf)`` …), and then expanded
TRANSITIVELY: calls from traced code into same-class methods
(``self._outputs_fn(...)``), locally-defined helpers, and simple aliases
(``ofn = self._a if cond else self._b``) mark those bodies traced too,
because jit purity is a property of everything the trace reaches.
"""

from __future__ import annotations

import ast
from typing import Iterator

from areal_tpu.analysis.core import (
    Finding,
    ProjectContext,
    SourceFile,
    dotted_name,
    make_key,
)

_JIT_NAMES = {"jax.jit", "jit", "pjit", "jax.pjit", "jax.experimental.pjit.pjit"}
# dotted transform -> positions of traced-callable arguments
_TRACED_ARGS = {
    "jax.jit": (0,),
    "jit": (0,),
    "pjit": (0,),
    "jax.pjit": (0,),
    "jax.grad": (0,),
    "jax.value_and_grad": (0,),
    "jax.vmap": (0,),
    "jax.pmap": (0,),
    "jax.checkpoint": (0,),
    "jax.remat": (0,),
    "jax.lax.scan": (0,),
    "lax.scan": (0,),
    "jax.lax.while_loop": (0, 1),
    "lax.while_loop": (0, 1),
    "jax.lax.cond": (1, 2),
    "lax.cond": (1, 2),
    "jax.lax.fori_loop": (2,),
    "lax.fori_loop": (2,),
}
_CLOCK_CALLS = {
    "time.time",
    "time.monotonic",
    "time.perf_counter",
    "time.time_ns",
    "time.process_time",
    "datetime.datetime.now",
    "datetime.datetime.utcnow",
    "uuid.uuid4",
}
_LOG_METHODS = {
    "debug", "info", "warning", "error", "exception", "critical", "log",
}
_LOGGERISH = ("logger", "logging", "log", "alog")


def _is_partial_of_jit(call: ast.Call) -> bool:
    fn = dotted_name(call.func)
    if fn not in ("partial", "functools.partial"):
        return False
    return bool(call.args) and dotted_name(call.args[0]) in _JIT_NAMES


class JaxPurityChecker:
    FAMILY = "JAX"
    RULES = {
        "JAX001": "print/logging inside a jit-traced function",
        "JAX002": "host RNG or clock read inside a jit-traced function",
        "JAX003": "state mutation inside a jit-traced function",
        "JAX004": "set iteration inside a jit-traced function",
        "JAX005": "dynamic getattr inside a jit-traced function",
    }
    _MAX_HOPS = 4  # transitive trace-following depth bound

    def check(self, sf: SourceFile, ctx: ProjectContext) -> Iterator[Finding]:
        tree = sf.tree
        has_import_random = any(
            isinstance(n, ast.Import)
            and any(a.name == "random" for a in n.names)
            for n in ast.walk(tree)
        )

        traced: list[ast.AST] = []  # FunctionDef/AsyncFunctionDef/Lambda nodes

        # decorator-marked defs
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for dec in node.decorator_list:
                d = dotted_name(dec)
                if d in _JIT_NAMES:
                    traced.append(node)
                elif isinstance(dec, ast.Call) and (
                    dotted_name(dec.func) in _JIT_NAMES or _is_partial_of_jit(dec)
                ):
                    traced.append(node)

        # call-site-marked callables: jax.jit(f), lax.scan(body, ...), ...
        def resolve_local_def(name: str, from_node: ast.AST) -> ast.AST | None:
            """Nearest enclosing scope's def with this name (lexical)."""
            cur: ast.AST | None = from_node
            while cur is not None:
                cur = sf.parents.get(id(cur))
                if cur is None or isinstance(
                    cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Module)
                ):
                    for stmt in getattr(cur, "body", []):
                        if (
                            isinstance(stmt, ast.FunctionDef)
                            and stmt.name == name
                        ):
                            return stmt
                    if isinstance(cur, ast.Module):
                        return None
            return None

        for call in ast.walk(tree):
            if not isinstance(call, ast.Call):
                continue
            fn = dotted_name(call.func)
            positions = _TRACED_ARGS.get(fn) if fn else None
            if positions is None:
                continue
            for pos in positions:
                if pos >= len(call.args):
                    continue
                arg = call.args[pos]
                if isinstance(arg, ast.Lambda):
                    traced.append(arg)
                elif isinstance(arg, ast.Name):
                    target = resolve_local_def(arg.id, call)
                    if target is not None:
                        traced.append(target)

        # -- transitive expansion: trace-reachable same-class methods,
        # local helpers, and simple aliases are traced code too ------------
        class_methods: dict[str, dict[str, ast.FunctionDef]] = {}
        for cls in ast.walk(tree):
            if isinstance(cls, ast.ClassDef):
                class_methods[cls.name] = {
                    n.name: n
                    for n in cls.body
                    if isinstance(n, ast.FunctionDef)
                }

        def enclosing_class(node: ast.AST) -> str | None:
            cur = sf.parents.get(id(node))
            while cur is not None:
                if isinstance(cur, ast.ClassDef):
                    return cur.name
                cur = sf.parents.get(id(cur))
            return None

        def self_method_aliases(
            fn: ast.AST, methods: dict[str, ast.FunctionDef]
        ) -> dict[str, set[str]]:
            """name -> {method,...} for ``x = self._m`` / ``x = self._a if c
            else self._b`` assignments visible from ``fn``'s closure."""
            aliases: dict[str, set[str]] = {}
            cur: ast.AST | None = fn
            while cur is not None:
                cur = sf.parents.get(id(cur))
                if isinstance(cur, (ast.FunctionDef, ast.Module)):
                    for stmt in ast.walk(cur):
                        if not isinstance(stmt, ast.Assign):
                            continue
                        hits = {
                            v.attr
                            for v in ast.walk(stmt.value)
                            if isinstance(v, ast.Attribute)
                            and isinstance(v.value, ast.Name)
                            and v.value.id == "self"
                            and v.attr in methods
                        }
                        if hits:
                            for t in stmt.targets:
                                if isinstance(t, ast.Name):
                                    aliases.setdefault(t.id, set()).update(hits)
                    if isinstance(cur, ast.Module):
                        break
            return aliases

        seen: set[int] = set()
        depth = {id(n): 0 for n in traced}
        frontier = list(traced)
        expanded: list[ast.AST] = []
        while frontier:
            node = frontier.pop()
            if id(node) in seen:
                continue
            seen.add(id(node))
            expanded.append(node)
            d = depth.get(id(node), 0)
            if d >= self._MAX_HOPS:
                continue
            methods = class_methods.get(enclosing_class(node) or "", {})
            aliases = self_method_aliases(node, methods) if methods else {}
            for sub in ast.walk(node):
                # nested defs/lambdas are trace-reachable too; queue them so
                # each body is scanned exactly once (the seen-set dedups),
                # instead of re-walking them inside the enclosing scan
                if (
                    sub is not node
                    and isinstance(
                        sub,
                        (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda),
                    )
                    and id(sub) not in seen
                ):
                    depth[id(sub)] = d  # same hop: lexically inside
                    frontier.append(sub)
                if not isinstance(sub, ast.Call):
                    continue
                targets: list[ast.AST] = []
                f = sub.func
                if (
                    isinstance(f, ast.Attribute)
                    and isinstance(f.value, ast.Name)
                    and f.value.id == "self"
                    and f.attr in methods
                ):
                    targets.append(methods[f.attr])
                elif isinstance(f, ast.Name):
                    if f.id in aliases:
                        targets.extend(methods[m] for m in aliases[f.id])
                    else:
                        local = resolve_local_def(f.id, sub)
                        if local is not None:
                            targets.append(local)
                for t in targets:
                    if id(t) not in seen:
                        depth[id(t)] = d + 1
                        frontier.append(t)

        for node in expanded:
            yield from self._check_traced(sf, node, has_import_random)

    def _check_traced(
        self, sf: SourceFile, fn_node: ast.AST, has_import_random: bool
    ) -> Iterator[Finding]:
        fname = getattr(fn_node, "name", "<lambda>")

        def emit(rule: str, node: ast.AST, msg: str, token: str) -> Finding:
            return Finding(
                rule=rule,
                path=sf.relpath,
                line=node.lineno,
                message=f"{msg} (inside traced function `{fname}`)",
                key=make_key(rule, sf.relpath, sf.scope_of(node), token),
            )

        def own_nodes(fn: ast.AST) -> Iterator[ast.AST]:
            # stop at nested defs/lambdas: the expansion pass queues them as
            # their own traced units, so scanning them here would double-
            # report every finding under two scopes
            body = [fn.body] if isinstance(fn, ast.Lambda) else list(fn.body)
            stack: list[ast.AST] = body
            while stack:
                n = stack.pop()
                yield n
                if not isinstance(
                    n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
                ):
                    stack.extend(ast.iter_child_nodes(n))

        for node in own_nodes(fn_node):
            if isinstance(node, ast.Call):
                dotted = dotted_name(node.func)
                if dotted == "print":
                    yield emit(
                        "JAX001", node,
                        "`print` runs at trace time only", "print",
                    )
                elif (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr in _LOG_METHODS
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id in _LOGGERISH
                ):
                    yield emit(
                        "JAX001", node,
                        f"logging call `{dotted}` runs at trace time only",
                        dotted or "log",
                    )
                elif dotted and (
                    dotted.startswith("np.random.")
                    or dotted.startswith("numpy.random.")
                    or (has_import_random and dotted.startswith("random."))
                ):
                    yield emit(
                        "JAX002", node,
                        f"host RNG `{dotted}` is frozen at trace time; "
                        "use jax.random with a threaded key",
                        dotted,
                    )
                elif dotted in _CLOCK_CALLS:
                    yield emit(
                        "JAX002", node,
                        f"host clock `{dotted}` is frozen at trace time",
                        dotted,
                    )
                elif dotted == "getattr":
                    target = ""
                    if len(node.args) >= 2:
                        arg1 = node.args[1]
                        if isinstance(arg1, ast.Constant):
                            target = f" ({arg1.value!r})"
                    yield emit(
                        "JAX005", node,
                        f"dynamic getattr{target} resolves at trace time and "
                        "is invisible to the jit cache key; hoist the read "
                        "to host code before tracing",
                        "getattr",
                    )
            elif isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = (
                    node.targets
                    if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for t in targets:
                    if (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                    ):
                        yield emit(
                            "JAX003", node,
                            f"`self.{t.attr} = ...` mutates object state at "
                            "trace time only (invisible to later replays)",
                            f"self.{t.attr}",
                        )
            elif isinstance(node, (ast.Global, ast.Nonlocal)):
                kw = "global" if isinstance(node, ast.Global) else "nonlocal"
                yield emit(
                    "JAX003", node,
                    f"`{kw} {', '.join(node.names)}` rebinds enclosing state "
                    "at trace time only",
                    f"{kw}:{','.join(node.names)}",
                )
            elif isinstance(node, (ast.For, ast.AsyncFor, ast.comprehension)):
                it = node.iter
                is_set = isinstance(it, (ast.Set, ast.SetComp)) or (
                    isinstance(it, ast.Call)
                    and dotted_name(it.func) in ("set", "frozenset")
                )
                if is_set:
                    yield emit(
                        "JAX004", node if not isinstance(node, ast.comprehension) else it,
                        "iterating a set during tracing is order-"
                        "nondeterministic; SPMD processes may compile "
                        "different programs — sort it first",
                        "set-iteration",
                    )
