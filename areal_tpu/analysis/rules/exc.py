"""EXC — silent exception swallowing around network/file I/O.

A ``try: <I/O> except Exception: pass`` hides exactly the failures the
fault-tolerance layer exists to surface: a dead replica, a torn file, a
refused connection. Swallowed silently, they degrade throughput or corrupt
recovery with no diagnostic trail. Rule:

  EXC001  a broad handler (bare ``except``, ``except Exception``, or
          ``except BaseException``) whose body does nothing — no logging,
          no metric, no re-raise, no state recorded — wrapping a try block
          that performs network or file I/O

A handler counts as NON-silent when its body does anything beyond
``pass``/``continue``/``...`` — logging, incrementing a metric, assigning
the error somewhere, raising. Narrow handlers (``except OSError``) are
deliberate classification and never flagged. I/O is recognized from
well-known callee shapes (urllib/requests/socket/http.client/shutil/
pickle, ``open``, ``os.*`` file ops) plus this repo's own transport
helpers (``http_json``, ``call_engine``, ``_get_json``/``_post_json``/
``_post_bytes``/``_post_all``, ``urlopen``).
"""

from __future__ import annotations

import ast
from typing import Iterator

from areal_tpu.analysis.core import (
    Finding,
    ProjectContext,
    SourceFile,
    dotted_name,
    make_key,
)

# dotted prefixes whose calls are network/file I/O
_IO_PREFIXES = (
    "urllib.",
    "requests.",
    "socket.",
    "http.client.",
    "shutil.",
)
# exact dotted names
_IO_NAMES = {
    "open",
    "os.remove",
    "os.unlink",
    "os.rename",
    "os.replace",
    "os.makedirs",
    "os.rmdir",
    "os.listdir",
    "os.stat",
    "os.fsync",
    "pickle.load",
    "pickle.loads",
    "pickle.dump",
    "pickle.dumps",
    "json.load",
    "json.dump",
}
# final attribute/name components that mark this repo's transport helpers
_IO_SUFFIXES = {
    "urlopen",
    "http_json",
    "_http_json",
    "call_engine",
    "call_all",
    "_get_json",
    "_post_json",
    "_post_json_failover",
    "_post_bytes",
    "_post_all",
    "_post_all_bytes",
}
_BROAD = {"Exception", "BaseException"}


def _is_io_call(call: ast.Call) -> str | None:
    """The I/O token when ``call`` performs network/file I/O, else None."""
    dotted = dotted_name(call.func)
    if dotted is not None:
        if dotted in _IO_NAMES:
            return dotted
        if any(dotted.startswith(p) for p in _IO_PREFIXES):
            return dotted
        last = dotted.rsplit(".", 1)[-1]
        if last in _IO_SUFFIXES:
            return dotted
    elif isinstance(call.func, ast.Attribute):
        if call.func.attr in _IO_SUFFIXES:
            return call.func.attr
    return None


def _iter_io_scope(root: ast.AST) -> Iterator[ast.AST]:
    """Walk ``root`` without entering nested function/class defs — I/O
    inside a nested def does not run under this try block."""
    stack = [root]
    while stack:
        n = stack.pop()
        if isinstance(
            n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)
        ):
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


def _handler_is_silent(handler: ast.ExceptHandler) -> bool:
    for stmt in handler.body:
        if isinstance(stmt, (ast.Pass, ast.Continue)):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Constant):
            continue  # docstring / ellipsis
        return False
    return True


def _handler_is_broad(handler: ast.ExceptHandler) -> bool:
    if handler.type is None:
        return True
    t = handler.type
    if isinstance(t, ast.Tuple):
        return any(dotted_name(e) in _BROAD for e in t.elts)
    return dotted_name(t) in _BROAD


class SilentExceptionChecker:
    FAMILY = "EXC"
    RULES = {
        "EXC001": "broad except silently swallows network/file I/O errors",
    }

    def check(self, sf: SourceFile, ctx: ProjectContext) -> Iterator[Finding]:
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Try):
                continue
            io_token = None
            for stmt in node.body:
                for sub in _iter_io_scope(stmt):
                    if isinstance(sub, ast.Call):
                        io_token = _is_io_call(sub)
                        if io_token:
                            break
                if io_token:
                    break
            if not io_token:
                continue
            for handler in node.handlers:
                if not _handler_is_broad(handler):
                    continue
                if not _handler_is_silent(handler):
                    continue
                yield Finding(
                    rule="EXC001",
                    path=sf.relpath,
                    line=handler.lineno,
                    message=(
                        f"broad except silently swallows errors from "
                        f"`{io_token}`; log, count a metric, record the "
                        "error, or narrow the exception type"
                    ),
                    key=make_key(
                        "EXC001", sf.relpath, sf.scope_of(handler), io_token
                    ),
                )
