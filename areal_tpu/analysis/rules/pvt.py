"""PVT — private-API dependency guard (pins against the installed jax).

The repo leans on private jax internals in exactly two sanctioned ways:
kernel launch forks that call a private Pallas kernel positionally
(``ops/paged_attention_q8.py``), and lazy imports of private library
kernels (flash attention, megablox gmm, the paged-attention wrapper).
A jax upgrade can silently reorder/extend those signatures — positional
call sites then pass the wrong argument into the wrong parameter with no
error at all. The defense is the pinned-signature idiom: an
``_EXPECTED_*`` tuple of parameter names compared against
``inspect.signature(...)`` at import/first-use (as in
``ops/paged_attention_q8.py``), or the equivalent
``utils.private_api.pin_signature(symbol, _EXPECTED_*)`` helper.

PVT both enforces the idiom and *executes* it at lint time: every pin on
a ``jax.*`` symbol is checked against the **installed** jax, so signature
drift surfaces as a lint finding with a parameter diff during the jax
bump itself — not as an ImportError (or silent corruption) at serve time.

  PVT001  import from a private jax module (``jax._src`` or
          ``jax.experimental.pallas.ops``) with no pinned-signature
          idiom and no try/except-ImportError gate
  PVT002  pinned ``_EXPECTED_*`` tuple disagrees with the installed
          jax's signature (reported with the added/removed/reordered
          parameter diff — never a crash)
  PVT003  pinned symbol cannot be resolved in the installed jax at all

Imports wrapped in try/except catching ImportError are exempt from
PVT001: they already degrade gracefully (the jax_compat shims). Only
``jax.``-prefixed modules are ever imported by the analyzer — pins on
anything else are left unverified.
"""

from __future__ import annotations

import ast
import importlib
import inspect
from typing import Iterator

from areal_tpu.analysis.core import (
    Finding,
    ProjectContext,
    SourceFile,
    dotted_name,
    make_key,
)

_PRIVATE_PREFIXES = ("jax._src", "jax.experimental.pallas.ops")


def _is_private(module: str | None) -> bool:
    return bool(module) and any(
        module == p or module.startswith(p + ".") for p in _PRIVATE_PREFIXES
    )


def _import_gated(sf: SourceFile, node: ast.AST) -> bool:
    """True when ``node`` sits in a try whose handlers catch ImportError
    (or a superclass) — the graceful-degradation idiom."""
    catching = {"ImportError", "ModuleNotFoundError", "Exception", "BaseException"}
    cur = sf.parents.get(id(node))
    while cur is not None:
        if isinstance(cur, ast.Try):
            for h in cur.handlers:
                types = []
                if h.type is None:
                    return True  # bare except
                if isinstance(h.type, ast.Tuple):
                    types = h.type.elts
                else:
                    types = [h.type]
                for t in types:
                    if (dotted_name(t) or "").split(".")[-1] in catching:
                        return True
        cur = sf.parents.get(id(cur))
    return False


def _signature_symbol(node: ast.expr) -> str | None:
    """NAME inside ``[tuple(]inspect.signature(NAME).parameters[)]``."""
    if isinstance(node, ast.Call) and (
        (dotted_name(node.func) or "").split(".")[-1] == "tuple"
    ):
        node = node.args[0] if node.args else node
    if isinstance(node, ast.Attribute) and node.attr == "parameters":
        node = node.value
    if isinstance(node, ast.Call) and (
        (dotted_name(node.func) or "").split(".")[-1] == "signature"
    ):
        if node.args and isinstance(node.args[0], ast.Name):
            return node.args[0].id
    return None


def _literal_str_tuple(node: ast.expr) -> tuple[str, ...] | None:
    if isinstance(node, (ast.Tuple, ast.List)) and all(
        isinstance(e, ast.Constant) and isinstance(e.value, str)
        for e in node.elts
    ):
        return tuple(e.value for e in node.elts)
    return None


class PrivateApiChecker:
    FAMILY = "PVT"
    RULES = {
        "PVT001": "private jax import without a pinned-signature guard",
        "PVT002": "pinned signature disagrees with the installed jax",
        "PVT003": "pinned private symbol unresolvable in the installed jax",
    }

    def __init__(self) -> None:
        self._module_cache: dict[str, object | Exception] = {}

    def check(self, sf: SourceFile, ctx: ProjectContext) -> Iterator[Finding]:
        # private imports: local name -> (module, original name, node)
        private: dict[str, tuple[str, str, ast.ImportFrom]] = {}
        statements: list[ast.ImportFrom] = []
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ImportFrom) and _is_private(node.module):
                statements.append(node)
                for a in node.names:
                    private[a.asname or a.name] = (node.module, a.name, node)
        if not private:
            return

        pins = self._collect_pins(sf)
        pinned_symbols = {sym for sym, _, _, _ in pins}

        # PVT001: every private import statement must be gated or carry at
        # least one pinned symbol (constants like DEFAULT_MASK_VALUE may
        # ride along with a pinned function from the same module).
        for node in statements:
            if _import_gated(sf, node):
                continue
            names = [a.asname or a.name for a in node.names]
            if any(n in pinned_symbols for n in names):
                continue
            yield Finding(
                rule="PVT001",
                path=sf.relpath,
                line=node.lineno,
                message=(
                    f"import from private `{node.module}` carries no "
                    "pinned-signature guard (`_EXPECTED_*` tuple checked "
                    "via inspect.signature, or "
                    "utils.private_api.pin_signature) and no try/except "
                    "ImportError gate: a jax bump can silently reorder "
                    "its parameters"
                ),
                key=make_key(
                    "PVT001", sf.relpath, sf.scope_of(node), node.module
                ),
            )

        # PVT002/PVT003: execute each pin against the installed jax.
        for sym, expected_name, expected, line in pins:
            if sym not in private:
                continue
            module, orig, _ = private[sym]
            if not module.startswith("jax"):
                continue
            obj, err = self._resolve_symbol(module, orig)
            if obj is None:
                yield Finding(
                    rule="PVT003",
                    path=sf.relpath,
                    line=line,
                    message=(
                        f"pin `{expected_name}` targets "
                        f"`{module}.{orig}` which the installed jax "
                        f"cannot resolve ({err}); the launch fork is "
                        "dead code until re-audited"
                    ),
                    key=make_key(
                        "PVT003", sf.relpath, "<module>", f"{module}.{orig}"
                    ),
                )
                continue
            try:
                got = tuple(inspect.signature(obj).parameters)
            except (TypeError, ValueError) as e:
                yield Finding(
                    rule="PVT003",
                    path=sf.relpath,
                    line=line,
                    message=(
                        f"pin `{expected_name}`: `{module}.{orig}` has no "
                        f"inspectable signature ({e})"
                    ),
                    key=make_key(
                        "PVT003", sf.relpath, "<module>", f"sig:{module}.{orig}"
                    ),
                )
                continue
            if got != expected:
                missing = [p for p in expected if p not in got]
                added = [p for p in got if p not in expected]
                if missing or added:
                    diff = (
                        f"installed jax removed {missing or 'nothing'}, "
                        f"added {added or 'nothing'}"
                    )
                else:
                    diff = f"parameters reordered: installed order is {got}"
                yield Finding(
                    rule="PVT002",
                    path=sf.relpath,
                    line=line,
                    message=(
                        f"pin `{expected_name}` disagrees with the "
                        f"installed `{module}.{orig}`: {diff}; re-audit "
                        "every positional call site, then update the pin"
                    ),
                    key=make_key(
                        "PVT002", sf.relpath, "<module>", expected_name
                    ),
                )

    # -- pin discovery ------------------------------------------------------
    def _collect_pins(
        self, sf: SourceFile
    ) -> list[tuple[str, str, tuple[str, ...], int]]:
        """(symbol, _EXPECTED name, pinned tuple, lineno) for every pin in
        the file, via either idiom:

          _got = tuple(inspect.signature(SYM).parameters)
          if _got != _EXPECTED_X: ...          # comparison idiom
          pin_signature(SYM, _EXPECTED_X)      # helper idiom
        """
        expected: dict[str, tuple[tuple[str, ...], int]] = {}
        sig_of: dict[str, str] = {}  # intermediate var -> pinned symbol
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 and (
                isinstance(node.targets[0], ast.Name)
            ):
                name = node.targets[0].id
                if name.startswith("_EXPECTED"):
                    tup = _literal_str_tuple(node.value)
                    if tup is not None:
                        expected[name] = (tup, node.lineno)
                sym = _signature_symbol(node.value)
                if sym is not None:
                    sig_of[name] = sym

        pins: list[tuple[str, str, tuple[str, ...], int]] = []

        def side_symbol(side: ast.expr) -> str | None:
            if isinstance(side, ast.Name) and side.id in sig_of:
                return sig_of[side.id]
            return _signature_symbol(side)

        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Compare) and len(node.comparators) == 1:
                sides = (node.left, node.comparators[0])
                exp = next(
                    (
                        s.id
                        for s in sides
                        if isinstance(s, ast.Name) and s.id in expected
                    ),
                    None,
                )
                sym = next(
                    (x for s in sides if (x := side_symbol(s)) is not None),
                    None,
                )
                if exp and sym:
                    pins.append((sym, exp, *expected[exp][:1], expected[exp][1]))
            elif isinstance(node, ast.Call) and (
                (dotted_name(node.func) or "").split(".")[-1]
                == "pin_signature"
            ):
                if (
                    len(node.args) >= 2
                    and isinstance(node.args[0], ast.Name)
                    and isinstance(node.args[1], ast.Name)
                    and node.args[1].id in expected
                ):
                    exp = node.args[1].id
                    pins.append(
                        (node.args[0].id, exp, *expected[exp][:1], expected[exp][1])
                    )
        return pins

    # -- installed-jax resolution -------------------------------------------
    def _resolve_symbol(self, module: str, name: str):
        cached = self._module_cache.get(module)
        if cached is None:
            try:
                cached = importlib.import_module(module)
            except Exception as e:  # noqa: BLE001 — any failure is PVT003
                cached = e
            self._module_cache[module] = cached
        if isinstance(cached, Exception):
            return None, f"import failed: {cached}"
        obj = getattr(cached, name, None)
        if obj is None:
            return None, "attribute missing"
        return obj, None
