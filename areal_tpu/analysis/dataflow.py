"""Interprocedural dataflow for arealint's performance families (PRF/DON/
SHD/RCP).

The one-hop AST checks (ASY/JAX/THR/...) answer "what does this statement
do"; the performance rules need two more answers:

1. **Is this code hot?** A `jax.device_get` in `initialize()` costs one
   transfer per process lifetime; the same call in the decode loop costs
   one round-trip *per chunk* and serializes host dispatch against device
   compute. Hotness is computed as call-graph reachability from a seed
   set: the decode loop, the trainer step loop, jit-traced callables, and
   any function carrying an explicit ``# arealint: hot-path`` marker.

2. **Is this value a device array?** ``np.asarray(host_thing)`` is free;
   ``np.asarray(device_thing)`` is a blocking device->host transfer. The
   grep surface for sync-shaped calls is ~360 sites repo-wide and most
   are benign — value-origin tracking is what separates the stats-path
   reads from the per-token-loop syncs.

Both facts are *approximate by design* (flow-insensitive origins, name-
resolved call edges, no cross-file attribute types). The rules that
consume them are tuned to fail quiet on "unknown": a finding requires a
positive hot-path hit and (where it matters) a positive device-origin
hit, so precision errors become missed findings, never false alarms.

Call-graph resolution covers the shapes this repo actually uses:
``f()`` to module-level defs and lexically-enclosing local defs,
``self.m()`` to methods of the enclosing class and its same-module
bases, ``mod.f()``/``from pkg.mod import f`` across package modules, and
the ``fn = self._get_step()`` / ``fn(...)`` jit-getter idiom (see
:class:`JitIndex`).
"""

from __future__ import annotations

import ast
import dataclasses
import io
import re
import tokenize
from pathlib import Path
from typing import Iterable, Iterator

HOT_MARKER_RE = re.compile(r"arealint:\s*hot-path\b")

# Qualname tails that seed the hot set by convention: the decode loop and
# the TrainEngine step entry points. Name-based so fixtures, subclasses,
# and future engines participate without registration.
DEFAULT_HOT_SEED_NAMES = frozenset(
    {
        "_loop",
        "train_batch",
        "eval_batch",
        "forward_batch",
        "train_step",
        "decode_step",
    }
)

# dotted transform -> positions of traced-callable arguments (the traced
# bodies join the hot set: everything the trace reaches replays per step)
TRACED_ARG_POSITIONS = {
    "jax.jit": (0,),
    "jit": (0,),
    "pjit": (0,),
    "jax.pjit": (0,),
    "jax.grad": (0,),
    "jax.value_and_grad": (0,),
    "jax.vmap": (0,),
    "jax.pmap": (0,),
    "jax.lax.scan": (0,),
    "lax.scan": (0,),
    "jax.lax.while_loop": (0, 1),
    "lax.while_loop": (0, 1),
    "jax.lax.cond": (1, 2),
    "lax.cond": (1, 2),
    "jax.lax.fori_loop": (2,),
    "lax.fori_loop": (2,),
}

_JIT_NAMES = {"jax.jit", "jit", "pjit", "jax.pjit"}

# -- value origins -----------------------------------------------------------

DEVICE = "device"
HOST = "host"
UNKNOWN = "unknown"

# dotted-prefix -> origin of the call's result
_DEVICE_CALL_PREFIXES = (
    "jnp.",
    "jax.numpy.",
    "jax.lax.",
    "jax.nn.",
    "jax.random.",
    "jax.scipy.",
    "lax.",
)
_DEVICE_CALLS = {
    "jax.device_put",
    "jax.make_array_from_callback",
    "jax.block_until_ready",  # returns its (device) operand
}
_HOST_CALL_PREFIXES = ("np.", "numpy.", "time.", "os.", "math.")
_HOST_CALLS = {
    "float",
    "int",
    "bool",
    "len",
    "str",
    "list",
    "tuple",
    "dict",
    "set",
    "range",
    "sorted",
    "jax.device_get",
    "min",
    "max",
    "sum",
    "abs",
    "round",
    "enumerate",
    "zip",
}
_HOST_METHODS = {"tolist", "item"}
# array-producing methods that preserve their receiver's origin
_PRESERVING_METHODS = {
    "astype",
    "reshape",
    "sum",
    "mean",
    "max",
    "min",
    "copy",
    "transpose",
    "squeeze",
    "at",
    "set",
    "add",
    "take",
    "view",
}


def dotted_name(node: ast.AST) -> str | None:
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


@dataclasses.dataclass
class FuncInfo:
    """One function/method in the graph."""

    key: str  # "relpath::Qual.Name"
    relpath: str
    qualname: str
    name: str
    node: ast.AST  # FunctionDef / AsyncFunctionDef / Lambda
    cls: str | None = None


def _comment_lines(text: str) -> dict[int, str]:
    try:
        toks = tokenize.generate_tokens(io.StringIO(text).readline)
        return {
            t.start[0]: t.string for t in toks if t.type == tokenize.COMMENT
        }
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return {}


class ModuleInfo:
    """Per-module function index, import table and intra-module call edges."""

    def __init__(self, relpath: str, text: str, tree: ast.Module):
        self.relpath = relpath
        self.tree = tree
        self.comments = _comment_lines(text)
        self.funcs: dict[str, FuncInfo] = {}  # qualname -> info
        self.module_defs: dict[str, str] = {}  # bare name -> qualname
        self.class_methods: dict[str, dict[str, str]] = {}  # cls -> name -> qualname
        self.class_bases: dict[str, list[str]] = {}
        # import resolution: local alias -> dotted module; name -> (module, name)
        self.import_modules: dict[str, str] = {}
        self.import_names: dict[str, tuple[str, str]] = {}
        self.parents: dict[int, ast.AST] = {}
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                self.parents[id(child)] = node
        self._jit_index = None  # lazy, shared by PRF/DON/RCP + attr origins
        self._index()

    def jit_index(self) -> "JitIndex":
        """The module's JitIndex, built once — three rule families and
        the device-attr inference all consume it."""
        if self._jit_index is None:
            self._jit_index = JitIndex(self)
        return self._jit_index

    def _index(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.import_modules[a.asname or a.name.split(".")[0]] = a.name
            elif isinstance(node, ast.ImportFrom) and node.module and node.level == 0:
                for a in node.names:
                    self.import_names[a.asname or a.name] = (node.module, a.name)

        def walk(body: list[ast.stmt], prefix: str, cls: str | None) -> None:
            for stmt in body:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    qual = f"{prefix}{stmt.name}"
                    key = f"{self.relpath}::{qual}"
                    self.funcs[qual] = FuncInfo(
                        key, self.relpath, qual, stmt.name, stmt, cls
                    )
                    if cls is None and not prefix.count("."):
                        self.module_defs[stmt.name] = qual
                    if cls is not None and prefix == f"{cls}.":
                        self.class_methods.setdefault(cls, {})[stmt.name] = qual
                    walk(stmt.body, f"{qual}.", cls)
                elif isinstance(stmt, ast.ClassDef):
                    self.class_bases[stmt.name] = [
                        b.id for b in stmt.bases if isinstance(b, ast.Name)
                    ]
                    walk(stmt.body, f"{stmt.name}.", stmt.name)
                else:
                    # defs nested in compound statements (if/for/with/try)
                    # bind in the enclosing scope — register them too
                    for attr in ("body", "orelse", "finalbody"):
                        sub = getattr(stmt, attr, None)
                        if sub:
                            walk(sub, prefix, cls)
                    for h in getattr(stmt, "handlers", []):
                        walk(h.body, prefix, cls)

        walk(self.tree.body, "", None)

    # -- seed detection ----------------------------------------------------
    def seed_quals(self) -> set[str]:
        """Hot seeds in this module: marker comments, seed-named defs, and
        jit/scan-traced callables."""
        seeds: set[str] = set()
        for qual, fi in self.funcs.items():
            node = fi.node
            if fi.name in DEFAULT_HOT_SEED_NAMES:
                seeds.add(qual)
                continue
            lines = [node.lineno]
            if node.decorator_list:
                lines.append(min(d.lineno for d in node.decorator_list))
            # plus the contiguous comment block directly above the def —
            # the marker may share a multi-line rationale comment
            ln = min(lines) - 1
            while ln in self.comments:
                lines.append(ln)
                ln -= 1
            if any(
                HOT_MARKER_RE.search(self.comments.get(ln, "")) for ln in lines
            ):
                seeds.add(qual)
                continue
            for dec in node.decorator_list:
                d = dotted_name(dec)
                if d in _JIT_NAMES or (
                    isinstance(dec, ast.Call)
                    and (
                        dotted_name(dec.func) in _JIT_NAMES
                        or _is_partial_of_jit(dec)
                    )
                ):
                    seeds.add(qual)
                    break
        # call-site-traced callables: jax.jit(f), lax.scan(body, ...)
        for call in ast.walk(self.tree):
            if not isinstance(call, ast.Call):
                continue
            fn = dotted_name(call.func)
            positions = TRACED_ARG_POSITIONS.get(fn) if fn else None
            if positions is None:
                continue
            for pos in positions:
                if pos >= len(call.args):
                    continue
                arg = call.args[pos]
                target = None
                if isinstance(arg, ast.Name):
                    target = self._resolve_local(arg.id, call)
                elif (
                    isinstance(arg, ast.Attribute)
                    and isinstance(arg.value, ast.Name)
                    and arg.value.id == "self"
                ):
                    cls = self.enclosing_class(call)
                    if cls:
                        target = self.method_qual(cls, arg.attr)
                if target:
                    seeds.add(target)
        return seeds

    # -- resolution helpers ------------------------------------------------
    def enclosing_class(self, node: ast.AST) -> str | None:
        cur = self.parents.get(id(node))
        while cur is not None:
            if isinstance(cur, ast.ClassDef):
                return cur.name
            cur = self.parents.get(id(cur))
        return None

    def enclosing_func(self, node: ast.AST) -> FuncInfo | None:
        cur = self.parents.get(id(node))
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for fi in self.funcs.values():
                    if fi.node is cur:
                        return fi
            cur = self.parents.get(id(cur))
        return None

    def method_qual(self, cls: str, name: str, _seen: frozenset = frozenset()) -> str | None:
        """Method lookup through same-module single inheritance."""
        if cls in _seen:
            return None
        qual = self.class_methods.get(cls, {}).get(name)
        if qual:
            return qual
        for base in self.class_bases.get(cls, []):
            found = self.method_qual(base, name, _seen | {cls})
            if found:
                return found
        return None

    def _resolve_local(self, name: str, from_node: ast.AST) -> str | None:
        """Bare-name resolution: nearest enclosing scope's def, then
        module level. A def anywhere in a scope's statement tree (e.g.
        inside an ``if``) binds in that scope, so the search stops only
        at NESTED function boundaries."""

        def scope_defs(scope: ast.AST):
            stack = list(scope.body)
            while stack:
                n = stack.pop()
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    yield n
                    continue  # its body is a deeper scope
                if not isinstance(n, (ast.Lambda, ast.ClassDef)):
                    stack.extend(ast.iter_child_nodes(n))

        cur: ast.AST | None = from_node
        while cur is not None:
            cur = self.parents.get(id(cur))
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Module)):
                for stmt in scope_defs(cur):
                    if stmt.name == name:
                        for fi in self.funcs.values():
                            if fi.node is stmt:
                                return fi.qualname
                if isinstance(cur, ast.Module):
                    return None
        return None


def _is_partial_of_jit(call: ast.Call) -> bool:
    fn = dotted_name(call.func)
    if fn not in ("partial", "functools.partial"):
        return False
    return bool(call.args) and dotted_name(call.args[0]) in _JIT_NAMES


class PackageGraph:
    """Call graph over a set of modules with hot-path reachability.

    ``hot_reason`` maps each hot function key to a human-readable chain
    root ("seeded" or "reachable from <seed qualname>") used in finding
    messages.
    """

    def __init__(self) -> None:
        self.modules: dict[str, ModuleInfo] = {}  # relpath -> module
        self.edges: dict[str, set[str]] = {}
        self._hot: dict[str, str] | None = None  # key -> reason

    @classmethod
    def build(cls, sources: Iterable[tuple[str, str, ast.Module]]) -> "PackageGraph":
        g = cls()
        for relpath, text, tree in sources:
            g.modules[relpath] = ModuleInfo(relpath, text, tree)
        g._link()
        return g

    # -- linking -----------------------------------------------------------
    def _module_for_dotted(self, dotted: str) -> ModuleInfo | None:
        """areal_tpu.engine.train_engine -> its ModuleInfo (by relpath
        suffix match, so the graph works from any repo root)."""
        tail = dotted.replace(".", "/") + ".py"
        init = dotted.replace(".", "/") + "/__init__.py"
        for relpath, mod in self.modules.items():
            if relpath.endswith(tail) or relpath.endswith(init):
                return mod
        return None

    def _link(self) -> None:
        for mod in self.modules.values():
            for fi in mod.funcs.values():
                self.edges.setdefault(fi.key, set())
                for call in ast.walk(fi.node):
                    if not isinstance(call, ast.Call):
                        continue
                    # skip calls that belong to a nested def (they get
                    # their own node); lambda bodies stay attributed here
                    encl = mod.enclosing_func(call)
                    if encl is not None and encl.node is not fi.node:
                        continue
                    for tgt in self._resolve_call(mod, fi, call):
                        self.edges[fi.key].add(tgt)

    def _resolve_call(
        self, mod: ModuleInfo, fi: FuncInfo, call: ast.Call
    ) -> Iterator[str]:
        f = call.func
        if isinstance(f, ast.Name):
            qual = mod._resolve_local(f.id, call)
            if qual:
                yield f"{mod.relpath}::{qual}"
                return
            imp = mod.import_names.get(f.id)
            if imp:
                other = self._module_for_dotted(imp[0])
                if other and imp[1] in other.module_defs:
                    yield f"{other.relpath}::{other.module_defs[imp[1]]}"
            return
        if isinstance(f, ast.Attribute):
            if isinstance(f.value, ast.Name) and f.value.id == "self":
                cls = mod.enclosing_class(call)
                if cls:
                    qual = mod.method_qual(cls, f.attr)
                    if qual:
                        yield f"{mod.relpath}::{qual}"
                return
            base = dotted_name(f.value)
            if base is None:
                return
            # mod_alias.f() across package modules
            target_mod = None
            if base in mod.import_modules:
                target_mod = self._module_for_dotted(mod.import_modules[base])
            elif base in mod.import_names:
                m, n = mod.import_names[base]
                target_mod = self._module_for_dotted(f"{m}.{n}")
            if target_mod and f.attr in target_mod.module_defs:
                yield f"{target_mod.relpath}::{target_mod.module_defs[f.attr]}"

    # -- hot set -----------------------------------------------------------
    @property
    def hot(self) -> dict[str, str]:
        if self._hot is None:
            hot: dict[str, str] = {}
            frontier: list[str] = []
            for mod in self.modules.values():
                for qual in mod.seed_quals():
                    key = f"{mod.relpath}::{qual}"
                    hot[key] = qual
                    frontier.append(key)
            while frontier:
                cur = frontier.pop()
                for nxt in self.edges.get(cur, ()):
                    if nxt not in hot:
                        hot[nxt] = hot[cur]
                        frontier.append(nxt)
            self._hot = hot
        return self._hot

    def hot_funcs_in(self, relpath: str) -> dict[int, tuple[FuncInfo, str]]:
        """id(fn node) -> (info, seed qualname) for hot functions of one
        file."""
        mod = self.modules.get(relpath)
        if mod is None:
            return {}
        out: dict[int, tuple[FuncInfo, str]] = {}
        for fi in mod.funcs.values():
            reason = self.hot.get(fi.key)
            if reason is not None:
                out[id(fi.node)] = (fi, reason)
        return out


# ---------------------------------------------------------------------------
# jit construction index (DON/RCP/PRF share it)
# ---------------------------------------------------------------------------


def _int_tuple(node: ast.expr | None) -> tuple[int, ...]:
    if node is None:
        return ()
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int):
                out.append(e.value)
        return tuple(out)
    return ()


def _str_tuple(node: ast.expr | None) -> tuple[str, ...]:
    if node is None:
        return ()
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(
            e.value
            for e in node.elts
            if isinstance(e, ast.Constant) and isinstance(e.value, str)
        )
    return ()


@dataclasses.dataclass
class JitSite:
    """One ``jax.jit(...)`` construction."""

    call: ast.Call
    target: ast.AST | None  # resolved FunctionDef/Lambda being wrapped
    params: tuple[str, ...]  # positional params of the target (when known)
    donate_pos: tuple[int, ...]
    donate_names: tuple[str, ...]
    static_pos: tuple[int, ...]
    static_names: tuple[str, ...]

    def donates(self, index: int, name: str | None) -> bool:
        if index in self.donate_pos:
            return True
        if name is not None and name in self.donate_names:
            return True
        return False

    def is_static(self, index: int, name: str | None) -> bool:
        if index in self.static_pos:
            return True
        if name is not None and name in self.static_names:
            return True
        return False


class JitIndex:
    """All jit constructions in one module, plus the two idioms this repo
    uses to reach them from call sites:

    - direct binding: ``g = jax.jit(f, donate_argnums=...)`` -> calls of
      ``g(...)`` in the same scope;
    - getter methods: ``def _get_step(self): ... self._cache[k] =
      jax.jit(step, ...); return self._cache[k]`` -> calls of
      ``self._get_step(...)(...)`` or ``fn = self._get_step(...); fn(...)``.
    """

    def __init__(self, mod: ModuleInfo):
        self.mod = mod
        self.sites: list[JitSite] = []
        self._by_call_id: dict[int, JitSite] = {}
        self.direct: dict[str, JitSite] = {}  # bound name -> site
        self.getters: dict[str, JitSite] = {}  # method/function name -> site
        # self.<attr> dicts that ever receive a jit construction via
        # subscript store: calls THROUGH them dispatch onto device
        self.cache_attrs: set[str] = set()
        self._build()

    def _build(self) -> None:
        tree = self.mod.tree
        for call in ast.walk(tree):
            if not isinstance(call, ast.Call):
                continue
            fn = dotted_name(call.func)
            if fn not in _JIT_NAMES and not (
                fn in ("partial", "functools.partial")
                and call.args
                and dotted_name(call.args[0]) in _JIT_NAMES
            ):
                continue
            if fn not in _JIT_NAMES:
                continue  # partial(jax.jit, ...) decorators handled via decorator scan
            target_node: ast.AST | None = None
            params: tuple[str, ...] = ()
            if call.args:
                arg = call.args[0]
                if isinstance(arg, ast.Lambda):
                    target_node = arg
                    params = tuple(a.arg for a in arg.args.args)
                elif isinstance(arg, ast.Name):
                    qual = self.mod._resolve_local(arg.id, call)
                    if qual:
                        target_node = self.mod.funcs[qual].node
                elif (
                    isinstance(arg, ast.Attribute)
                    and isinstance(arg.value, ast.Name)
                    and arg.value.id == "self"
                ):
                    cls = self.mod.enclosing_class(call)
                    qual = self.mod.method_qual(cls, arg.attr) if cls else None
                    if qual:
                        target_node = self.mod.funcs[qual].node
                if target_node is not None and not isinstance(
                    target_node, ast.Lambda
                ):
                    args = target_node.args
                    params = tuple(a.arg for a in args.posonlyargs + args.args)
                    if params and params[0] in ("self", "cls"):
                        params = params[1:]
            kw = {k.arg: k.value for k in call.keywords if k.arg}
            site = JitSite(
                call=call,
                target=target_node,
                params=params,
                donate_pos=_int_tuple(kw.get("donate_argnums")),
                donate_names=_str_tuple(kw.get("donate_argnames")),
                static_pos=_int_tuple(kw.get("static_argnums")),
                static_names=_str_tuple(kw.get("static_argnames")),
            )
            self.sites.append(site)
            self._by_call_id[id(call)] = site

        # direct bindings + getter pattern
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                site = self._by_call_id.get(id(node.value))
                if site is None:
                    continue
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self.direct[t.id] = site
                    elif (
                        isinstance(t, ast.Subscript)
                        and isinstance(t.value, ast.Attribute)
                        and isinstance(t.value.value, ast.Name)
                        and t.value.value.id == "self"
                    ):
                        self.cache_attrs.add(t.value.attr)
        for fi in self.mod.funcs.values():
            if isinstance(fi.node, ast.Lambda):
                continue
            returned_jit = self._getter_site(fi.node)
            if returned_jit is not None:
                self.getters[fi.name] = returned_jit

    def _getter_site(self, fn: ast.AST) -> JitSite | None:
        """A function is a jit getter when it assigns a jit construction
        (to anything — a cache subscript counts) and every return
        statement returns either that binding or a subscript of the same
        cache. Only the getter's OWN nodes count: the jit *target* is
        usually a nested def whose returns must not disqualify the
        pattern."""

        def own_nodes(root: ast.AST) -> Iterator[ast.AST]:
            stack = list(getattr(root, "body", []))
            while stack:
                n = stack.pop()
                yield n
                if not isinstance(
                    n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
                ):
                    stack.extend(ast.iter_child_nodes(n))

        site: JitSite | None = None
        assigned_to: set[str] = set()  # rendered targets of the jit assign
        for node in own_nodes(fn):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                s = self._by_call_id.get(id(node.value))
                if s is not None:
                    if site is not None and s is not site:
                        return None  # two different jits: ambiguous
                    site = s
                    for t in node.targets:
                        assigned_to.add(ast.dump(t))
        if site is None:
            return None
        returns = [
            n
            for n in own_nodes(fn)
            if isinstance(n, ast.Return) and n.value is not None
        ]
        if not returns:
            return None
        for r in returns:
            if ast.dump(r.value) not in assigned_to and not self._same_cache(
                r.value, assigned_to
            ):
                return None
        return site

    @staticmethod
    def _same_cache(ret: ast.expr, assigned: set[str]) -> bool:
        """return self._cache[key] matches an assign to self._cache[key2]
        (key expressions may differ textually; match on the cache base)."""
        if not isinstance(ret, ast.Subscript):
            return False
        base = ast.dump(ret.value)
        for a in assigned:
            if f"value={base}" in a or a.startswith(
                f"Subscript(value={base}"
            ):
                return True
        return False

    def site_for_callsite(self, call: ast.Call) -> JitSite | None:
        """The JitSite a *call site* dispatches into, through the direct
        or getter idiom, or an inline ``jax.jit(f)(x)``."""
        f = call.func
        if isinstance(f, ast.Name) and f.id in self.direct:
            return self.direct[f.id]
        if isinstance(f, ast.Call):
            inline = self._by_call_id.get(id(f))
            if inline is not None:
                return inline
            g = f.func
            if (
                isinstance(g, ast.Attribute)
                and isinstance(g.value, ast.Name)
                and g.value.id == "self"
                and g.attr in self.getters
            ):
                return self.getters[g.attr]
            if isinstance(g, ast.Name) and g.id in self.getters:
                return self.getters[g.id]
        return None


# ---------------------------------------------------------------------------
# value-origin tracking
# ---------------------------------------------------------------------------


class OriginTracker:
    """Flow-ordered (single forward pass) device/host origin inference for
    the locals of one function.

    ``device_names``: names known to dispatch onto device when *called*
    (locally-bound jit functions, jit-getter methods). ``device_attrs``:
    ``self.<attr>`` names holding device trees (inferred per class from
    assignment sites)."""

    def __init__(
        self,
        fn: ast.AST,
        device_names: set[str] | None = None,
        device_attrs: set[str] | None = None,
        jit_index: JitIndex | None = None,
        param_origins: dict[str, str] | None = None,
    ):
        self.fn = fn
        self.device_names = device_names or set()
        self.device_attrs = device_attrs or set()
        self.jit_index = jit_index
        self.env: dict[str, str] = dict(param_origins or {})
        self._annotate_params()
        self._sweep()

    def _annotate_params(self) -> None:
        args = getattr(self.fn, "args", None)
        if args is None:
            return
        for a in list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs):
            if a.arg in self.env:
                continue
            ann = a.annotation
            label = None
            if ann is not None:
                d = dotted_name(ann) or (
                    ann.value if isinstance(ann, ast.Constant) else None
                )
                if isinstance(d, str):
                    if "jax" in d or "jnp" in d or d.endswith("Array"):
                        label = DEVICE
                    elif d.startswith("np.") or "ndarray" in d:
                        label = HOST
            self.env[a.arg] = label or UNKNOWN

    def _own_statements(self) -> list[ast.stmt]:
        body = (
            [self.fn.body]
            if isinstance(self.fn, ast.Lambda)
            else list(getattr(self.fn, "body", []))
        )
        out: list[ast.stmt] = []
        stack: list[ast.AST] = list(body)
        while stack:
            n = stack.pop(0)
            if isinstance(n, ast.stmt):
                out.append(n)
            if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                stack.extend(ast.iter_child_nodes(n))
        out.sort(key=lambda s: s.lineno)
        return out

    def _sweep(self) -> None:
        # two source-ordered passes: the second resolves bindings whose
        # right-hand side reads a name bound later in pass one (loop
        # targets over dicts of step outputs, branch-divergent binds)
        for _ in range(2):
            self._sweep_once()

    def _sweep_once(self) -> None:
        for stmt in self._own_statements():
            if isinstance(stmt, ast.Assign):
                origin = self.origin_of(stmt.value)
                for t in stmt.targets:
                    self._bind(t, origin, stmt.value)
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                self._bind(stmt.target, self.origin_of(stmt.value), stmt.value)
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                self._bind_loop_target(stmt.target, stmt.iter)
            elif isinstance(stmt, ast.With):
                for item in stmt.items:
                    if item.optional_vars is not None:
                        self._bind(
                            item.optional_vars,
                            self.origin_of(item.context_expr),
                            item.context_expr,
                        )

    def _bind(self, target: ast.expr, origin: str, value: ast.expr) -> None:
        if isinstance(target, ast.Name):
            self.env[target.id] = origin
        elif isinstance(target, (ast.Tuple, ast.List)):
            # tuple unpack of a device-returning call: every element is
            # device (the jit boundary returns arrays, not mixed tuples)
            for el in target.elts:
                self._bind(el, origin if origin == DEVICE else UNKNOWN, value)

    def _bind_loop_target(self, target: ast.expr, it: ast.expr) -> None:
        origin = UNKNOWN
        # for k, v in <device-dict>.items(): the VALUES are device
        if (
            isinstance(it, ast.Call)
            and isinstance(it.func, ast.Attribute)
            and it.func.attr == "items"
            and self.origin_of(it.func.value) == DEVICE
        ):
            if isinstance(target, (ast.Tuple, ast.List)) and len(target.elts) == 2:
                self._bind(target.elts[0], HOST, it)
                self._bind(target.elts[1], DEVICE, it)
                return
        elif self.origin_of(it) == DEVICE:
            origin = DEVICE
        self._bind(target, origin, it)

    # -- expression origins ----------------------------------------------
    def origin_of(self, node: ast.expr) -> str:
        if isinstance(node, ast.Name):
            return self.env.get(node.id, UNKNOWN)
        if isinstance(node, ast.Constant):
            return HOST
        if isinstance(node, ast.Attribute):
            if isinstance(node.value, ast.Name) and node.value.id == "self":
                return DEVICE if node.attr in self.device_attrs else UNKNOWN
            base = self.origin_of(node.value)
            return base if base == DEVICE else UNKNOWN
        if isinstance(node, ast.Subscript):
            return self.origin_of(node.value)
        if isinstance(node, ast.BinOp):
            if DEVICE in (self.origin_of(node.left), self.origin_of(node.right)):
                return DEVICE
            return UNKNOWN
        if isinstance(node, ast.UnaryOp):
            return self.origin_of(node.operand)
        if isinstance(node, ast.IfExp):
            a, b = self.origin_of(node.body), self.origin_of(node.orelse)
            return a if a == b else UNKNOWN
        if isinstance(node, (ast.Dict,)):
            vals = [self.origin_of(v) for v in node.values if v is not None]
            if vals and any(v == DEVICE for v in vals):
                return DEVICE
            return UNKNOWN
        if isinstance(node, (ast.Tuple, ast.List)):
            vals = [self.origin_of(v) for v in node.elts]
            if vals and any(v == DEVICE for v in vals):
                return DEVICE
            return UNKNOWN
        if isinstance(node, ast.DictComp):
            return self.origin_of(node.value)
        if isinstance(node, (ast.ListComp, ast.GeneratorExp, ast.SetComp)):
            return self.origin_of(node.elt)
        if isinstance(node, ast.Call):
            return self._call_origin(node)
        return UNKNOWN

    def _call_origin(self, call: ast.Call) -> str:
        d = dotted_name(call.func)
        if d is not None:
            if d in _DEVICE_CALLS or any(
                d.startswith(p) for p in _DEVICE_CALL_PREFIXES
            ):
                return DEVICE
            if d in _HOST_CALLS or any(
                d.startswith(p) for p in _HOST_CALL_PREFIXES
            ):
                return HOST
            if d.startswith("jax.tree.") or d.startswith("jax.tree_util."):
                # tree.map over a device tree yields a device tree
                for a in call.args:
                    if self.origin_of(a) == DEVICE:
                        return DEVICE
                return UNKNOWN
        f = call.func
        if isinstance(f, ast.Name):
            if f.id in self.device_names:
                return DEVICE
            if self.env.get(f.id) == DEVICE:
                # calling a value that IS a device-dispatching callable
                return DEVICE
        if isinstance(f, ast.Attribute):
            if f.attr in _HOST_METHODS:
                return HOST
            if (
                isinstance(f.value, ast.Name)
                and f.value.id == "self"
                and f.attr in self.device_names
            ):
                return DEVICE
            if f.attr in _PRESERVING_METHODS:
                return self.origin_of(f.value)
        if (
            isinstance(f, ast.Subscript)
            and isinstance(f.value, ast.Attribute)
            and isinstance(f.value.value, ast.Name)
            and f.value.value.id == "self"
            and self.jit_index is not None
            and f.value.attr in self.jit_index.cache_attrs
        ):
            # self._fn_cache[key](...) — a call through a jit cache
            return DEVICE
        if isinstance(f, ast.Call) and self.jit_index is not None:
            if self.jit_index.site_for_callsite(call) is not None:
                return DEVICE
        return UNKNOWN


def device_attrs_of_class(mod: ModuleInfo, cls: str) -> set[str]:
    """``self.<attr>`` names that are device trees: every observed
    assignment to the attr (outside nested defs) has device origin.
    Mixed or host-assigned attrs are excluded."""
    cls_node = None
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.ClassDef) and node.name == cls:
            cls_node = node
            break
    if cls_node is None:
        return set()
    jit_idx = mod.jit_index()
    device_names = set(jit_idx.direct) | set(jit_idx.getters)
    verdict: dict[str, bool] = {}
    for meth in cls_node.body:
        if not isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        tracker = OriginTracker(
            meth, device_names=device_names, jit_index=jit_idx
        )
        for stmt in tracker._own_statements():
            if not isinstance(stmt, ast.Assign):
                continue
            origin = tracker.origin_of(stmt.value)
            for t in stmt.targets:
                targets = (
                    t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]
                )
                for el in targets:
                    if (
                        isinstance(el, ast.Attribute)
                        and isinstance(el.value, ast.Name)
                        and el.value.id == "self"
                    ):
                        ok = origin == DEVICE
                        verdict[el.attr] = verdict.get(el.attr, True) and ok
    return {a for a, ok in verdict.items() if ok}


# ---------------------------------------------------------------------------
# graph construction entry points
# ---------------------------------------------------------------------------


def iter_package_sources(
    package_root: Path,
) -> list[tuple[str, str, ast.Module]]:
    """(relpath, text, tree) for every parseable package module — the ONE
    enumeration both the call graph and the wire contract build from, so
    a filter change cannot silently apply to one and not the other."""
    sources: list[tuple[str, str, ast.Module]] = []
    repo_root = package_root.parent
    for path in sorted(package_root.rglob("*.py")):
        if any(part.startswith(".") for part in path.parts):
            continue
        try:
            text = path.read_text(encoding="utf-8", errors="replace")
            tree = ast.parse(text)
        except SyntaxError:
            continue
        try:
            rel = path.resolve().relative_to(repo_root.resolve()).as_posix()
        except ValueError:
            rel = path.as_posix()
        sources.append((rel, text, tree))
    return sources


def build_package_graph(package_root: Path) -> PackageGraph:
    return PackageGraph.build(iter_package_sources(package_root))


def single_file_graph(relpath: str, text: str, tree: ast.Module) -> PackageGraph:
    return PackageGraph.build([(relpath, text, tree)])
