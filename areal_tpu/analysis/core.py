"""arealint core: source loading, suppressions, project context, rule engine.

Design:
  - Every rule family is a class with a ``FAMILY`` prefix (ASY/JAX/THR/
    CFG/OBS/EXC/SIG and the dataflow-backed PRF/DON/SHD/RCP), a
    ``RULES`` table (id -> one-line title), and a ``check(sf, ctx)``
    generator yielding :class:`Finding`. Interprocedural facts (call
    graph, hot-path reachability, value origins) come from
    :mod:`areal_tpu.analysis.dataflow` via :meth:`ProjectContext.graph_for`.
  - Findings carry a line number for humans and a line-independent ``key``
    for the baseline, so baselined findings survive unrelated edits that
    shift line numbers.
  - Suppressions are comments: ``# arealint: disable=ASY001 reason`` on
    the finding line, ``# arealint: disable-next=ASY001 reason`` on the
    line above, or ``# arealint: disable-file=OBS001 reason`` anywhere for
    the whole file (``# arealint: skip-file`` excludes the file entirely).
    ``disable=all`` and family prefixes (``disable=THR``) are accepted.
    Comments are located with :mod:`tokenize`, so a ``#`` inside a string
    literal can never suppress anything.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import json
import re
import tokenize
from collections import Counter
from pathlib import Path
from typing import Iterable, Iterator

SEVERITY_ERROR = "error"
SEVERITY_WARNING = "warning"

_SUPPRESS_RE = re.compile(
    r"arealint:\s*(?P<kind>disable(?:-next|-file)?|skip-file)"
    r"(?:\s*=\s*(?P<rules>[A-Za-z0-9_,]+))?"
    r"(?:\s+(?P<reason>\S.*))?"
)


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at a specific site."""

    rule: str  # e.g. "ASY001"
    path: str  # repo-relative posix path
    line: int
    message: str
    severity: str = SEVERITY_ERROR
    # line-independent identity used for baseline matching:
    #   rule:path:scope:token  (scope = enclosing def/class qualname,
    #   token = rule-specific detail such as the callee or attribute name)
    key: str = ""

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "message": self.message,
            "severity": self.severity,
            "key": self.key,
        }

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} [{self.severity}] {self.message}"


def make_key(rule: str, path: str, scope: str, token: str) -> str:
    return f"{rule}:{path}:{scope}:{token}"


@dataclasses.dataclass(frozen=True)
class Suppression:
    rules: frozenset[str]  # rule ids, family prefixes, or {"all"}
    reason: str

    def covers(self, rule: str) -> bool:
        if "all" in self.rules:
            return True
        if rule in self.rules:
            return True
        # family prefix, e.g. disable=THR covers THR001
        return any(rule.startswith(r) and r.isalpha() for r in self.rules)


class SourceFile:
    """A parsed module plus the comment-derived suppression table."""

    def __init__(self, path: Path, relpath: str, text: str, tree: ast.Module):
        self.path = path
        self.relpath = relpath
        self.text = text
        self.tree = tree
        self.suppressions: dict[int, Suppression] = {}
        self.file_suppression: Suppression | None = None
        self.skip_file = False
        self._parents: dict[int, ast.AST] | None = None
        self._parse_suppressions()

    @classmethod
    def load(cls, path: Path, repo_root: Path) -> "SourceFile":
        text = path.read_text(encoding="utf-8", errors="replace")
        tree = ast.parse(text, filename=str(path))
        try:
            rel = path.resolve().relative_to(repo_root.resolve()).as_posix()
        except ValueError:
            rel = path.as_posix()
        return cls(path, rel, text, tree)

    def _parse_suppressions(self) -> None:
        try:
            tokens = tokenize.generate_tokens(io.StringIO(self.text).readline)
            comments = [
                (t.start[0], t.string) for t in tokens if t.type == tokenize.COMMENT
            ]
        except (tokenize.TokenError, IndentationError, SyntaxError):
            comments = []
        lines = self.text.splitlines()
        for lineno, comment in comments:
            m = _SUPPRESS_RE.search(comment)
            if not m:
                continue
            kind = m.group("kind")
            if kind == "skip-file":
                self.skip_file = True
                continue
            rules = frozenset(
                r.strip() for r in (m.group("rules") or "all").split(",") if r.strip()
            )
            sup = Suppression(rules=rules, reason=(m.group("reason") or "").strip())
            if kind == "disable-file":
                prev = self.file_suppression
                if prev is not None:
                    sup = Suppression(
                        rules=prev.rules | sup.rules,
                        reason=(prev.reason + "; " + sup.reason).strip("; "),
                    )
                self.file_suppression = sup
                continue
            if kind == "disable-next":
                # covers the full extent of the statement STARTING on the
                # next line (a wrapped call anchors findings on its first
                # physical line, but inner nodes may anchor deeper)
                targets = self._stmt_extent(lineno + 1, starting=True)
            else:
                # trailing comment: covers the whole multi-line statement it
                # trails — but ONLY when there is code on the comment's own
                # line; a standalone comment inside a function must not
                # blanket the enclosing block (use disable-next for that)
                code = lines[lineno - 1] if lineno <= len(lines) else ""
                has_code = code.split("#", 1)[0].strip() != ""
                targets = (
                    self._stmt_extent(lineno, starting=False)
                    if has_code
                    else [lineno]
                )
            for target in targets:
                prev = self.suppressions.get(target)
                merged = sup
                if prev is not None:
                    merged = Suppression(
                        rules=prev.rules | sup.rules,
                        reason=(prev.reason + "; " + sup.reason).strip("; "),
                    )
                self.suppressions[target] = merged

    def _stmt_extent(self, line: int, starting: bool) -> list[int]:
        """Lines of the smallest statement containing ``line`` (or, with
        ``starting=True``, beginning exactly at ``line``). Falls back to
        ``[line]`` when no statement matches."""
        best: tuple[int, int] | None = None
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.stmt):
                continue
            end = getattr(node, "end_lineno", node.lineno) or node.lineno
            if starting:
                if node.lineno != line:
                    continue
            elif not (node.lineno <= line <= end):
                continue
            if best is None or (end - node.lineno) < (best[1] - best[0]):
                best = (node.lineno, end)
        if best is None:
            return [line]
        return list(range(best[0], best[1] + 1))

    def suppressed(self, finding: Finding) -> bool:
        if self.file_suppression is not None and self.file_suppression.covers(
            finding.rule
        ):
            return True
        sup = self.suppressions.get(finding.line)
        return sup is not None and sup.covers(finding.rule)

    @property
    def parents(self) -> dict[int, ast.AST]:
        """id(node) -> parent node map, built lazily once per file."""
        if self._parents is None:
            self._parents = {}
            for node in ast.walk(self.tree):
                for child in ast.iter_child_nodes(node):
                    self._parents[id(child)] = node
        return self._parents

    def scope_of(self, node: ast.AST) -> str:
        """Dotted qualname of the enclosing def/class chain ("<module>" at
        top level). Used for stable finding keys."""
        names: list[str] = []
        cur = self.parents.get(id(node))
        while cur is not None:
            if isinstance(
                cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                names.append(cur.name)
            cur = self.parents.get(id(cur))
        return ".".join(reversed(names)) or "<module>"


def dotted_name(node: ast.AST) -> str | None:
    """Render a Name/Attribute chain as "a.b.c" (None for anything else)."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def const_str(node: ast.AST) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def config_class_of_annotation(
    ann: ast.expr | None, names: "set[str] | dict"
) -> str | None:
    """The single config-class name an annotation refers to, if exactly one
    of ``names`` appears in it (handles string annotations and unions like
    ``X | None`` / ``Optional[X]``). Shared by the context builder and the
    CFG rule so both sides accept the same annotation shapes."""
    if ann is None:
        return None
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        try:
            ann = ast.parse(ann.value, mode="eval").body
        except SyntaxError:
            return None
    hits = {
        n.id for n in ast.walk(ann) if isinstance(n, ast.Name) and n.id in names
    }
    return hits.pop() if len(hits) == 1 else None


def default_package_root() -> Path:
    """The areal_tpu package directory (this file lives in its analysis/)."""
    return Path(__file__).resolve().parents[1]


def default_baseline_path() -> Path:
    return default_package_root() / "analysis" / "baseline.json"


# ---------------------------------------------------------------------------
# Project context: facts extracted once from the package the rules check
# against (config dataclass fields, metric catalog names).
# ---------------------------------------------------------------------------


class ProjectContext:
    def __init__(self, package_root: Path):
        self.package_root = package_root
        self.repo_root = package_root.parent
        # config dataclasses (api/config.py): class -> own+inherited fields
        self.config_fields: dict[str, set[str]] = {}
        # class -> field -> config-class name of the field's annotation
        # (None when the annotation is not another config dataclass)
        self.config_field_types: dict[str, dict[str, str | None]] = {}
        # methods/properties defined on config classes (allowed accesses)
        self.config_methods: dict[str, set[str]] = {}
        # metric catalog (observability/catalog.py)
        self.metric_names: set[str] = set()
        self.metric_prefixes: set[str] = set()
        self.catalog_relpath = "areal_tpu/observability/catalog.py"
        # declared device-mesh axis names (parallel/mesh.py MESH_AXES) —
        # the SHD family validates every PartitionSpec string against them
        self.mesh_axes: frozenset[str] = frozenset()
        # lazy interprocedural state (dataflow.py): one package-wide call
        # graph shared by every PRF/DON/RCP check, plus per-file graphs
        # for sources outside the package (fixtures, repo scripts)
        self._package_graph = None
        self._file_graphs: dict[str, object] = {}
        # lazy wire contract (wirecontract.py): the package's HTTP route
        # tables + handler schemas, shared by every WIRE check; files
        # outside the package get a self-contained single-file contract
        self._package_wire = None
        self._file_wire: dict[str, object] = {}
        self._build_config_registry()
        self._build_metric_catalog()
        self._build_mesh_axes()

    # -- config dataclasses ------------------------------------------------
    def _build_config_registry(self) -> None:
        path = self.package_root / "api" / "config.py"
        if not path.exists():
            return
        tree = ast.parse(path.read_text(encoding="utf-8"))
        own_fields: dict[str, list[tuple[str, ast.expr | None]]] = {}
        bases: dict[str, list[str]] = {}
        methods: dict[str, set[str]] = {}
        for node in tree.body:
            if not isinstance(node, ast.ClassDef):
                continue
            is_dc = any(
                (isinstance(d, ast.Name) and d.id == "dataclass")
                or (isinstance(d, ast.Attribute) and d.attr == "dataclass")
                or (
                    isinstance(d, ast.Call)
                    and dotted_name(d.func) in ("dataclass", "dataclasses.dataclass")
                )
                for d in node.decorator_list
            )
            if not is_dc:
                continue
            flds: list[tuple[str, ast.expr | None]] = []
            meths: set[str] = set()
            for stmt in node.body:
                if isinstance(stmt, ast.AnnAssign) and isinstance(
                    stmt.target, ast.Name
                ):
                    flds.append((stmt.target.id, stmt.annotation))
                elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    meths.add(stmt.name)
            own_fields[node.name] = flds
            bases[node.name] = [
                b.id for b in node.bases if isinstance(b, ast.Name)
            ]
            methods[node.name] = meths

        def resolve(cls: str, seen: frozenset[str]) -> list[tuple[str, ast.expr | None]]:
            if cls not in own_fields or cls in seen:
                return []
            out: list[tuple[str, ast.expr | None]] = []
            for b in bases.get(cls, []):
                out.extend(resolve(b, seen | {cls}))
            out.extend(own_fields[cls])
            return out

        for cls in own_fields:
            resolved = resolve(cls, frozenset())
            self.config_fields[cls] = {n for n, _ in resolved}
            self.config_methods[cls] = set()
            for b in [cls] + bases.get(cls, []):
                self.config_methods[cls] |= methods.get(b, set())
            # field -> nested config class (for attribute-chain resolution)
            ftypes: dict[str, str | None] = {}
            for name, ann in resolved:
                ftypes[name] = config_class_of_annotation(ann, own_fields)
            self.config_field_types[cls] = ftypes

    # -- metric catalog ----------------------------------------------------
    def _build_metric_catalog(self) -> None:
        path = self.package_root / "observability" / "catalog.py"
        if not path.exists():
            return
        tree = ast.parse(path.read_text(encoding="utf-8"))
        for call in ast.walk(tree):
            if not isinstance(call, ast.Call):
                continue
            if not (
                isinstance(call.func, ast.Attribute)
                and call.func.attr in ("counter", "gauge", "histogram")
            ):
                continue
            name = const_str(call.args[0]) if call.args else None
            if name and name.startswith("areal_"):
                self.metric_names.add(name)
        self.metric_prefixes = {
            "_".join(n.split("_")[:2]) for n in self.metric_names
        }

    # -- mesh axes ---------------------------------------------------------
    def _build_mesh_axes(self) -> None:
        path = self.package_root / "parallel" / "mesh.py"
        if not path.exists():
            return
        try:
            tree = ast.parse(path.read_text(encoding="utf-8"))
        except SyntaxError:
            return
        for node in tree.body:
            if not isinstance(node, ast.Assign):
                continue
            if not any(
                isinstance(t, ast.Name) and t.id == "MESH_AXES"
                for t in node.targets
            ):
                continue
            if isinstance(node.value, (ast.Tuple, ast.List)):
                self.mesh_axes = frozenset(
                    e.value
                    for e in node.value.elts
                    if isinstance(e, ast.Constant) and isinstance(e.value, str)
                )

    # -- interprocedural graphs (dataflow.py) ------------------------------
    def graph_for(self, sf: "SourceFile"):
        """The call graph covering ``sf``: the shared package graph when
        the file lives under the package root, a single-file graph
        otherwise (fixtures, bench/prof scripts). Both are cached —
        hot-path reachability is computed once per process."""
        from areal_tpu.analysis import dataflow

        try:
            sf.path.resolve().relative_to(self.package_root.resolve())
            in_package = True
        except ValueError:
            in_package = False
        if in_package:
            if self._package_graph is None:
                self._package_graph = dataflow.build_package_graph(
                    self.package_root
                )
            if sf.relpath in self._package_graph.modules:
                return self._package_graph
        g = self._file_graphs.get(sf.relpath)
        if g is None:
            g = dataflow.single_file_graph(sf.relpath, sf.text, sf.tree)
            self._file_graphs[sf.relpath] = g
        return g

    def wire_for(self, sf: "SourceFile"):
        """The wire contract covering ``sf``: the shared package contract
        for package files (built once from every server module), a
        single-file contract otherwise (fixtures are self-contained
        client+server pairs). Mirrors :meth:`graph_for`'s caching."""
        from areal_tpu.analysis import wirecontract

        try:
            sf.path.resolve().relative_to(self.package_root.resolve())
            in_package = True
        except ValueError:
            in_package = False
        if in_package:
            if self._package_wire is None:
                # reuse the call graph's parsed modules when a dataflow
                # rule already built it (the default full run)
                g = self._package_graph
                self._package_wire = wirecontract.build_package_contract(
                    self.package_root,
                    modules=g.modules.values() if g is not None else None,
                )
            return self._package_wire
        c = self._file_wire.get(sf.relpath)
        if c is None:
            c = wirecontract.build_contract(
                [(sf.relpath, sf.text, sf.tree)]
            )
            self._file_wire[sf.relpath] = c
        return c


# ---------------------------------------------------------------------------
# Engine
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class AnalysisResult:
    findings: list[Finding]  # non-suppressed, non-baselined
    baselined: list[Finding]
    suppressed: list[Finding]
    stale_baseline: list[dict]  # baseline entries no current finding matches
    files_checked: int

    @property
    def ok(self) -> bool:
        return not self.findings

    def to_dict(self) -> dict:
        return {
            "version": 1,
            "ok": self.ok,
            "files_checked": self.files_checked,
            "findings": [f.to_dict() for f in self.findings],
            "baselined": len(self.baselined),
            "suppressed": len(self.suppressed),
            "stale_baseline": self.stale_baseline,
        }


def iter_python_files(paths: Iterable[Path]) -> Iterator[Path]:
    for p in paths:
        p = Path(p)
        if p.is_dir():
            # hidden-dir filter applies only BELOW the requested root, so a
            # repo living under a dotted parent directory still analyzes
            yield from sorted(
                f
                for f in p.rglob("*.py")
                if not any(
                    part.startswith(".") for part in f.relative_to(p).parts
                )
            )
        elif p.suffix == ".py":
            yield p


class Analyzer:
    def __init__(
        self,
        rules: Iterable[str] | None = None,
        package_root: Path | None = None,
    ):
        from areal_tpu.analysis.rules import all_checkers

        self.context = ProjectContext(package_root or default_package_root())
        self.checkers = all_checkers()
        if rules:
            # case-insensitive selection: `--rules wire,lck` == `WIRE,LCK`
            wanted = {r.strip().upper() for r in rules if r.strip()}
            known = {c.FAMILY for c in self.checkers} | {
                r for c in self.checkers for r in c.RULES
            }
            unknown = wanted - known
            if unknown:
                # a typo'd rule selection must never silently check nothing
                raise ValueError(
                    f"unknown rule(s) {sorted(unknown)}; "
                    f"known: {sorted(known)}"
                )
            self.checkers = [
                c
                for c in self.checkers
                if c.FAMILY in wanted or any(r in wanted for r in c.RULES)
            ]
            for c in self.checkers:
                if c.FAMILY not in wanted:
                    c.only_rules = {r for r in c.RULES if r in wanted}

    def rule_table(self) -> dict[str, str]:
        table: dict[str, str] = {}
        for c in self.checkers:
            table.update(c.RULES)
        return dict(sorted(table.items()))

    def run(
        self,
        paths: Iterable[Path],
        baseline: dict | None = None,
    ) -> AnalysisResult:
        findings: list[Finding] = []
        suppressed: list[Finding] = []
        n_files = 0
        for path in iter_python_files(paths):
            n_files += 1
            try:
                sf = SourceFile.load(Path(path), self.context.repo_root)
            except SyntaxError as e:
                rel = Path(path).as_posix()
                findings.append(
                    Finding(
                        rule="PARSE",
                        path=rel,
                        line=e.lineno or 1,
                        message=f"syntax error: {e.msg}",
                        key=make_key("PARSE", rel, "<module>", "syntax"),
                    )
                )
                continue
            if sf.skip_file:
                continue
            for checker in self.checkers:
                for f in checker.check(sf, self.context):
                    only = getattr(checker, "only_rules", None)
                    if only and f.rule not in only:
                        continue
                    if sf.suppressed(f):
                        suppressed.append(f)
                    else:
                        findings.append(f)
        findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
        suppressed.sort(key=lambda f: (f.path, f.line, f.rule, f.message))

        baselined: list[Finding] = []
        stale: list[dict] = []
        if baseline:
            budget = Counter(
                e["key"] for e in baseline.get("findings", []) if e.get("key")
            )
            fresh: list[Finding] = []
            for f in findings:
                if budget.get(f.key, 0) > 0:
                    budget[f.key] -= 1
                    baselined.append(f)
                else:
                    fresh.append(f)
            findings = fresh
            leftover = +budget  # strips zero/negative counts
            for e in baseline.get("findings", []):
                if leftover.get(e.get("key", ""), 0) > 0:
                    leftover[e["key"]] -= 1
                    stale.append(e)
        return AnalysisResult(
            findings=findings,
            baselined=baselined,
            suppressed=suppressed,
            stale_baseline=stale,
            files_checked=n_files,
        )


def load_baseline(path: Path) -> dict:
    with open(path) as f:
        data = json.load(f)
    if not isinstance(data, dict) or "findings" not in data:
        raise ValueError(f"malformed baseline file {path}")
    return data


def render_baseline(
    findings: Iterable[Finding], old: dict | None = None
) -> dict:
    """Baseline document for the given findings, carrying over reasons from
    ``old`` for keys that persist (new entries get an empty reason that a
    human must fill in — the gate test enforces non-empty reasons)."""
    reasons: dict[str, str] = {}
    if old:
        for e in old.get("findings", []):
            if e.get("reason"):
                reasons.setdefault(e.get("key", ""), e["reason"])
    entries = [
        {
            "rule": f.rule,
            "path": f.path,
            "key": f.key,
            "message": f.message,
            "reason": reasons.get(f.key, ""),
        }
        for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule))
    ]
    return {"version": 1, "findings": entries}


def run_analysis(
    paths: Iterable[Path],
    rules: Iterable[str] | None = None,
    baseline_path: Path | None = None,
    package_root: Path | None = None,
) -> AnalysisResult:
    """One-call API: analyze ``paths`` with the given rule families against
    the baseline at ``baseline_path`` (pass None to disable baselining)."""
    analyzer = Analyzer(rules=rules, package_root=package_root)
    baseline = None
    if baseline_path is not None and Path(baseline_path).exists():
        baseline = load_baseline(Path(baseline_path))
    return analyzer.run(paths, baseline=baseline)
