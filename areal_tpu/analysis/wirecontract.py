"""Wire-contract extraction for arealint's WIRE family.

The control plane is a set of HTTP-coupled processes: aiohttp servers
(inference server, rpc worker, proxy gateway, rollout proxy) and the
clients that call them (inference client transport, /statusz scrapers,
autopilot knob pushes, tools). The contract between them — which paths
exist, which JSON body keys a handler reads, which response keys it
emits, which status codes it returns — lives only in convention, and the
repo's review history shows it drifting (legacy-body downgrades,
``_hold_ack`` vs ``_pause_ack`` mixups, swallowed status codes).

This module extracts both sides of that contract statically:

- **Route tables**: every ``web.get/post(...)`` / ``app.router.add_*``
  registration, with the handler resolved to its function (including the
  gateway's ``for path in FORWARDED_PATHS`` idiom).
- **Handler schemas**: per handler, the JSON body keys read
  (``d.get(...)`` / ``d["..."]`` — subscript-only keys are *required*),
  the response keys emitted by ``web.json_response`` dict literals
  (including the ``out = {...}; out["k"] = v`` build-up idiom), and the
  status codes returned (``status=`` kwargs + ``web.HTTPXxx`` raises).
  One-hop resolution follows the body dict into same-module helpers
  (``_req_from_json(d)``) and the response out of them.
- **Client call sites**: calls through recognizably transport-shaped
  callables (``_post_json*``, ``_get_json``, ``urlopen`` over an
  ``http://.../path`` f-string, ...) with a resolvable literal path,
  plus the dict-literal body they send and the variable their parsed
  response lands in.

Everything is *approximate by design*, tuned like the dataflow engine:
a body that escapes into unresolvable code marks the schema **open**
(reads/emits anything), a path that cannot be resolved to a literal is
simply not recorded — precision errors become missed findings, never
false alarms.

Consumers outside the calling function can opt in with a marker comment
on the def line (or the line above)::

    # arealint: wire-doc=/statusz
    def from_statusz(cls, addr, doc, ...):

which declares the first non-self/cls parameter a parsed response
document of that path, so its key reads check against the emitting
handlers fleet-wide.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from pathlib import Path
from typing import Iterable, Iterator

from areal_tpu.analysis.dataflow import (
    ModuleInfo,
    dotted_name,
    iter_package_sources,
)

# terminal callable names that look like an HTTP transport (the repo's
# client layer: _post_json/_post_json_failover/_post_all/_get_json/
# _send_json_once/urlopen/http_json/...)
TRANSPORT_RE = re.compile(
    r"(?:^|_)(?:a?post|get|put|send|fetch|scrape|urlopen|http_json)(?:$|_)",
    re.IGNORECASE,
)

# tokens that by themselves mark a callable as HTTP-shaped; names matching
# only the generic verbs above (get/put/send/fetch) also name filesystem
# and name-resolve helpers (get_subtree("/rollout/servers")) and need an
# http URL argument as corroboration
_STRONG_TRANSPORT_RE = re.compile(
    r"(?:^|_)(?:a?post|urlopen|http|json|scrape)(?:$|_)", re.IGNORECASE
)

_HTTP_VERBS = {"get", "post", "put", "delete", "patch", "head"}

# aiohttp's raise-able response classes -> status code
_HTTP_EXC_STATUS = {
    "HTTPBadRequest": 400,
    "HTTPUnauthorized": 401,
    "HTTPForbidden": 403,
    "HTTPNotFound": 404,
    "HTTPConflict": 409,
    "HTTPGone": 410,
    "HTTPRequestTimeout": 408,
    "HTTPTooManyRequests": 429,
    "HTTPInternalServerError": 500,
    "HTTPNotImplemented": 501,
    "HTTPServiceUnavailable": 503,
}

WIRE_DOC_RE = re.compile(r"arealint:\s*wire-doc=(\S+)(?:\s+(\w+))?")

# functions a raw request body may flow into without "escaping" the
# handler (still ends up as the parsed-json value we track)
_JSON_PARSERS = {"loads"}


@dataclasses.dataclass
class HandlerSchema:
    """One (path, handler) registration with its extracted contract."""

    path: str
    method: str  # "GET" / "POST" / ...
    relpath: str
    line: int
    qualname: str
    body_keys: set[str] = dataclasses.field(default_factory=set)
    body_required: set[str] = dataclasses.field(default_factory=set)
    body_open: bool = False
    resp_keys: set[str] = dataclasses.field(default_factory=set)
    resp_open: bool = False
    statuses: set[int] = dataclasses.field(default_factory=set)
    # a handler passing a non-literal ``status=`` may return ANY code
    statuses_open: bool = False


@dataclasses.dataclass
class WireContract:
    """The union contract over every server module analyzed."""

    handlers: dict[str, list[HandlerSchema]] = dataclasses.field(
        default_factory=dict
    )
    # relpath -> the ModuleInfo the contract was built from, retained so
    # per-file checkers reuse it instead of re-walking the AST
    modules: dict[str, ModuleInfo] = dataclasses.field(default_factory=dict)

    @property
    def has_routes(self) -> bool:
        return bool(self.handlers)

    def paths(self) -> set[str]:
        return set(self.handlers)

    def for_path(self, path: str) -> list[HandlerSchema]:
        return self.handlers.get(path, [])

    def body_reads(self, path: str) -> tuple[set[str], bool]:
        """(union of keys any handler reads, any-handler-open)."""
        keys: set[str] = set()
        open_ = False
        for h in self.for_path(path):
            keys |= h.body_keys
            open_ = open_ or h.body_open
        return keys, open_

    def body_required(self, path: str) -> set[str]:
        """Keys EVERY handler of the path requires (subscript access with
        no defaulted read anywhere) — the safe definition across servers
        that share a path."""
        hs = [h for h in self.for_path(path) if not h.body_open]
        if not hs or len(hs) != len(self.for_path(path)):
            return set()
        req = set(hs[0].body_required)
        for h in hs[1:]:
            req &= h.body_required
        return req

    def resp_emits(self, path: str) -> tuple[set[str], bool]:
        keys: set[str] = set()
        open_ = False
        for h in self.for_path(path):
            keys |= h.resp_keys
            open_ = open_ or h.resp_open
        return keys, open_

    def all_statuses(self) -> set[int] | None:
        """Every status code any handler returns, or None when some
        handler's ``status=`` is dynamic — the package may then return
        any code and dead-status checks must stay silent."""
        out = {200}
        for hs in self.handlers.values():
            for h in hs:
                if h.statuses_open:
                    return None
                out |= h.statuses
        return out


# ---------------------------------------------------------------------------
# registration discovery
# ---------------------------------------------------------------------------


def _module_const(mod: ModuleInfo, name: str) -> ast.expr | None:
    for node in mod.tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == name:
                    return node.value
    return None


def _paths_of(mod: ModuleInfo, expr: ast.expr, at: ast.AST) -> list[str]:
    """Resolve a route-path expression to literal path(s): a string
    constant, a module-level string constant, or a loop variable over a
    module-level tuple/list of strings (the gateway FORWARDED_PATHS
    idiom). Unresolvable -> [] (silent)."""
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return [expr.value]
    if not isinstance(expr, ast.Name):
        return []
    # loop variable over a module constant?
    cur = mod.parents.get(id(at))
    while cur is not None:
        if (
            isinstance(cur, (ast.For, ast.AsyncFor))
            and isinstance(cur.target, ast.Name)
            and cur.target.id == expr.id
            and isinstance(cur.iter, ast.Name)
        ):
            seq = _module_const(mod, cur.iter.id)
            if isinstance(seq, (ast.Tuple, ast.List)):
                return [
                    e.value
                    for e in seq.elts
                    if isinstance(e, ast.Constant) and isinstance(e.value, str)
                ]
            return []
        cur = mod.parents.get(id(cur))
    const = _module_const(mod, expr.id)
    if isinstance(const, ast.Constant) and isinstance(const.value, str):
        return [const.value]
    return []


def _handler_node(mod: ModuleInfo, expr: ast.expr, at: ast.AST):
    """Resolve a route-handler expression to its FunctionDef (qualname,
    node) — ``self.h_x`` methods and lexically-visible bare names."""
    if (
        isinstance(expr, ast.Attribute)
        and isinstance(expr.value, ast.Name)
        and expr.value.id == "self"
    ):
        cls = mod.enclosing_class(at)
        if cls:
            qual = mod.method_qual(cls, expr.attr)
            if qual:
                return qual, mod.funcs[qual].node
    if isinstance(expr, ast.Name):
        qual = mod._resolve_local(expr.id, at)
        if qual:
            return qual, mod.funcs[qual].node
    return None


def iter_registrations(
    mod: ModuleInfo,
) -> Iterator[tuple[str, str, str, ast.AST]]:
    """(path, METHOD, handler qualname, handler node) for every resolvable
    route registration in the module."""
    for call in ast.walk(mod.tree):
        if not isinstance(call, ast.Call) or len(call.args) < 2:
            continue
        verb: str | None = None
        f = call.func
        d = dotted_name(f)
        if d is not None and "." in d:
            head, _, tail = d.rpartition(".")
            if head.endswith("web") and tail in _HTTP_VERBS:
                verb = tail
        if verb is None and isinstance(f, ast.Attribute):
            if f.attr.startswith("add_") and f.attr[4:] in _HTTP_VERBS:
                verb = f.attr[4:]
        if verb is None:
            continue
        resolved = _handler_node(mod, call.args[1], call)
        if resolved is None:
            continue
        qual, node = resolved
        for path in _paths_of(mod, call.args[0], call):
            yield path, verb.upper(), qual, node


def is_registration(call: ast.Call) -> bool:
    """True for route-registration calls (they carry '/'-leading string
    args but are the server table, not client traffic)."""
    f = call.func
    d = dotted_name(f)
    if d is not None and "." in d:
        head, _, tail = d.rpartition(".")
        if head.endswith("web") and tail in _HTTP_VERBS | {"route"}:
            return True
    if isinstance(f, ast.Attribute) and f.attr.startswith("add_"):
        return True
    return False


# ---------------------------------------------------------------------------
# handler schema extraction
# ---------------------------------------------------------------------------


def _own_nodes(fn: ast.AST) -> Iterator[ast.AST]:
    """Walk a function's own statements, not descending into nested defs
    (except lambdas, whose bodies execute in this frame)."""
    stack = list(getattr(fn, "body", []))
    while stack:
        n = stack.pop()
        yield n
        if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            stack.extend(ast.iter_child_nodes(n))


def _is_json_source(node: ast.AST, request_names: set[str]) -> bool:
    """``await request.json()`` or ``json.loads(...)`` — the expressions
    that produce the parsed request body inside a handler."""
    if isinstance(node, ast.Await):
        node = node.value
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    if isinstance(f, ast.Attribute):
        if f.attr == "json" and isinstance(f.value, ast.Name):
            return f.value.id in request_names
        if f.attr in _JSON_PARSERS:
            return True
    return False


def _const_key(node: ast.expr) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


class _BodyReads:
    def __init__(self) -> None:
        self.keys: set[str] = set()
        self.subscript: set[str] = set()
        self.defaulted: set[str] = set()
        self.open = False

    @property
    def required(self) -> set[str]:
        return self.subscript - self.defaulted


def _scan_body_reads(
    mod: ModuleInfo,
    fn: ast.AST,
    var_names: set[str],
    source_pred,
    reads: _BodyReads,
    depth: int = 0,
) -> None:
    """Accumulate key reads of the body value bound to ``var_names`` (or
    produced inline by ``source_pred``) within ``fn``. Follows the value
    one hop into same-module callables; any other escape opens the
    schema."""

    def is_body(expr: ast.expr) -> bool:
        if isinstance(expr, ast.Name) and expr.id in var_names:
            return True
        return source_pred(expr)

    for node in _own_nodes(fn):
        if isinstance(node, ast.Call):
            f = node.func
            # d.get("k", default) / d.pop("k", default)
            if (
                isinstance(f, ast.Attribute)
                and f.attr in ("get", "pop")
                and is_body(f.value)
            ):
                k = _const_key(node.args[0]) if node.args else None
                if k is not None:
                    reads.keys.add(k)
                    reads.defaulted.add(k)
                continue
            # d.items()/keys()/values() -> wholesale use
            if (
                isinstance(f, ast.Attribute)
                and f.attr in ("items", "keys", "values", "update", "copy")
                and is_body(f.value)
            ):
                reads.open = True
                continue
            # body passed onward as an argument
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if not is_body(arg):
                    continue
                if dotted_name(f) in ("isinstance", "len", "bool", "repr", "str"):
                    continue
                if dotted_name(f) == "dict":
                    reads.open = True
                    continue
                absorbed = False
                if depth < 2:
                    target = None
                    if isinstance(f, ast.Name):
                        q = mod._resolve_local(f.id, node)
                        target = mod.funcs[q].node if q else None
                    elif (
                        isinstance(f, ast.Attribute)
                        and isinstance(f.value, ast.Name)
                        and f.value.id == "self"
                    ):
                        cls = mod.enclosing_class(node)
                        q = mod.method_qual(cls, f.attr) if cls else None
                        target = mod.funcs[q].node if q else None
                    if target is not None:
                        # map the argument onto the callee's parameter
                        idx = None
                        for i, a in enumerate(node.args):
                            if a is arg:
                                idx = i
                                break
                        params = [
                            a.arg
                            for a in target.args.args
                            if a.arg not in ("self", "cls")
                        ]
                        pname = None
                        if idx is not None and idx < len(params):
                            pname = params[idx]
                        else:
                            for kw in node.keywords:
                                if kw.value is arg and kw.arg:
                                    pname = kw.arg
                        if pname is not None:
                            _scan_body_reads(
                                mod,
                                target,
                                {pname},
                                lambda e: False,
                                reads,
                                depth + 1,
                            )
                            absorbed = True
                if not absorbed:
                    reads.open = True
        elif isinstance(node, ast.Subscript) and is_body(node.value):
            k = _const_key(node.slice)
            if k is not None:
                if isinstance(node.ctx, ast.Load):
                    reads.keys.add(k)
                    reads.subscript.add(k)
            else:
                reads.open = True  # dynamic key: anything may be read
        elif isinstance(node, (ast.For, ast.AsyncFor)) and is_body(node.iter):
            reads.open = True
        elif isinstance(node, ast.Return) and node.value is not None:
            if is_body(node.value):
                reads.open = True
        elif isinstance(node, ast.keyword) and node.arg is None:
            # **body splat into a call
            if is_body(node.value):
                reads.open = True
        elif isinstance(node, ast.Starred) and is_body(node.value):
            reads.open = True


def _dict_literal_keys(expr: ast.expr) -> tuple[set[str], bool] | None:
    """(keys, has_splat) for a dict literal (or an IfExp of two literals);
    None when the expression is not a literal dict."""
    if isinstance(expr, ast.IfExp):
        a = _dict_literal_keys(expr.body)
        b = _dict_literal_keys(expr.orelse)
        if a is None or b is None:
            return None
        return a[0] | b[0], a[1] or b[1]
    if not isinstance(expr, ast.Dict):
        return None
    keys: set[str] = set()
    splat = False
    for k in expr.keys:
        if k is None:
            splat = True
            continue
        ck = _const_key(k)
        if ck is None:
            splat = True
        else:
            keys.add(ck)
    return keys, splat


def _scan_responses(
    mod: ModuleInfo, fn: ast.AST, schema: HandlerSchema, depth: int = 0
) -> None:
    """Collect response keys and status codes emitted by a handler,
    following one hop into locally-resolvable helper returns."""
    # name -> (keys, open) built up from literal assignments + key stores
    # (source order matters: `out = {...}` must precede `out["k"] = v`)
    built: dict[str, tuple[set[str], bool]] = {}
    saw_response = False
    assigns = sorted(
        (n for n in _own_nodes(fn) if isinstance(n, ast.Assign)),
        key=lambda n: n.lineno,
    )
    for node in assigns:
        if len(node.targets) == 1:
            t = node.targets[0]
            if isinstance(t, ast.Name):
                lit = _dict_literal_keys(node.value)
                if lit is not None:
                    # UNION across rebinds, not last-literal-wins: the var
                    # may be returned between two bindings, so it "may
                    # emit" any of them — narrowing here would turn a real
                    # emit into a false WIRE003 on the consumer
                    keys, op = built.get(t.id, (set(), False))
                    built[t.id] = (keys | lit[0], op or lit[1])
                elif t.id in built:
                    # rebound to something unresolvable: keep the keys,
                    # mark the shape open
                    built[t.id] = (built[t.id][0], True)
            elif (
                isinstance(t, ast.Subscript)
                and isinstance(t.value, ast.Name)
                and t.value.id in built
            ):
                k = _const_key(t.slice)
                keys, op = built[t.value.id]
                if k is None:
                    built[t.value.id] = (keys, True)
                else:
                    keys.add(k)
    for node in _own_nodes(fn):
        if isinstance(node, ast.Call):
            d = dotted_name(node.func)
            tail = d.rpartition(".")[2] if d else (
                node.func.attr if isinstance(node.func, ast.Attribute) else ""
            )
            if tail == "json_response":
                saw_response = True
                status = 200
                for kw in node.keywords:
                    if kw.arg == "status":
                        if isinstance(kw.value, ast.Constant) and isinstance(
                            kw.value.value, int
                        ):
                            status = kw.value.value
                        else:
                            status = -1  # dynamic: may return any code
                if status > 0:
                    schema.statuses.add(status)
                else:
                    schema.statuses_open = True
                arg = node.args[0] if node.args else None
                lit = _dict_literal_keys(arg) if arg is not None else None
                if lit is not None:
                    schema.resp_keys |= lit[0]
                    if lit[1]:
                        schema.resp_open = True
                elif isinstance(arg, ast.Name) and arg.id in built:
                    keys, op = built[arg.id]
                    schema.resp_keys |= keys
                    if op:
                        schema.resp_open = True
                else:
                    schema.resp_open = True
            elif tail in ("Response", "StreamResponse", "FileResponse"):
                saw_response = True
                schema.resp_open = True
            elif d is not None:
                exc = d.rpartition(".")[2]
                if exc in _HTTP_EXC_STATUS:
                    schema.statuses.add(_HTTP_EXC_STATUS[exc])
        elif isinstance(node, ast.Return) and node.value is not None:
            # return await helper(...) -> absorb the helper's responses
            v = node.value
            if isinstance(v, ast.Await):
                v = v.value
            if isinstance(v, ast.Call) and depth < 2:
                target = None
                if isinstance(v.func, ast.Name):
                    q = mod._resolve_local(v.func.id, node)
                    target = mod.funcs[q].node if q else None
                elif (
                    isinstance(v.func, ast.Attribute)
                    and isinstance(v.func.value, ast.Name)
                    and v.func.value.id == "self"
                ):
                    cls = mod.enclosing_class(node)
                    q = mod.method_qual(cls, v.func.attr) if cls else None
                    target = mod.funcs[q].node if q else None
                if target is not None:
                    saw_response = True
                    _scan_responses(mod, target, schema, depth + 1)
    if not saw_response and depth == 0:
        schema.resp_open = True


def analyze_handler(
    mod: ModuleInfo, path: str, method: str, qual: str, node: ast.AST
) -> HandlerSchema:
    schema = HandlerSchema(
        path=path,
        method=method,
        relpath=mod.relpath,
        line=getattr(node, "lineno", 1),
        qualname=qual,
    )
    # request parameter: first non-self arg
    req_names = set()
    args = [a.arg for a in node.args.args if a.arg not in ("self", "cls")]
    if args:
        req_names.add(args[0])

    # body variables: names assigned a json source; raw-read vars feed
    # json.loads chains (handled by the source predicate)
    body_vars: set[str] = set()
    for n in _own_nodes(node):
        if isinstance(n, ast.Assign) and len(n.targets) == 1:
            t = n.targets[0]
            if isinstance(t, ast.Name) and _is_json_source(
                n.value, req_names
            ):
                body_vars.add(t.id)

    reads = _BodyReads()
    _scan_body_reads(
        mod,
        node,
        body_vars,
        lambda e: _is_json_source(e, req_names),
        reads,
    )
    # a raw body forwarded wholesale (gateway passthrough): request.read()
    # result used by anything but a json parser
    raw_vars: set[str] = set()
    for n in _own_nodes(node):
        if isinstance(n, ast.Assign) and len(n.targets) == 1:
            t = n.targets[0]
            v = n.value
            if isinstance(v, ast.Await):
                v = v.value
            if (
                isinstance(t, ast.Name)
                and isinstance(v, ast.Call)
                and isinstance(v.func, ast.Attribute)
                and v.func.attr == "read"
                and isinstance(v.func.value, ast.Name)
                and v.func.value.id in req_names
            ):
                raw_vars.add(t.id)
    if raw_vars:
        for n in _own_nodes(node):
            if not isinstance(n, ast.Call):
                continue
            f = n.func
            fname = (
                f.attr
                if isinstance(f, ast.Attribute)
                else (f.id if isinstance(f, ast.Name) else "")
            )
            if fname in _JSON_PARSERS or fname in ("len", "strip"):
                continue
            for arg in list(n.args) + [kw.value for kw in n.keywords]:
                base = arg
                # raw.strip() etc. still the raw body
                while isinstance(base, ast.Call) and isinstance(
                    base.func, ast.Attribute
                ):
                    base = base.func.value
                if isinstance(base, ast.Name) and base.id in raw_vars:
                    reads.open = True

    schema.body_keys = reads.keys
    schema.body_required = reads.required
    schema.body_open = reads.open
    _scan_responses(mod, node, schema)
    return schema


# ---------------------------------------------------------------------------
# client-side call extraction
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ClientCall:
    """One outbound HTTP call with a resolvable literal path."""

    node: ast.Call
    path: str
    body_keys: set[str] | None  # None = unknown / non-dict body
    body_splat: bool
    resp_var: str | None  # name the parsed response is bound to


def _path_from_fstring(js: ast.JoinedStr) -> str | None:
    """Extract "/path" from f"http://{addr}/path..." — the constant
    fragment that follows the host FormattedValue."""
    vals = list(js.values)
    if not vals:
        return None
    head = vals[0]
    # f"/path?{q}" — the path IS the leading constant
    if isinstance(head, ast.Constant) and isinstance(head.value, str):
        if head.value.startswith("/"):
            return head.value.split("?")[0]
        if not head.value.startswith("http"):
            return None
    else:
        # f"{backend}/path" — host expression first, then the path
        for v in vals[1:]:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                if v.value.startswith("/"):
                    return v.value.split("?")[0]
        return None
    for v in vals[1:]:
        if isinstance(v, ast.Constant) and isinstance(v.value, str):
            if v.value.startswith("/"):
                return v.value.split("?")[0]
    return None


def transport_callee_name(call: ast.Call) -> str | None:
    f = call.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


def is_transport_call(call: ast.Call) -> bool:
    if is_registration(call):
        return False
    name = transport_callee_name(call)
    if name is None:
        return False
    if name.lower() in ("get", "put", "pop", "post"):
        # dict-like method names double as HTTP verbs: only a first-arg
        # literal path / http url makes them a transport
        # (``os.environ.get("KEY", "/tmp/default")`` is not a request)
        if not call.args:
            return False
        a0 = call.args[0]
        if isinstance(a0, ast.Constant) and isinstance(a0.value, str):
            return a0.value.startswith("/")
        if isinstance(a0, ast.JoinedStr):
            return _path_from_fstring(a0) is not None
        return False
    if TRANSPORT_RE.search(name):
        if _STRONG_TRANSPORT_RE.search(name):
            return True
        # weak verb (get/put/send/fetch): only an absolute http(s) URL
        # argument marks it as a transport
        return any(
            (
                isinstance(a, ast.Constant)
                and isinstance(a.value, str)
                and a.value.startswith(("http://", "https://"))
            )
            or (
                isinstance(a, ast.JoinedStr)
                and a.values
                and isinstance(a.values[0], ast.Constant)
                and str(a.values[0].value).startswith("http")
            )
            for a in list(call.args) + [kw.value for kw in call.keywords]
        )
    # pool.submit(self._post_json_one, addr, "/path", payload) /
    # loop.run_in_executor(None, self._post_bytes, ...): the transport
    # callable rides as an argument
    if name in ("submit", "run_in_executor", "map"):
        for arg in call.args:
            d = dotted_name(arg)
            tail = d.rpartition(".")[2] if d else None
            if tail and TRANSPORT_RE.search(tail):
                return True
    return False


def call_path(call: ast.Call) -> str | None:
    """The literal request path of a transport call, or None."""
    for arg in list(call.args) + [kw.value for kw in call.keywords]:
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            if arg.value.startswith("/"):
                return arg.value.split("?")[0]
        if isinstance(arg, ast.JoinedStr):
            p = _path_from_fstring(arg)
            if p is not None:
                return p
    return None


def iter_client_calls(fn: ast.AST) -> Iterator[ClientCall]:
    """Client calls in one function: transport-shaped callables with a
    literal path, the dict-literal body they carry, and the variable
    their parsed response binds to."""
    # name -> dict-literal bindings in source order; a CALL resolves its
    # body var to the latest binding AT OR BEFORE its own line (a global
    # last-binding-wins map mis-attributed an earlier call's body to a
    # later rebind — false WIRE002 on contract-faithful clients)
    dict_bindings: dict[str, list[tuple[int, set[str], bool]]] = {}
    assigns: list[ast.Assign] = [
        n for n in _own_nodes(fn) if isinstance(n, ast.Assign)
    ]
    for n in sorted(assigns, key=lambda a: a.lineno):
        if len(n.targets) == 1 and isinstance(n.targets[0], ast.Name):
            lit = _dict_literal_keys(n.value)
            if lit is not None:
                dict_bindings.setdefault(n.targets[0].id, []).append(
                    (n.lineno, lit[0], lit[1])
                )

    def dict_var_at(name: str, lineno: int) -> tuple[set[str], bool] | None:
        best = None
        for ln, keys, splat in dict_bindings.get(name, ()):
            if ln <= lineno:
                best = (keys, splat)
        return best

    # urlopen context vars: with urlopen(f"http://../p") as r -> r : path.
    # A var reused for DIFFERENT paths is dropped: reads of it cannot be
    # attributed to one path without false WIRE003s.
    resp_objs: dict[str, str | None] = {}
    for n in _own_nodes(fn):
        if isinstance(n, (ast.With, ast.AsyncWith)):
            for item in n.items:
                cexpr = item.context_expr
                if (
                    isinstance(cexpr, ast.Call)
                    and is_transport_call(cexpr)
                    and item.optional_vars is not None
                    and isinstance(item.optional_vars, ast.Name)
                ):
                    p = call_path(cexpr)
                    if p is not None:
                        var = item.optional_vars.id
                        if resp_objs.get(var, p) != p:
                            resp_objs[var] = None
                        else:
                            resp_objs[var] = p

    # one parent map per function, shared by every resp_binding lookup
    parent_map: dict[int, ast.AST] = {}
    for n in ast.walk(fn):
        for c in ast.iter_child_nodes(n):
            parent_map[id(c)] = n

    def resp_binding(call: ast.Call) -> str | None:
        """The name this call's (awaited) result is assigned to —
        last element for tuple targets ((addr, data) unpack)."""
        cur: ast.AST | None = parent_map.get(id(call))
        while isinstance(cur, (ast.Await,)):
            cur = parent_map.get(id(cur))
        if isinstance(cur, ast.Assign) and len(cur.targets) == 1:
            t = cur.targets[0]
            if isinstance(t, ast.Name):
                return t.id
            if isinstance(t, (ast.Tuple, ast.List)) and t.elts:
                last = t.elts[-1]
                if isinstance(last, ast.Name):
                    return last.id
        return None

    out: list[ClientCall] = []
    for n in _own_nodes(fn):
        if not isinstance(n, ast.Call) or not is_transport_call(n):
            continue
        path = call_path(n)
        if path is None:
            continue
        body_keys: set[str] | None = None
        splat = False
        for arg in list(n.args) + [
            kw.value
            for kw in n.keywords
            if kw.arg in (None, "json", "payload", "data", "body")
        ]:
            lit = _dict_literal_keys(arg)
            if lit is None and isinstance(arg, ast.Name):
                lit = dict_var_at(arg.id, n.lineno)
            if lit is not None:
                body_keys, splat = set(lit[0]), lit[1]
                break
        out.append(
            ClientCall(
                node=n,
                path=path,
                body_keys=body_keys,
                body_splat=splat,
                resp_var=resp_binding(n),
            )
        )

    # parsed-response bindings over a tracked response object:
    #   with urlopen(f".../p") as r: d = json.loads(r.read() or b"{}")
    #   async with sess.post(f".../p") as r: d = await r.json()
    for n in _own_nodes(fn):
        if not (
            isinstance(n, ast.Assign)
            and len(n.targets) == 1
            and isinstance(n.targets[0], ast.Name)
        ):
            continue
        for call in ast.walk(n.value):
            if not (isinstance(call, ast.Call) and isinstance(call.func, ast.Attribute)):
                continue
            if call.func.attr in _JSON_PARSERS:
                for sub in ast.walk(call):
                    if (
                        isinstance(sub, ast.Attribute)
                        and sub.attr == "read"
                        and isinstance(sub.value, ast.Name)
                        and resp_objs.get(sub.value.id) is not None
                    ):
                        out.append(
                            ClientCall(
                                node=call,
                                path=resp_objs[sub.value.id],
                                body_keys=None,
                                body_splat=False,
                                resp_var=n.targets[0].id,
                            )
                        )
            elif (
                call.func.attr == "json"
                and isinstance(call.func.value, ast.Name)
                and resp_objs.get(call.func.value.id) is not None
            ):
                out.append(
                    ClientCall(
                        node=call,
                        path=resp_objs[call.func.value.id],
                        body_keys=None,
                        body_splat=False,
                        resp_var=n.targets[0].id,
                    )
                )

    # a response var bound by calls to DIFFERENT paths is untrackable:
    # its reads would be checked against every path (false WIRE003);
    # applies to BOTH binding mechanisms (assign and context-manager)
    var_paths: dict[str, set[str]] = {}
    for c in out:
        if c.resp_var is not None:
            var_paths.setdefault(c.resp_var, set()).add(c.path)
    for c in out:
        if c.resp_var is not None and len(var_paths[c.resp_var]) > 1:
            c.resp_var = None
    yield from out


# ---------------------------------------------------------------------------
# contract construction
# ---------------------------------------------------------------------------


def build_contract_from_modules(
    mods: Iterable[ModuleInfo],
) -> WireContract:
    contract = WireContract()
    for mod in mods:
        contract.modules[mod.relpath] = mod
        for path, method, qual, node in iter_registrations(mod):
            schema = analyze_handler(mod, path, method, qual, node)
            contract.handlers.setdefault(path, []).append(schema)
    return contract


def build_contract(
    sources: Iterable[tuple[str, str, ast.Module]],
) -> WireContract:
    return build_contract_from_modules(
        ModuleInfo(relpath, text, tree) for relpath, text, tree in sources
    )


def build_package_contract(
    package_root: Path,
    modules: Iterable[ModuleInfo] | None = None,
) -> WireContract:
    """Package-wide contract; pass the call graph's already-parsed
    ``modules`` to skip the second read+parse of every package file."""
    if modules is not None:
        return build_contract_from_modules(modules)
    return build_contract(iter_package_sources(package_root))
