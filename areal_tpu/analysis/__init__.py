"""arealint — AST-based static analysis for areal_tpu invariants.

The async-RL stack is a concurrency machine whose worst bugs never crash:
a blocking call starving the rollout event loop, a side effect captured
inside a ``jax.jit`` trace, a config field drifting from its dataclass, a
metric name drifting from the catalog. These corrupt throughput or
training signal silently. This package makes those invariants
machine-checked: a rule engine (`core`), eleven rule families
(`rules/`), and a burn-down baseline (`baseline.json`) so the gate is
zero-new-findings from day one.

Since v2 the engine is dataflow-aware (`dataflow`): a package-wide call
graph with hot-path reachability (seeded from the decode loop, the
trainer step loops, jit-traced callables, and ``# arealint: hot-path``
markers) plus device/host value-origin tracking. The performance
families — PRF (hot-path host<->device syncs), DON (jit buffer
donation), SHD (PartitionSpec/mesh consistency), RCP (recompile risk) —
consume it to enforce statically what the goodput observatory measures
at runtime (docs/static_analysis.md, docs/perf.md).

Entry points:
  - CLI: ``python -m areal_tpu.tools.arealint [paths]`` (``--changed-only``
    for git-diff-scoped runs, ``--format sarif`` for CI annotation)
  - API: :func:`run_analysis`
"""

from areal_tpu.analysis.core import (
    Analyzer,
    AnalysisResult,
    Finding,
    ProjectContext,
    SourceFile,
    default_baseline_path,
    default_package_root,
    run_analysis,
)

__all__ = [
    "Analyzer",
    "AnalysisResult",
    "Finding",
    "ProjectContext",
    "SourceFile",
    "default_baseline_path",
    "default_package_root",
    "run_analysis",
]
