"""arealint — AST-based static analysis for areal_tpu invariants.

The async-RL stack is a concurrency machine whose worst bugs never crash:
a blocking call starving the rollout event loop, a side effect captured
inside a ``jax.jit`` trace, a config field drifting from its dataclass, a
metric name drifting from the catalog. These corrupt throughput or
training signal silently. This package makes those invariants
machine-checked: a rule engine (`core`), five rule families (`rules/`),
and a burn-down baseline (`baseline.json`) so the gate is
zero-new-findings from day one.

Entry points:
  - CLI: ``python -m areal_tpu.tools.arealint [paths]``
  - API: :func:`run_analysis`
"""

from areal_tpu.analysis.core import (
    Analyzer,
    AnalysisResult,
    Finding,
    ProjectContext,
    SourceFile,
    default_baseline_path,
    default_package_root,
    run_analysis,
)

__all__ = [
    "Analyzer",
    "AnalysisResult",
    "Finding",
    "ProjectContext",
    "SourceFile",
    "default_baseline_path",
    "default_package_root",
    "run_analysis",
]
