from areal_tpu.parallel.mesh import (  # noqa: F401
    MESH_AXES,
    BATCH_AXES,
    make_mesh,
    mesh_from_parallel_strategy,
    batch_sharding,
    replicated,
)
