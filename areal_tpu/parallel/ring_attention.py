"""Ring attention over the mesh "seq" axis (context parallelism).

The reference delegates ring/context attention to TransformerEngine inside
Megatron (megatron_utils/packed_context_parallel.py:9-173); here it is a
first-class shard_map kernel: K/V shards rotate around the ring via
``ppermute`` while each device folds one block per step into a flash-style
running softmax (fp32 max/sum carries). Causal + packed-segment masking uses
explicit global column indices, so any sequence layout works — including the
reference's 2-chunks-per-rank causal load balancing (``zigzag_indices``).

Complements Ulysses (models/qwen.py head<->seq all-to-all): Ulysses is
cheaper up to num_heads ways; ring scales context beyond head count with
O(L/sp) memory per device.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P
from areal_tpu.utils.jax_compat import axis_size, get_abstract_mesh, shard_map


def _block_attn(q, k, v, seg_q, seg_k, idx_q, idx_k, scale):
    """One q-shard × kv-block flash update ingredients.

    q: [B, Lq, H, d]; k/v: [B, Lk, H, d]. Returns (logits-masked, mask).
    """
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    mask = (
        (seg_q[:, :, None] == seg_k[:, None, :])
        & (seg_q[:, :, None] != 0)
        & (idx_q[:, :, None] >= idx_k[:, None, :])
    )[:, None]  # [B, 1, Lq, Lk]
    return jnp.where(mask, logits, -jnp.inf)


def _ring_shard_fn(q, k, v, seg, idx, axis_name: str, scale: float, vary_axes=()):
    """Per-device body under shard_map. All inputs are local shards:
    q/k/v [B, Lc, H, d], seg/idx [B, Lc]."""
    sp = axis_size(axis_name)
    B, Lc, H, d = q.shape

    def step(i, carry):
        o, m, l, k_cur, v_cur, seg_cur, idx_cur = carry
        logits = _block_attn(q, k_cur, v_cur, seg, seg_cur, idx, idx_cur, scale)
        m_blk = jnp.max(logits, axis=-1)  # [B, H, Lq]
        m_new = jnp.maximum(m, m_blk)
        # guard fully-masked rows (exp(-inf - -inf))
        m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
        p = jnp.exp(logits - m_safe[..., None])  # [B, H, Lq, Lk]
        corr = jnp.where(jnp.isneginf(m), 0.0, jnp.exp(m - m_safe))
        l_new = l * corr + p.sum(axis=-1)
        o_new = o * corr[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, v_cur.astype(jnp.float32)
        )
        perm = [(j, (j - 1) % sp) for j in range(sp)]
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        seg_nxt = jax.lax.ppermute(seg_cur, axis_name, perm)
        idx_nxt = jax.lax.ppermute(idx_cur, axis_name, perm)
        return (o_new, m_new, l_new, k_nxt, v_nxt, seg_nxt, idx_nxt)

    # initial accumulators must carry the same varying-manual-axes type as
    # the loop outputs (which depend on mesh-varying q/k/v)
    axes = tuple(vary_axes) or (axis_name,)
    if hasattr(jax.lax, "pcast"):
        _vary = lambda x: jax.lax.pcast(x, axes, to="varying")  # noqa: E731
    elif hasattr(jax.lax, "pvary"):
        _vary = lambda x: jax.lax.pvary(x, axes)  # noqa: E731
    else:  # pre-varying-types jax: no manual-axes type system to satisfy
        _vary = lambda x: x  # noqa: E731
    o0 = _vary(jnp.zeros((B, H, Lc, d), jnp.float32))
    m0 = _vary(jnp.full((B, H, Lc), -jnp.inf, jnp.float32))
    l0 = _vary(jnp.zeros((B, H, Lc), jnp.float32))
    o, m, l, *_ = jax.lax.fori_loop(0, sp, step, (o0, m0, l0, k, v, seg, idx))
    o = o / jnp.maximum(l, 1e-30)[..., None]
    return jnp.transpose(o, (0, 2, 1, 3)).astype(q.dtype)  # [B, Lq, H, d]


def ring_attention(
    q: jax.Array,  # [B, L, H, d] (sharded over mesh "seq" on L)
    k: jax.Array,
    v: jax.Array,
    segment_ids: jax.Array,  # [B, L] (0 = padding)
    col_index: jax.Array,  # [B, L] global row-column index (causality)
    mesh=None,
    axis_name: str = "seq",
    batch_axes=("data", "fsdp"),
) -> jax.Array:
    """Context-parallel causal attention for packed grids. Call inside jit
    with a mesh context; outside a mesh it falls back to single-device."""
    mesh = mesh or get_abstract_mesh()
    if mesh is None or axis_name not in mesh.shape or mesh.shape[axis_name] == 1:
        scale = q.shape[-1] ** -0.5
        logits = _block_attn(q, k, v, segment_ids, segment_ids, col_index, col_index, scale)
        m = jnp.max(logits, axis=-1, keepdims=True)
        m = jnp.where(jnp.isneginf(m), 0.0, m)
        p = jnp.exp(logits - m)
        o = jnp.einsum("bhqk,bkhd->bhqd", p, v.astype(jnp.float32))
        o = o / jnp.maximum(p.sum(-1), 1e-30)[..., None]
        return jnp.transpose(o, (0, 2, 1, 3)).astype(q.dtype)

    scale = q.shape[-1] ** -0.5
    import math

    bdeg = math.prod(mesh.shape[a] for a in batch_axes if a in mesh.shape)
    batch_spec = batch_axes if bdeg > 1 and q.shape[0] % bdeg == 0 else None
    spec_qkv = P(batch_spec, axis_name, None, None)
    spec_tok = P(batch_spec, axis_name)
    vary_axes = (axis_name,) + (tuple(batch_axes) if batch_spec else ())
    fn = shard_map(
        partial(
            _ring_shard_fn, axis_name=axis_name, scale=scale, vary_axes=vary_axes
        ),
        mesh=mesh,
        in_specs=(spec_qkv, spec_qkv, spec_qkv, spec_tok, spec_tok),
        out_specs=spec_qkv,
    )
    return fn(q, k, v, segment_ids, col_index)


def zigzag_indices(L: int, sp: int) -> np.ndarray:
    """Causal load-balanced layout (reference packed_context_parallel.py:9-60):
    split [0, L) into 2·sp chunks; device r gets chunks (r, 2sp−1−r). Returns
    the permutation ``perm`` such that ``x[..., perm, :]`` lays tokens out in
    device order; invert with ``np.argsort(perm)``."""
    assert L % (2 * sp) == 0, (L, sp)
    c = L // (2 * sp)
    chunks = [np.arange(i * c, (i + 1) * c) for i in range(2 * sp)]
    order = []
    for r in range(sp):
        order.append(chunks[r])
        order.append(chunks[2 * sp - 1 - r])
    return np.concatenate(order)
