"""GPipe pipeline parallelism over a mesh axis (SURVEY §2.4 PP row).

The reference implements PP twice (Megatron 1F1B/VPP schedules,
megatron_engine.py:561-637; Archon torch.distributed.pipelining incl.
ZBV/DualPipeV, archon_engine.py:16-19). On TPU, GSPMD sharding covers the
reference's PP use cases *within* a pod — the train engine deliberately
scales via (data, fsdp, seq, model, expert) sharding rules instead
(SURVEY §7.1: "XLA SPMD rarely needs PP on TPU"). This module provides
the mechanism itself for the cases where stage partitioning IS wanted
(DCN-connected pod slices; models whose layer count dwarfs HBM): a
functional GPipe fill–drain schedule whose backward comes from jax.grad
differentiating through the collectives — no hand-written schedule code
for the bwd pass, XLA overlaps the ppermute with stage compute.

Design (the scaling-book "pipelining" recipe, restated TPU-first):
- layers live STACKED as [n_layers, ...] leaves (the repo-wide layout);
  stage s owns the contiguous slice [s*L/S, (s+1)*L/S) — resharding from
  the GSPMD layout is one device_put of a differently-sharded array.
- inside shard_map over the ``stage`` axis, every device runs the same
  fill–drain loop of length n_micro + S - 1: apply my stage's layers to
  my current microbatch, then ``ppermute`` activations to the next stage
  while rotating in the next microbatch.
- the [n_microbatches, ...] input buffer is REPLICATED on every stage
  (only stage 0 reads it) and the output accumulator likewise lives on
  every stage (only the last writes it; a final masked psum broadcasts
  it), so callers see an ordinary [M, ...] -> [M, ...] function. Memory
  per stage is therefore two full [M, ...] activation buffers — the
  simple/robust choice at RL-activation sizes; a stage-0-resident
  variant (rotating buffers) is the optimization for activation-bound
  regimes.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def gpipe(
    layer_fn: Callable,  # (carry, layer_params) -> carry, applied per layer
    n_stages: int,
    n_microbatches: int,
    axis_name: str = "model",
):
    """Build a GPipe-pipelined apply: ``fn(stage_params, x_micro) -> y``.

    ``stage_params``: pytree whose leaves are [layers_per_stage, ...] — the
    CURRENT stage's slice (callers shard a stacked [n_layers, ...] tree
    over the pp axis; inside shard_map each device sees its slice).
    ``x_micro``: [n_microbatches, ...] microbatched activations, all
    resident on every stage (replicated entry; only stage 0's are read).

    Returns y of the same shape: microbatch m's output after all layers.
    Must be called INSIDE shard_map with ``axis_name`` mapped; the stacked
    layer count must divide evenly over the stages (shard_map's P("stage")
    split enforces the same — asserted eagerly by the caller's in_specs).
    """

    def apply_stage(params, x):
        def body(carry, layer):
            return layer_fn(carry, layer), None

        y, _ = jax.lax.scan(body, x, params)
        return y

    def fn(stage_params, x_micro):
        """``x_micro`` may be a single [M, ...] array or a PYTREE of them
        (the engine's PP path flows (x, segment_ids, positions) together so
        every stage can rebuild its attention mask)."""
        stage = jax.lax.axis_index(axis_name)
        M = n_microbatches
        S = n_stages
        n_steps = M + S - 1
        fwd_perm = [(i, (i + 1) % S) for i in range(S)]
        tmap = jax.tree.map

        # state: the activation currently flowing through THIS stage, plus
        # the output accumulator (written by the last stage)
        cur = tmap(lambda a: jnp.zeros_like(a[0]), x_micro)
        out = tmap(jnp.zeros_like, x_micro)

        def step(t, carry):
            cur, out = carry
            # stage 0 injects microbatch t (while t < M), others take the
            # activation handed to them last step
            inject = tmap(
                lambda a: jax.lax.dynamic_index_in_dim(
                    a, jnp.minimum(t, M - 1), 0, keepdims=False
                ),
                x_micro,
            )
            cur = tmap(lambda i, c: jnp.where(stage == 0, i, c), inject, cur)
            cur = apply_stage(stage_params, cur)
            # the LAST stage retires microbatch t-(S-1) (valid once t >= S-1)
            m_idx = t - (S - 1)
            write = jnp.logical_and(stage == S - 1, m_idx >= 0)
            out = jax.lax.cond(
                write,
                lambda o: tmap(
                    lambda o_leaf, c_leaf: jax.lax.dynamic_update_index_in_dim(
                        o_leaf, c_leaf, jnp.maximum(m_idx, 0), 0
                    ),
                    o,
                    cur,
                ),
                lambda o: o,
                out,
            )
            # hand my activation to the next stage
            cur = jax.lax.ppermute(cur, axis_name, fwd_perm)
            return cur, out

        _, out = jax.lax.fori_loop(0, n_steps, step, (cur, out))
        # every stage ends with the LAST stage's accumulator only on that
        # device; psum-broadcast so callers see it replicated (cheap at
        # [M, ...] activation size; callers usually reduce immediately)
        out = jax.lax.psum(
            tmap(lambda o: jnp.where(stage == S - 1, o, jnp.zeros_like(o)), out),
            axis_name,
        )
        return out

    return fn
