"""Device mesh construction + sharding helpers.

One GSPMD mesh replaces the reference's three per-backend parallel-dims
systems (FSDP DeviceMesh areal/engine/fsdp_utils/parallel.py:34-214, Megatron
mpu, Archon ParallelDims areal/experimental/models/archon/parallel_dims.py):

    axes = (data, fsdp, seq, model, expert)

- ``data``×``fsdp``: batch rows (DP); params ZeRO-3-shard over ``fsdp``
  (set fsdp=world, data=1 for pure FSDP; data>1 gives HSDP-style replication)
- ``seq``: sequence/context parallelism (Ulysses all-to-all inserted by XLA
  between seq- and head-sharded regions; ring attention via Pallas kernel)
- ``model``: tensor parallelism (TP all-reduces inserted by XLA)
- ``expert``: MoE expert parallelism

Collectives ride ICI within a pod; multi-host extends the same mesh over DCN
via jax.distributed (axis order puts ``model``/``seq`` innermost so their
collectives stay on ICI).
"""

from __future__ import annotations

import math

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from areal_tpu.api.alloc_mode import ParallelStrategy
from areal_tpu.api.config import MeshConfig

MESH_AXES = ("data", "fsdp", "seq", "model", "expert", "pipe")
BATCH_AXES = ("data", "fsdp")


def make_mesh(cfg: MeshConfig | None = None, devices=None) -> Mesh:
    """Build the 5-axis mesh. ``data == -1`` absorbs all remaining devices."""
    cfg = cfg or MeshConfig()
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    sizes = dict(
        data=cfg.data,
        fsdp=cfg.fsdp,
        seq=cfg.seq,
        model=cfg.model,
        expert=cfg.expert,
        pipe=getattr(cfg, "pipe", 1),
    )
    fixed = math.prod(v for v in sizes.values() if v != -1)
    wildcard = [k for k, v in sizes.items() if v == -1]
    if wildcard:
        assert len(wildcard) == 1, "at most one mesh axis may be -1"
        assert n % fixed == 0, (n, sizes)
        sizes[wildcard[0]] = n // fixed
    total = math.prod(sizes.values())
    assert total == n, f"mesh {sizes} needs {total} devices, have {n}"
    shape = tuple(sizes[a] for a in MESH_AXES)
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, MESH_AXES)


def mesh_from_parallel_strategy(ps: ParallelStrategy, devices=None) -> Mesh:
    """AllocationMode DSL strategy -> mesh: dp→fsdp (ZeRO sharding is the
    TPU default for DP), tp→model, cp→seq, ep→expert. pp is asserted 1 —
    GSPMD covers TPU pipelining needs (SURVEY §2.4 PP row)."""
    assert ps.pp == 1, "pipeline parallelism: use GSPMD stage sharding (pp must be 1)"
    cfg = MeshConfig(data=1, fsdp=ps.dp, seq=ps.cp, model=ps.tp, expert=ps.ep)
    return make_mesh(cfg, devices)


def batch_sharding(mesh: Mesh, extra: tuple = ()) -> NamedSharding:
    """Sharding for [G, L, ...] microbatch grids: rows over data×fsdp."""
    return NamedSharding(mesh, P(BATCH_AXES, *extra))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def dp_size(mesh: Mesh) -> int:
    return mesh.shape["data"] * mesh.shape["fsdp"]


def param_sharding(mesh: Mesh, spec_tree):
    """PartitionSpec tree -> NamedSharding tree."""
    return jax.tree.map(
        lambda spec: NamedSharding(mesh, spec),
        spec_tree,
        is_leaf=lambda x: isinstance(x, P),
    )


def shard_for_path(shardings: dict, path: str):
    """Walk a PartitionSpec/NamedSharding tree by a flat "a/b/c" param path
    (works for the stacked-layer text tree AND the nested vision tree)."""
    node = shardings
    for seg in path.split("/"):
        node = node[seg]
    return node
