"""Background asyncio loop on a dedicated thread with thread-safe queues.

Behavioral parity with reference areal/infra/async_task_runner.py:66-680
(minus uvloop, which is not in this image — stdlib asyncio). Producers submit
coroutine factories from any thread; results come back through an output
queue as TimedResult. Task exceptions are captured and re-raised on the
caller thread (fail-fast, reference workflow_executor.py:305-317).
"""

from __future__ import annotations

import asyncio
import queue
import threading
import time
import uuid
from typing import Any, Awaitable, Callable

from areal_tpu.api.io_struct import TimedResult
from areal_tpu.utils import logging as alog

logger = alog.getLogger("async_task_runner")


class TaskFailed(RuntimeError):
    def __init__(self, task_id: str, exc: BaseException):
        super().__init__(f"task {task_id} failed: {exc!r}")
        self.task_id = task_id
        self.exc = exc


class AsyncTaskRunner:
    def __init__(self, max_concurrency: int | None = None):
        self._loop: asyncio.AbstractEventLoop | None = None
        self._thread: threading.Thread | None = None
        self._started = threading.Event()
        self._shutdown = threading.Event()
        self._out: queue.Queue[TimedResult | TaskFailed] = queue.Queue()
        self._n_pending = 0
        self._lock = threading.Lock()
        # completion signal for wait_all (shares _lock with _n_pending)
        self._pending_cv = threading.Condition(self._lock)
        self._sem: asyncio.Semaphore | None = None
        self._max_concurrency = max_concurrency
        self._paused: asyncio.Event | None = None

    # -- lifecycle --------------------------------------------------------
    def start(self) -> None:
        assert self._thread is None
        # loop and primitives are created HERE, before the thread exists:
        # creating them inside the thread raced every reader that checked
        # `self._loop is not None` during startup (arealint THR001).
        # asyncio.Event/Semaphore bind to the running loop on first await,
        # so off-thread construction is safe on Python 3.10+.
        self._loop = asyncio.new_event_loop()
        if self._max_concurrency:
            self._sem = asyncio.Semaphore(self._max_concurrency)
        self._paused = asyncio.Event()
        self._paused.set()  # set = running

        def run():
            asyncio.set_event_loop(self._loop)
            self._started.set()
            self._loop.run_forever()

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()
        if not self._started.wait(10):
            raise TimeoutError("async task runner failed to start")

    def stop(self) -> None:
        if self._loop is not None:
            self._loop.call_soon_threadsafe(self._loop.stop)
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    @property
    def alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    # -- pause/resume -----------------------------------------------------
    def pause(self) -> None:
        if self._loop:
            self._loop.call_soon_threadsafe(self._paused.clear)

    def resume(self) -> None:
        if self._loop:
            self._loop.call_soon_threadsafe(self._paused.set)

    # -- submission -------------------------------------------------------
    def submit(
        self,
        coro_fn: Callable[[], Awaitable[Any]],
        task_id: str | None = None,
    ) -> str:
        """Schedule a coroutine; its result lands in the output queue."""
        assert self._loop is not None, "runner not started"
        task_id = task_id or uuid.uuid4().hex

        async def wrapper():
            try:
                await self._paused.wait()
                if self._sem is not None:
                    async with self._sem:
                        result = await coro_fn()
                else:
                    result = await coro_fn()
                self._out.put(TimedResult(data=result, task_id=task_id))
            except Exception as e:  # noqa: BLE001
                logger.exception(f"task {task_id} failed")
                self._out.put(TaskFailed(task_id, e))
            finally:
                # arealint: disable-next=ASY003 microsecond counter update, never held across an await; wait_all waits on a threading primitive so the notify must be one too
                with self._pending_cv:
                    self._n_pending -= 1
                    self._pending_cv.notify_all()

        with self._lock:
            self._n_pending += 1
        asyncio.run_coroutine_threadsafe(wrapper(), self._loop)
        return task_id

    @property
    def n_pending(self) -> int:
        with self._lock:
            return self._n_pending

    # -- results ----------------------------------------------------------
    def poll_result(self, timeout: float | None = None) -> TimedResult | None:
        """Next completed task (raises TaskFailed for failed tasks)."""
        try:
            item = self._out.get(timeout=timeout) if timeout else self._out.get_nowait()
        except queue.Empty:
            return None
        if isinstance(item, TaskFailed):
            raise item
        return item

    def drain(self) -> list[TimedResult]:
        out = []
        while True:
            try:
                item = self._out.get_nowait()
            except queue.Empty:
                return out
            if isinstance(item, TaskFailed):
                raise item
            out.append(item)

    def wait_all(self, timeout: float = 60.0) -> None:
        """Block until every submitted task completed. Event-driven: wakes
        on each task completion instead of polling (was a 5 ms sleep loop)."""
        deadline = time.monotonic() + timeout
        with self._pending_cv:
            while self._n_pending > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"{self._n_pending} tasks still pending"
                    )
                self._pending_cv.wait(remaining)
